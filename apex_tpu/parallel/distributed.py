"""Data-parallel training utilities (reference: apex/parallel/distributed.py).

The reference DDP registers per-parameter backward hooks, buckets grads,
and overlaps NCCL all-reduce with the rest of backward (SURVEY.md §3.4).
Under SPMD on TPU that whole mechanism disappears: the train step runs
inside shard_map/pjit over the "data" mesh axis, gradients are reduced by
ONE psum that XLA schedules and overlaps itself.  This module keeps the
reference's API shape on top of that reality:

  - ``DistributedDataParallel`` wraps an apply_fn; its
    ``reduce_gradients`` is the explicit psum/pmean (for shard_map-style
    steps).  Bucketing knobs (message_size, delay_allreduce,
    allreduce_trigger_params) are accepted and ignored — XLA's collective
    scheduler owns that decision.
  - ``flat_dist_call`` / ``broadcast_params`` mirror the ctor broadcast.
  - ``Reducer`` is the raw-reduction facade.

Bucket-granular path (flat AMP pipeline): hand ``Reducer`` or
``DistributedDataParallel`` a :class:`BucketPlan` (or a bucketed fused
optimizer) and reduction runs over the plan's flat buckets —
``all_reduce_flat_buffers`` issues ONE psum per dtype bucket instead of
one per leaf, and packed buffer lists stay packed through the
collective so the fused unscale/norm kernel consumes the reduced
buckets directly (amp/flat_pipeline.py wires the whole chain).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.telemetry import _tape

Pytree = Any


def _emit_reduce_telemetry(bufs) -> None:
    """Report collective payload: bytes all-reduced this step (summed
    over calls) and the number of collectives issued.  Shapes/dtypes
    are static, so this is host arithmetic at trace time — nothing is
    added to the compiled program beyond two ring-slot constants.

    Both reduce paths cast to float32 BEFORE the collective (see
    reduce_leaf / _reduce_one_flat_buffer), so the wire payload is
    4 bytes per element regardless of the leaf's storage dtype —
    counting input-dtype bytes under-reported bf16 leaves by half
    until apexcost's static analysis cross-checked this figure
    (tests/test_lint_cost.py pins the agreement)."""
    nbytes = sum(int(b.size) * 4 for b in bufs)
    _tape.emit("ddp/bytes_allreduced", float(nbytes), reduce="sum")
    _tape.emit("ddp/buckets", float(len(bufs)), reduce="sum")


def _in_shard_map(axis_name: str) -> bool:
    """True when called under shard_map/pmap with `axis_name` bound
    (comm.axis_is_bound: NameError-only probe, VERDICT r1 weak #7)."""
    return comm.axis_is_bound(axis_name)


def all_reduce_gradients(grads: Pytree,
                         axis_name: Optional[str] = comm.AXIS_DATA,
                         average: bool = True,
                         gradient_predivide_factor: float = 1.0) -> Pytree:
    """Reduce grads over the data axis (the reference's allreduce_bucket +
    divide-by-world-size, collapsed to one fused collective).

    Explicit contract: ``axis_name=None`` declares a pjit/GSPMD context
    — grads are returned unchanged because XLA already inserted the
    reduction.  With an axis name, the call must be under shard_map/pmap
    with that name bound (probed via the NameError contract above, so
    the same wrapped step works in both execution styles).
    """
    if axis_name is None or not _in_shard_map(axis_name):
        return grads
    world = comm.bound_axis_size(axis_name)
    pre = gradient_predivide_factor
    post = world / pre if average else 1.0 / pre
    _emit_reduce_telemetry(jax.tree_util.tree_leaves(grads))

    def reduce_leaf(g):
        # same cast discipline as the bucketed path: f32 leaves pay no
        # convert in either direction
        gf = g if g.dtype == jnp.float32 else g.astype(jnp.float32)
        if pre != 1.0:
            gf = gf / pre
        gf = jax.lax.psum(gf, axis_name)
        if post != 1.0:
            gf = gf / post
        return gf if gf.dtype == g.dtype else gf.astype(g.dtype)

    return jax.tree_util.tree_map(reduce_leaf, grads)


def _reduce_one_flat_buffer(b, axis_name, world, pre, post,
                            decompose: str = "psum",
                            out_dtype=None):
    """One bucket's data-parallel sum: f32 accumulation, cast back to
    ``out_dtype`` (default: the buffer's own dtype).

    Cast discipline: an already-f32 bucket pays NO convert in either
    direction — the old unconditional ``astype(f32)``/cast-back pair
    wrapped every f32 bucket (the common case) in two no-op converts
    that sat between the pack and the collective and could block
    fusion.  ``decompose="reduce_scatter"`` lowers the sum as
    psum_scatter + all_gather — bitwise the same result, but the two
    halves are independently schedulable async collectives (the
    scatter's reduction can start as soon as the bucket exists and the
    gather can complete under later compute), the latency-hiding
    scheduler's preferred shape for large buckets (docs/perf.md)."""
    bf = b if b.dtype == jnp.float32 else b.astype(jnp.float32)
    if pre != 1.0:
        bf = bf / pre
    if decompose == "reduce_scatter" and world > 1:
        n = bf.shape[0]
        pad = (-n) % world
        if pad:
            bf = jnp.pad(bf, (0, pad))
        bf = jax.lax.psum_scatter(bf, axis_name, scatter_dimension=0,
                                  tiled=True)
        bf = jax.lax.all_gather(bf, axis_name, axis=0, tiled=True)
        if pad:
            bf = jax.lax.slice(bf, (0,), (n,))
    else:
        bf = jax.lax.psum(bf, axis_name)
    if post != 1.0:
        bf = bf / post
    want = jnp.dtype(out_dtype) if out_dtype is not None \
        else jnp.dtype(b.dtype)
    return bf if bf.dtype == want else bf.astype(want)


def all_reduce_flat_buffers(bufs, axis_name: str = comm.AXIS_DATA,
                            average: bool = True,
                            gradient_predivide_factor: float = 1.0,
                            decompose: str = "psum",
                            always_fp32: bool = False):
    """Bucket-granular all-reduce: ONE collective per flat bucket.

    The flat AMP pipeline's collective stage — gradients arrive packed
    in a BucketPlan layout (a handful of large 1-D buffers instead of
    hundreds of leaves), so DDP-shaped reduction issues one collective
    per bucket.  Same average/predivide semantics as
    ``all_reduce_gradients``; f32 accumulation, results cast back to
    each buffer's dtype — with no convert at all when a bucket is
    already f32.  No-op outside shard_map (pjit/GSPMD already reduced)
    — identical contract to the per-leaf entry point.

    ``decompose="reduce_scatter"`` emits each bucket's sum as
    psum_scatter + all_gather (see :func:`_reduce_one_flat_buffer`).
    ``always_fp32=True`` keeps the REDUCED buffers in f32 instead of
    casting back to the input dtype — the reference's
    ``allreduce_always_fp32`` without the caller pre-casting (which
    paid a second convert on the way in).
    """
    if decompose not in ("psum", "reduce_scatter"):
        raise ValueError(f"unknown decompose {decompose!r}")
    bufs = list(bufs)
    if axis_name is None or not _in_shard_map(axis_name):
        if always_fp32:
            return [b if b.dtype == jnp.float32
                    else b.astype(jnp.float32) for b in bufs]
        return bufs
    world = comm.bound_axis_size(axis_name)
    pre = gradient_predivide_factor
    post = world / pre if average else 1.0 / pre
    _emit_reduce_telemetry(bufs)
    out_dtype = jnp.float32 if always_fp32 else None
    return [_reduce_one_flat_buffer(b, axis_name, world, pre, post,
                                    decompose=decompose,
                                    out_dtype=out_dtype)
            for b in bufs]


def broadcast_params(params: Pytree) -> Pytree:
    """Ctor-time rank-0 broadcast parity.  Under SPMD, "broadcast" means
    "replicate onto the mesh": device_put with a replicated sharding."""
    if not comm.is_initialized():
        return params
    sharding = comm.replicated_sharding()
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), params)


def flat_dist_call(tensors, op: Callable, args=None):
    """Reference-shaped helper (flatten → collective → unflatten).  The
    flatten step is unnecessary under XLA (collectives take pytrees), so
    this simply maps ``op`` over the tensors."""
    if args is not None:
        return [op(t, *args) for t in tensors]
    return [op(t) for t in tensors]


def _resolve_plan(plan):
    """plan= may be a BucketPlan or a bucketed fused optimizer.  An
    optimizer WITHOUT a plan (fuse_buckets=False, or the packer
    declined its tree) is a loud error, not a silent per-leaf
    fallback — the user asked for bucket-granular collectives and must
    learn they are not getting them (FlatGradPipeline raises for the
    same input)."""
    if plan is None:
        return None
    resolved = getattr(plan, "_plan", plan)
    if resolved is None:
        raise ValueError(
            "plan= was given an optimizer without a bucket plan "
            "(fuse_buckets=False or the packer declined its tree) — "
            "bucket-granular reduction needs the bucketed path; omit "
            "plan= for per-leaf reduction")
    return resolved


class Reducer:
    """Raw gradient reducer (reference: apex/parallel/distributed.py::
    Reducer) — explicitly-invoked reduction, no hooks.

    ``plan``: an optional :class:`BucketPlan` (or a bucketed fused
    optimizer, whose plan is borrowed).  With a plan, reduction is
    bucket-granular — pytree grads are packed once and reduced as flat
    buckets (one psum per bucket, the reference's allreduce_bucket
    made literal), and already-packed buffer lists are reduced as-is
    and returned packed, so the flat AMP pipeline keeps grads flat
    straight through the collective."""

    def __init__(self, module_or_grads_list=None,
                 axis_name: str = comm.AXIS_DATA, plan=None):
        self.axis_name = axis_name
        self.plan = _resolve_plan(plan)

    def reduce(self, grads: Pytree, average: bool = True) -> Pytree:
        if self.plan is not None:
            if self.plan.is_packed(grads):
                return all_reduce_flat_buffers(
                    grads, self.axis_name, average=average)
            # no-op contexts (axis unbound / GSPMD) must stay free:
            # don't pay a pack+unpack gradient copy for nothing
            if self.axis_name is None \
                    or not _in_shard_map(self.axis_name):
                return grads
            bufs = all_reduce_flat_buffers(
                self.plan.pack_grads(grads), self.axis_name,
                average=average)
            return self.plan.unpack_grads(bufs)
        return all_reduce_gradients(grads, self.axis_name, average=average)


class DistributedDataParallel:
    """apex.parallel.DistributedDataParallel-shaped wrapper.

    Wraps an ``apply_fn(params, *args) -> out`` (or a flax module's
    ``.apply``).  Forward is a passthrough; ``reduce_gradients`` performs
    the data-parallel mean that the reference performed via backward-hook
    buckets.  Intended use inside a shard_map-decorated train step:

        ddp = DistributedDataParallel(model.apply)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch_shard)
        grads = ddp.reduce_gradients(grads)
    """

    def __init__(self, apply_fn: Callable = None,
                 message_size: int = 10_000_000,
                 delay_allreduce: bool = False,
                 shared_param: Optional[bool] = None,
                 allreduce_trigger_params=None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_name: str = comm.AXIS_DATA,
                 bucket_plan=None,
                 reduce_decompose: str = "psum"):
        # bucketing/overlap knobs accepted for parity; XLA owns scheduling
        del message_size, delay_allreduce, shared_param
        del allreduce_trigger_params, retain_allreduce_buffers
        self.apply_fn = apply_fn
        if reduce_decompose == "auto":
            # measured per-topology preference (tools/autotune.py);
            # absent entry = the design default
            from apex_tpu.ops import _dispatch
            reduce_decompose = _dispatch.pipeline_pref(
                "reduce_decompose", "psum")
        self.reduce_decompose = reduce_decompose
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.axis_name = axis_name
        # bucket_plan: a BucketPlan or bucketed fused optimizer — grads
        # then reduce as flat buckets (one collective per bucket), the
        # honest realization of the knobs deleted above
        self.bucket_plan = _resolve_plan(bucket_plan)

    def __call__(self, *args, **kwargs):
        return self.apply_fn(*args, **kwargs)

    def reduce_gradients(self, grads: Pytree) -> Pytree:
        if self.bucket_plan is not None:
            packed = self.bucket_plan.is_packed(grads)
            if not packed and (self.axis_name is None
                               or not _in_shard_map(self.axis_name)):
                # no-op context: skip the pack+unpack gradient copy
                # (per-leaf path below returns grads untouched too)
                return grads
            bufs = (list(grads) if packed
                    else self.bucket_plan.pack_grads(grads))
            # allreduce_always_fp32 rides the reduction's own f32
            # accumulation (skip the cast-back) instead of pre-casting
            # every bucket — the old pre-cast put a second convert in
            # front of the collective for buckets that were bf16 and a
            # no-op convert for ones already f32
            bufs = all_reduce_flat_buffers(
                bufs, self.axis_name, average=self.gradient_average,
                gradient_predivide_factor=self.gradient_predivide_factor,
                decompose=self.reduce_decompose,
                always_fp32=self.allreduce_always_fp32)
            # packed in -> packed out (the flat pipeline consumes the
            # buckets directly); tree in -> tree out
            return bufs if packed else self.bucket_plan.unpack_grads(bufs)
        if self.allreduce_always_fp32:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
        return all_reduce_gradients(
            grads, self.axis_name, average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor)
