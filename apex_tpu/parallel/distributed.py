"""Data-parallel training utilities (reference: apex/parallel/distributed.py).

The reference DDP registers per-parameter backward hooks, buckets grads,
and overlaps NCCL all-reduce with the rest of backward (SURVEY.md §3.4).
Under SPMD on TPU that whole mechanism disappears: the train step runs
inside shard_map/pjit over the "data" mesh axis, gradients are reduced by
ONE psum that XLA schedules and overlaps itself.  This module keeps the
reference's API shape on top of that reality:

  - ``DistributedDataParallel`` wraps an apply_fn; its
    ``reduce_gradients`` is the explicit psum/pmean (for shard_map-style
    steps).  Bucketing knobs (message_size, delay_allreduce,
    allreduce_trigger_params) are accepted and ignored — XLA's collective
    scheduler owns that decision.
  - ``flat_dist_call`` / ``broadcast_params`` mirror the ctor broadcast.
  - ``Reducer`` is the raw-reduction facade.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu import comm

Pytree = Any


def _in_shard_map(axis_name: str) -> bool:
    """True when called under shard_map/pmap with `axis_name` bound
    (comm.axis_is_bound: NameError-only probe, VERDICT r1 weak #7)."""
    return comm.axis_is_bound(axis_name)


def all_reduce_gradients(grads: Pytree,
                         axis_name: Optional[str] = comm.AXIS_DATA,
                         average: bool = True,
                         gradient_predivide_factor: float = 1.0) -> Pytree:
    """Reduce grads over the data axis (the reference's allreduce_bucket +
    divide-by-world-size, collapsed to one fused collective).

    Explicit contract: ``axis_name=None`` declares a pjit/GSPMD context
    — grads are returned unchanged because XLA already inserted the
    reduction.  With an axis name, the call must be under shard_map/pmap
    with that name bound (probed via the NameError contract above, so
    the same wrapped step works in both execution styles).
    """
    if axis_name is None or not _in_shard_map(axis_name):
        return grads
    world = jax.lax.axis_size(axis_name)
    pre = gradient_predivide_factor
    post = world / pre if average else 1.0 / pre

    def reduce_leaf(g):
        gf = g.astype(jnp.float32)
        if pre != 1.0:
            gf = gf / pre
        gf = jax.lax.psum(gf, axis_name)
        if post != 1.0:
            gf = gf / post
        return gf.astype(g.dtype)

    return jax.tree_util.tree_map(reduce_leaf, grads)


def broadcast_params(params: Pytree) -> Pytree:
    """Ctor-time rank-0 broadcast parity.  Under SPMD, "broadcast" means
    "replicate onto the mesh": device_put with a replicated sharding."""
    if not comm.is_initialized():
        return params
    sharding = comm.replicated_sharding()
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), params)


def flat_dist_call(tensors, op: Callable, args=None):
    """Reference-shaped helper (flatten → collective → unflatten).  The
    flatten step is unnecessary under XLA (collectives take pytrees), so
    this simply maps ``op`` over the tensors."""
    if args is not None:
        return [op(t, *args) for t in tensors]
    return [op(t) for t in tensors]


class Reducer:
    """Raw gradient reducer (reference: apex/parallel/distributed.py::
    Reducer) — explicitly-invoked reduction, no hooks."""

    def __init__(self, module_or_grads_list=None,
                 axis_name: str = comm.AXIS_DATA):
        self.axis_name = axis_name

    def reduce(self, grads: Pytree, average: bool = True) -> Pytree:
        return all_reduce_gradients(grads, self.axis_name, average=average)


class DistributedDataParallel:
    """apex.parallel.DistributedDataParallel-shaped wrapper.

    Wraps an ``apply_fn(params, *args) -> out`` (or a flax module's
    ``.apply``).  Forward is a passthrough; ``reduce_gradients`` performs
    the data-parallel mean that the reference performed via backward-hook
    buckets.  Intended use inside a shard_map-decorated train step:

        ddp = DistributedDataParallel(model.apply)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch_shard)
        grads = ddp.reduce_gradients(grads)
    """

    def __init__(self, apply_fn: Callable = None,
                 message_size: int = 10_000_000,
                 delay_allreduce: bool = False,
                 shared_param: Optional[bool] = None,
                 allreduce_trigger_params=None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_name: str = comm.AXIS_DATA):
        # bucketing/overlap knobs accepted for parity; XLA owns scheduling
        del message_size, delay_allreduce, shared_param
        del allreduce_trigger_params, retain_allreduce_buffers
        self.apply_fn = apply_fn
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.axis_name = axis_name

    def __call__(self, *args, **kwargs):
        return self.apply_fn(*args, **kwargs)

    def reduce_gradients(self, grads: Pytree) -> Pytree:
        if self.allreduce_always_fp32:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
        return all_reduce_gradients(
            grads, self.axis_name, average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor)
