"""SyncBatchNorm (reference: apex/parallel/optimized_sync_batchnorm.py +
sync_batchnorm_kernel.py, call stack SURVEY.md §3.6).

Reference structure: local Welford stats → all_gather(mean, var, count)
→ Welford combine → normalize; backward all-reduces (sum_dy, sum_dy_xmu).
TPU rebuild keeps exactly that dataflow: local stats from the Pallas
Welford kernel (apex_tpu.ops.welford), the cross-device combine is a
``psum`` of (count, sum, sumsq-equivalents) over the "data" mesh axis
inside shard_map, and the backward's reductions fall out of autodiff-ing
the psum (jax differentiates collectives), so no hand-written backward
kernel is needed.

Outside shard_map (single device or GSPMD auto-partitioning) the sync
degenerates to plain BatchNorm, matching the reference's behavior in a
single-process run.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.ops.welford import welford_mean_var_ref


def _axis_bound(axis_name: str) -> bool:
    return comm.axis_is_bound(axis_name)


def sync_batch_norm_stats(x2d: jax.Array, axis_name: Optional[str]):
    """Global (mean, biased var) of an (N, C) array, synced over
    ``axis_name`` when bound.

    Local stats come from the (differentiable) Welford reference path;
    the cross-device merge is Chan's combine expressed with two psums —
    numerically stable where a sum/sumsq merge would cancel
    catastrophically for large-mean activations.
    """
    mean_l, var_l, n_l = welford_mean_var_ref(x2d)
    m2_l = var_l * n_l
    if axis_name is not None and _axis_bound(axis_name):
        n, nmean = jax.lax.psum((n_l, n_l * mean_l), axis_name)
        mean = nmean / n
        # Chan: M2 = sum_i (M2_i + n_i * (mean_i - mean)^2)
        m2 = jax.lax.psum(m2_l + n_l * (mean_l - mean) ** 2, axis_name)
    else:
        n, mean, m2 = n_l, mean_l, m2_l
    var = m2 / n
    return mean, jnp.maximum(var, 0.0), n


class SyncBatchNorm(nn.Module):
    """Reference-shaped constructor (num_features, eps, momentum, affine,
    track_running_stats, channel_last); process_group is a mesh-axis name
    instead of a torch process group."""

    num_features: Optional[int] = None   # None: infer from the input
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    process_group: Optional[str] = comm.AXIS_DATA
    channel_last: bool = False
    use_running_average: Optional[bool] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        if self.num_features is not None:
            c = self.num_features
        else:
            c = (x.shape[-1] if self.channel_last or x.ndim == 2
                 else x.shape[1])
        if self.channel_last or x.ndim == 2:
            xc = x.reshape(-1, c)                      # (..., C)
            def restore(y2d):
                return y2d.reshape(x.shape)
        else:
            # NCHW-style: channel axis 1 (reference default layout)
            perm = (0,) + tuple(range(2, x.ndim)) + (1,)
            xt = jnp.transpose(x, perm)
            xc = xt.reshape(-1, c)
            inv = tuple(int(i) for i in jnp.argsort(jnp.array(perm)))
            def restore(y2d):
                return jnp.transpose(y2d.reshape(xt.shape), inv)

        ra_mean = self.variable("batch_stats", "running_mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "running_var",
                               lambda: jnp.ones((c,), jnp.float32))

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            mean, var, n = sync_batch_norm_stats(xc, self.process_group)
            if self.track_running_stats and not self.is_initializing():
                m = self.momentum
                # torch stores UNBIASED running var
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * unbiased

        y = (xc.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            w = self.param("weight", nn.initializers.ones, (c,), jnp.float32)
            b = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
            y = y * w + b
        return restore(y.astype(x.dtype))


def convert_syncbn_model(module: Any, process_group: Optional[str] =
                         comm.AXIS_DATA, channel_last: bool = False):
    """Reference parity: apex.parallel.convert_syncbn_model recursively
    swaps torch BatchNorm modules for SyncBatchNorm.  flax modules are
    immutable dataclasses, so the equivalent is a clone with every
    nn.BatchNorm leaf replaced; models built from apex_tpu.models take a
    ``norm_cls`` factory instead — pass ``SyncBatchNorm`` there.  For a
    bare nn.BatchNorm this returns the configured SyncBatchNorm."""
    if isinstance(module, nn.BatchNorm):
        return SyncBatchNorm(
            num_features=None,               # inferred at first call
            momentum=1.0 - module.momentum,  # flax momentum is decay
            eps=module.epsilon,
            process_group=process_group,
            channel_last=channel_last,
        )
    if hasattr(module, "replace_norm"):
        return module.replace_norm(SyncBatchNorm)
    raise TypeError(
        "convert_syncbn_model supports flax nn.BatchNorm instances or "
        "modules exposing replace_norm(); build apex_tpu models with "
        "norm_cls=SyncBatchNorm instead.")
