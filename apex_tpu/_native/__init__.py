"""Native host runtime (reference L0's C++ half: csrc/
flatten_unflatten.cpp and friends, SURVEY.md §2.4 `apex_C`).

The .so is built lazily with the system g++ on first import (the
environment bans pip installs, not compilers) and cached next to the
source; every entry point has a NumPy fallback so the package works even
without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "apex_c.cpp")
_SO = os.path.join(_HERE, "libapex_c.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _src_hash() -> str:
    import hashlib
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def _build() -> Optional[str]:
    # Rebuild keyed on a source-content hash, not mtimes: a checkout
    # refreshes every mtime, which made a stale (possibly other-arch)
    # committed .so look fresh forever (ADVICE r1).
    stamp = _SO + ".srchash"
    try:
        want = _src_hash()
    except OSError:      # source not shipped/readable: NumPy fallback
        return _SO if os.path.exists(_SO) else None
    if os.path.exists(_SO) and os.path.exists(stamp):
        try:
            with open(stamp) as f:
                if f.read().strip() == want:
                    return _SO
        except OSError:
            pass
    try:
        # compile to a private temp path and publish atomically: a
        # concurrent first-run process must never CDLL a torn ELF
        tmp = f"{_SO}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        tmp_stamp = f"{stamp}.{os.getpid()}.tmp"
        with open(tmp_stamp, "w") as f:
            f.write(want)
        os.replace(tmp_stamp, stamp)
        return _SO
    except Exception:
        return None


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (NumPy fallbacks engage)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is None and not _tried:
            # double-checked locking: writes happen under _lock;
            # the unlocked fast-path READ above is a GIL-atomic
            # reference check whose worst case is blocking on
            # _lock like everyone else
            _tried = True   # apexlint: disable=APX1001
            so = _build()
            if so:
                try:
                    l = ctypes.CDLL(so)
                    i64p = ctypes.POINTER(ctypes.c_int64)
                    l.apex_c_flatten.restype = None
                    l.apex_c_flatten.argtypes = [
                        ctypes.POINTER(ctypes.c_void_p), i64p,
                        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
                    l.apex_c_unflatten.restype = None
                    l.apex_c_unflatten.argtypes = [
                        ctypes.c_void_p, i64p, ctypes.c_int64,
                        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64]
                    l.apex_c_l2norm_sq_f32.restype = ctypes.c_double
                    l.apex_c_l2norm_sq_f32.argtypes = [
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                        ctypes.c_int64]
                    _lib = l   # apexlint: disable=APX1001
                except OSError:
                    _lib = None
    return _lib


def available() -> bool:
    return lib() is not None


def _n_threads() -> int:
    return min(8, os.cpu_count() or 1)


def host_flatten(arrays: List[np.ndarray]) -> np.ndarray:
    """Pack host arrays into one contiguous byte buffer (apex_C.flatten
    semantics on the host side; dtype-agnostic)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = np.asarray([a.nbytes for a in arrays], np.int64)
    out = np.empty(int(sizes.sum()), np.uint8)
    l = lib()
    if l is None or not arrays:
        off = 0
        for a, nb in zip(arrays, sizes):
            out[off:off + nb] = a.view(np.uint8).ravel()
            off += int(nb)
        return out
    Ptrs = ctypes.c_void_p * len(arrays)
    ptrs = Ptrs(*[a.ctypes.data_as(ctypes.c_void_p) for a in arrays])
    l.apex_c_flatten(ptrs, sizes.ctypes.data_as(
        ctypes.POINTER(ctypes.c_int64)), len(arrays),
        out.ctypes.data_as(ctypes.c_void_p), _n_threads())
    return out


def host_unflatten(flat: np.ndarray, like: List[np.ndarray]
                   ) -> List[np.ndarray]:
    """Inverse of host_flatten: split into arrays shaped/dtyped as `like`."""
    flat = np.ascontiguousarray(flat.view(np.uint8).ravel())
    outs = [np.empty(a.shape, a.dtype) for a in like]
    sizes = np.asarray([a.nbytes for a in outs], np.int64)
    l = lib()
    if l is None or not outs:
        off = 0
        for o, nb in zip(outs, sizes):
            o.view(np.uint8).ravel()[:] = flat[off:off + int(nb)]
            off += int(nb)
        return outs
    Ptrs = ctypes.c_void_p * len(outs)
    ptrs = Ptrs(*[o.ctypes.data_as(ctypes.c_void_p) for o in outs])
    l.apex_c_unflatten(flat.ctypes.data_as(ctypes.c_void_p),
                       sizes.ctypes.data_as(
                           ctypes.POINTER(ctypes.c_int64)),
                       len(outs), ptrs, _n_threads())
    return outs


def host_l2norm(x: np.ndarray) -> float:
    """Threaded L2 norm of a host f32 buffer (checkpoint checksums)."""
    x = np.ascontiguousarray(x, np.float32).ravel()
    l = lib()
    if l is None:
        return float(np.linalg.norm(x.astype(np.float64)))
    return float(l.apex_c_l2norm_sq_f32(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        x.size, _n_threads())) ** 0.5
