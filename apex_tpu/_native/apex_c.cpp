// Native host-side buffer utilities (reference: csrc/flatten_unflatten.cpp
// — apex_C.flatten/unflatten, the C++ glue behind DDP bucketing, and the
// checksum/norm helpers the multi_tensor path uses on host).
//
// TPU role: device-side flatten is jnp.concatenate (XLA), but the HOST
// side — checkpoint packing, DDP bucket assembly before device_put,
// grad-norm checksums over checkpoint shards — benefits from a real
// parallel memcpy/reduction instead of Python loops.  Built lazily with
// g++ -O3 -shared (no CUDA analog needed: this half of the reference was
// always pure C++).

#include <cstdint>
#include <cstring>
#include <cmath>
#include <thread>
#include <vector>

extern "C" {

// Pack n buffers (ptrs[i], nbytes[i]) into dst contiguously, threaded.
void apex_c_flatten(const void** ptrs, const int64_t* nbytes, int64_t n,
                    void* dst, int64_t n_threads) {
    std::vector<int64_t> offsets(n + 1, 0);
    for (int64_t i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + nbytes[i];
    if (n_threads < 1) n_threads = 1;
    auto worker = [&](int64_t tid) {
        for (int64_t i = tid; i < n; i += n_threads) {
            std::memcpy(static_cast<char*>(dst) + offsets[i], ptrs[i],
                        static_cast<size_t>(nbytes[i]));
        }
    };
    std::vector<std::thread> ts;
    for (int64_t t = 1; t < n_threads; ++t) ts.emplace_back(worker, t);
    worker(0);
    for (auto& t : ts) t.join();
}

// Scatter src back into n buffers (the unflatten inverse).
void apex_c_unflatten(const void* src, const int64_t* nbytes, int64_t n,
                      void** ptrs, int64_t n_threads) {
    std::vector<int64_t> offsets(n + 1, 0);
    for (int64_t i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + nbytes[i];
    if (n_threads < 1) n_threads = 1;
    auto worker = [&](int64_t tid) {
        for (int64_t i = tid; i < n; i += n_threads) {
            std::memcpy(ptrs[i],
                        static_cast<const char*>(src) + offsets[i],
                        static_cast<size_t>(nbytes[i]));
        }
    };
    std::vector<std::thread> ts;
    for (int64_t t = 1; t < n_threads; ++t) ts.emplace_back(worker, t);
    worker(0);
    for (auto& t : ts) t.join();
}

// Threaded squared-L2 over a float32 buffer (host-side multi_tensor_l2norm
// for checkpoint verification / bucket checksums).
double apex_c_l2norm_sq_f32(const float* x, int64_t n, int64_t n_threads) {
    if (n_threads < 1) n_threads = 1;
    std::vector<double> partial(static_cast<size_t>(n_threads), 0.0);
    auto worker = [&](int64_t tid) {
        int64_t chunk = (n + n_threads - 1) / n_threads;
        int64_t lo = tid * chunk;
        int64_t hi = lo + chunk < n ? lo + chunk : n;
        double acc = 0.0;
        for (int64_t i = lo; i < hi; ++i) {
            double v = static_cast<double>(x[i]);
            acc += v * v;
        }
        partial[static_cast<size_t>(tid)] = acc;
    };
    std::vector<std::thread> ts;
    for (int64_t t = 1; t < n_threads; ++t) ts.emplace_back(worker, t);
    worker(0);
    for (auto& t : ts) t.join();
    double total = 0.0;
    for (double p : partial) total += p;
    return total;
}

}  // extern "C"
