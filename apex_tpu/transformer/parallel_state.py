"""Model-parallel topology state (reference:
apex/transformer/parallel_state.py).

The reference builds torch.distributed process groups for TP x PP x DP
(plus virtual-PP bookkeeping and embedding groups).  Here the topology IS
the global mesh (apex_tpu.comm); "groups" are mesh axes, and rank queries
answer from ``jax.lax.axis_index`` inside traced code or from the mesh
config outside.  The API names mirror the reference 1:1 so Megatron-style
code ports directly.
"""

from __future__ import annotations

from typing import Optional

import jax

from apex_tpu import comm

_VIRTUAL_PP_SIZE: Optional[int] = None
_VIRTUAL_PP_RANK: Optional[int] = None


def initialize_model_parallel(
        tensor_model_parallel_size_: int = 1,
        pipeline_model_parallel_size_: int = 1,
        virtual_pipeline_model_parallel_size_: Optional[int] = None,
        pipeline_model_parallel_split_rank_: Optional[int] = None,
        context_parallel_size: int = 1,
        *, default_backend: Optional[str] = None,
        p2p_backend: Optional[str] = None) -> None:
    """Build the mesh: world = dp x pp x cp x tp, tp minor (ICI-adjacent).

    default_backend/p2p_backend are accepted for signature parity and
    ignored (XLA owns the transport: ICI intra-slice, DCN inter-slice).
    """
    global _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK
    comm.initialize(data=-1,
                    pipe=pipeline_model_parallel_size_,
                    ctx=context_parallel_size,
                    model=tensor_model_parallel_size_)
    _VIRTUAL_PP_SIZE = virtual_pipeline_model_parallel_size_
    _VIRTUAL_PP_RANK = 0 if virtual_pipeline_model_parallel_size_ else None


def model_parallel_is_initialized() -> bool:
    return comm.is_initialized()


def destroy_model_parallel() -> None:
    global _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK
    comm.destroy()
    _VIRTUAL_PP_SIZE = None
    _VIRTUAL_PP_RANK = None


# --- group handles: a "group" is a mesh axis name -------------------------

def get_tensor_model_parallel_group() -> str:
    return comm.AXIS_MODEL


def get_pipeline_model_parallel_group() -> str:
    return comm.AXIS_PIPE


def get_data_parallel_group() -> str:
    return comm.AXIS_DATA


def get_context_parallel_group() -> str:
    return comm.AXIS_CTX


def get_embedding_group() -> str:
    # first+last pipeline stages share embedding grads; on the mesh this
    # is a psum over the pipe axis masked to those stages
    return comm.AXIS_PIPE


# --- sizes ----------------------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return comm.model_parallel_size()


def get_pipeline_model_parallel_world_size() -> int:
    return comm.pipeline_parallel_size()


def get_data_parallel_world_size() -> int:
    return comm.data_parallel_size()


def get_context_parallel_world_size() -> int:
    return comm.context_parallel_size()


# --- ranks ----------------------------------------------------------------

def _axis_rank(axis: str):
    """Rank on an axis: traced value inside shard_map, 0 outside (the
    single-controller host view)."""
    if comm.axis_is_bound(axis):
        return jax.lax.axis_index(axis)
    return 0


def get_tensor_model_parallel_rank():
    return _axis_rank(comm.AXIS_MODEL)


def get_pipeline_model_parallel_rank():
    return _axis_rank(comm.AXIS_PIPE)


def get_data_parallel_rank():
    return _axis_rank(comm.AXIS_DATA)


def get_context_parallel_rank():
    return _axis_rank(comm.AXIS_CTX)


def get_tensor_model_parallel_src_rank() -> int:
    return 0


def is_pipeline_first_stage(ignore_virtual: bool = False):
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        if _VIRTUAL_PP_RANK != 0:
            return False
    r = get_pipeline_model_parallel_rank()
    return r == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        if _VIRTUAL_PP_RANK != _VIRTUAL_PP_SIZE - 1:
            return False
    r = get_pipeline_model_parallel_rank()
    return r == get_pipeline_model_parallel_world_size() - 1


# --- virtual pipeline bookkeeping ----------------------------------------

def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PP_SIZE


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PP_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    global _VIRTUAL_PP_RANK
    _VIRTUAL_PP_RANK = rank


def get_pipeline_model_parallel_prev_rank():
    world = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() - 1) % world


def get_pipeline_model_parallel_next_rank():
    world = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() + 1) % world
