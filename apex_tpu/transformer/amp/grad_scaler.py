"""Grad scaler variant used by the transformer stack (reference:
apex/transformer/amp/grad_scaler.py).

The reference subclasses torch.cuda.amp.GradScaler to all-reduce the
found_inf flag across the model-parallel group (so every pipeline/tensor
rank skips in lockstep).  Here the flag is already a traced value; the
sync is a pmax over every bound mesh axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.amp.scaler import (LossScaleConfig, LossScaleState,
                                 LossScaler, update_state)


def sync_found_inf(found_inf, axes=(comm.AXIS_MODEL, comm.AXIS_PIPE,
                                    comm.AXIS_DATA)):
    """Max-reduce the overflow flag over all bound parallel axes."""
    for ax in axes:
        if comm.axis_is_bound(ax):
            found_inf = jax.lax.pmax(found_inf, ax)
    return found_inf


class GradScaler(LossScaler):
    """LossScaler whose update first syncs found_inf across the mesh."""

    def update_scale(self, found_inf):
        found_inf = sync_found_inf(jnp.asarray(found_inf, jnp.int32))
        self.state = update_state(self.state, found_inf, self.config)


__all__ = ["GradScaler", "sync_found_inf", "LossScaleState",
           "LossScaleConfig"]
