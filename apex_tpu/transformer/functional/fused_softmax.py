"""FusedScaleMaskSoftmax (reference:
apex/transformer/functional/fused_softmax.py).

Stateless callable with the reference's constructor surface, dispatching
to the Pallas kernels in apex_tpu.ops.softmax (causal → the
upper-triang variant, padding → the masked variant) with the same
eligibility logic idea (kernel when shapes allow, generic XLA path
otherwise).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from apex_tpu.ops import softmax as softmax_ops
from apex_tpu.transformer.enums import AttnMaskType


class FusedScaleMaskSoftmax:
    def __init__(self,
                 input_in_fp16: bool = False,
                 input_in_bf16: bool = True,
                 attn_mask_type: AttnMaskType = AttnMaskType.padding,
                 scaled_masked_softmax_fusion: bool = True,
                 mask_func: Optional[Callable] = None,
                 softmax_in_fp32: bool = True,
                 scale: Optional[float] = None):
        assert not (input_in_fp16 and input_in_bf16), \
            "both fp16 and bf16 flags cannot be active at the same time."
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        assert self.scale is None or softmax_in_fp32, \
            "softmax should be in fp32 when scaled"

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        return (self.scaled_masked_softmax_fusion
                and sk % 128 == 0 and sk <= softmax_ops._MAX_SK)

    def __call__(self, x, mask=None):
        scale = self.scale if self.scale is not None else 1.0
        b, np_, sq, sk = x.shape
        if self.attn_mask_type == AttnMaskType.causal:
            # the reference asserts squareness here too — a silent
            # fall-through would drop causality entirely
            assert sq == sk, \
                "causal mask requires square attention (sq == sk)"
            y = softmax_ops.scaled_upper_triang_masked_softmax(
                x.reshape(-1, sq, sk), scale)
            return y.reshape(x.shape)
        if self.mask_func is not None and mask is not None and \
                not self.scaled_masked_softmax_fusion:
            # reference "torch fallback": user mask_func + plain softmax
            xf = self.mask_func(x.astype(jnp.float32) * scale, mask)
            import jax
            return jax.nn.softmax(xf, axis=-1).astype(x.dtype)
        return softmax_ops.scaled_masked_softmax(x, mask, scale)


scaled_masked_softmax = softmax_ops.scaled_masked_softmax
scaled_upper_triang_masked_softmax = \
    softmax_ops.scaled_upper_triang_masked_softmax
generic_scaled_masked_softmax = softmax_ops.generic_scaled_masked_softmax
