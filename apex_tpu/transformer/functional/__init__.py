"""apex_tpu.transformer.functional (reference:
apex/transformer/functional).

``fp8_matmul`` (beyond-reference) is the e4m3/e5m2 quantized matmul
the transformer blocks take under ``amp.initialize(..., fp8=...)`` —
the tensor-parallel linears route their local dot through it when
built with ``fp8=state.fp8_policy`` (docs/amp.md "fp8 training")."""

from apex_tpu.fused_dense.fused_dense import fp8_matmul
from apex_tpu.transformer.functional.fused_softmax import (
    FusedScaleMaskSoftmax,
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.functional.fused_rope import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
)

__all__ = [
    "FusedScaleMaskSoftmax",
    "fp8_matmul",
    "generic_scaled_masked_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
]
