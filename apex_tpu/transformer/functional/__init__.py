"""apex_tpu.transformer.functional (reference:
apex/transformer/functional)."""

from apex_tpu.transformer.functional.fused_softmax import (
    FusedScaleMaskSoftmax,
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.functional.fused_rope import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
)

__all__ = [
    "FusedScaleMaskSoftmax",
    "generic_scaled_masked_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
]
