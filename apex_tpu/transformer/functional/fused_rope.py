"""Fused RoPE wrappers (reference:
apex/transformer/functional/fused_rope.py)."""

from apex_tpu.ops.rope import fused_apply_rotary_pos_emb, rope_ref


def fused_apply_rotary_pos_emb_cached(t, cos, sin, interleaved=False):
    """Variant taking precomputed cos/sin (reference cached API).

    cos/sin: (s, 1, 1, hn)."""
    import jax.numpy as jnp
    freqs = jnp.arctan2(sin.astype(jnp.float32), cos.astype(jnp.float32))
    return fused_apply_rotary_pos_emb(t, freqs, interleaved)


__all__ = ["fused_apply_rotary_pos_emb",
           "fused_apply_rotary_pos_emb_cached", "rope_ref"]
