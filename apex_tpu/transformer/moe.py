"""Mixture-of-experts with expert parallelism over the mesh.

NO reference equivalent: apex has no MoE and SURVEY.md §2.5 marks
expert parallelism out of reference scope.  Like ``ring_attention``
(context parallelism), this is a TPU-native extension that makes the
remaining first-class parallelism axis available: experts shard over a
mesh axis and tokens move with ONE ``lax.all_to_all`` each way riding
ICI — the dispatch pattern every TPU MoE uses (the "how to scale your
model" recipe: dense dispatch/combine einsums + all_to_all, static
capacity so shapes never depend on routing).

Per-rank SPMD view (use inside shard_map over ``axis``):

  x (T, H) tokens local to this rank
  -> top-k gating (router replicated)
  -> dispatch einsum to (E, C, H)          [E = global experts]
  -> all_to_all over ``axis``              [tokens to expert owners]
  -> local expert FFN (E/ep experts here)
  -> all_to_all back
  -> combine einsum weighted by gate probs

Static capacity C = ceil(2 * T * capacity_factor / E) (top-2:
two assignments per token); overflow tokens are
dropped by the position-in-expert cumsum mask (standard MoE semantics;
dropped tokens pass through the residual path of the caller).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.transformer.tensor_parallel import mappings

Array = jax.Array


def _capacity(tokens: int, num_experts: int,
              capacity_factor: float, k: int = 2) -> int:
    """GShard-style top-k capacity: ceil(k * T * cf / E) — k assignments
    per token must fit in E * C slots at cf=1 under perfect balance."""
    c = -(-(k * tokens * capacity_factor) // num_experts)
    return max(int(c), 1)


def top2_gating(logits: Array, capacity: int,
                jitter_rng: Optional[Array] = None,
                jitter_eps: float = 0.0
                ) -> Tuple[Array, Array, Array]:
    """Top-2 router (Shazeer-style), static shapes throughout.

    logits (T, E) -> (dispatch (T, E, C) bool, combine (T, E, C) f32,
    aux_loss scalar).  combine carries the renormalized gate prob at
    the token's position in its expert's capacity buffer; tokens past
    capacity get all-zero rows (dropped).
    """
    t, e = logits.shape
    if jitter_rng is not None and jitter_eps > 0.0:
        # multiplicative jitter: noise scales with logit magnitude
        logits = logits * jax.random.uniform(
            jitter_rng, logits.shape, logits.dtype,
            1.0 - jitter_eps, 1.0 + jitter_eps)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    g1 = jnp.max(probs, axis=-1)
    i1 = jnp.argmax(probs, axis=-1)
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(i1, e))
    g2 = jnp.max(probs_wo1, axis=-1)
    i2 = jnp.argmax(probs_wo1, axis=-1)

    # load-balancing auxiliary loss (mean prob * mean assignment)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(i1, e, dtype=jnp.float32), axis=0)
    aux = jnp.sum(me * ce) * e

    # position of each token within its chosen expert, first choice
    # filling before second (the usual priority)
    oh1 = jax.nn.one_hot(i1, e, dtype=jnp.int32)            # (T, E)
    oh2 = jax.nn.one_hot(i2, e, dtype=jnp.int32)
    pos1 = jnp.cumsum(oh1, axis=0) - oh1                    # (T, E)
    count1 = jnp.sum(oh1, axis=0, keepdims=True)
    pos2 = jnp.cumsum(oh2, axis=0) - oh2 + count1
    p1 = jnp.sum(pos1 * oh1, axis=1)                        # (T,)
    p2 = jnp.sum(pos2 * oh2, axis=1)
    keep1 = p1 < capacity
    keep2 = p2 < capacity

    # renormalize the two gates over the kept pair
    denom = g1 * keep1 + g2 * keep2
    denom = jnp.where(denom > 0.0, denom, 1.0)
    w1 = jnp.where(keep1, g1 / denom, 0.0)
    w2 = jnp.where(keep2, g2 / denom, 0.0)

    # one_hot of index==capacity (overflow sentinel) is an all-zero row
    cap_oh1 = jax.nn.one_hot(jnp.where(keep1, p1, capacity), capacity,
                             dtype=jnp.float32)
    cap_oh2 = jax.nn.one_hot(jnp.where(keep2, p2, capacity), capacity,
                             dtype=jnp.float32)
    combine = (w1[:, None, None] * oh1[..., None] * cap_oh1[:, None, :]
               + w2[:, None, None] * oh2[..., None] * cap_oh2[:, None, :])
    dispatch = combine > 0.0
    return dispatch, combine.astype(jnp.float32), aux


class ExpertParallelMLP(nn.Module):
    """Top-2 MoE FFN with experts sharded over a mesh axis.

    hidden/ffn sizes are per-expert; ``num_experts`` is GLOBAL and must
    divide by the axis size.  Call inside shard_map with ``axis`` bound
    (or axis=None / unbound for single-rank execution, where all
    experts live locally — the degenerate path used off-mesh).

    Returns (out (T, H), aux_loss).  Router jitter applies only when
    ``deterministic=False`` (training) — eval calls need no rng.
    """
    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    capacity_factor: float = 1.25
    router_jitter_eps: float = 0.0   # multiplicative routing noise
    axis: Optional[str] = comm.AXIS_MODEL
    activation: Callable = jax.nn.gelu
    param_dtype: jnp.dtype = jnp.float32
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = False):
        t, h = x.shape
        e = self.num_experts
        ep = (comm.bound_axis_size(self.axis)
              if self.axis is not None and comm.axis_is_bound(self.axis)
              else 1)
        if e % ep != 0:
            raise ValueError(f"num_experts {e} % axis size {ep} != 0")
        e_local = e // ep
        dt = self.dtype or x.dtype

        wg = self.param("router", nn.initializers.normal(0.02),
                        (h, e), jnp.float32)
        if ep > 1:
            # replicated router consumed by TOKEN-SHARDED inputs: each
            # rank's router grad sums only its token shard, so the true
            # grad needs a psum over the expert axis — same f/g copy
            # mapping (fwd identity / bwd psum) as the sequence-parallel
            # layernorm params
            wg = mappings.copy_to_tensor_model_parallel_region(
                wg, self.axis)
        # per-rank expert shards, rank-decorrelated init
        def einit(base):
            def init(key, shape, dtype):
                if ep > 1:
                    key = jax.random.fold_in(
                        key, jax.lax.axis_index(self.axis))
                return base(key, shape, dtype)
            return init
        w1 = self.param("w1", einit(nn.initializers.lecun_normal()),
                        (e_local, h, self.ffn_hidden_size),
                        self.param_dtype)
        w2 = self.param("w2", einit(nn.initializers.lecun_normal()),
                        (e_local, self.ffn_hidden_size, h),
                        self.param_dtype)

        cap = _capacity(t, e, self.capacity_factor)
        logits = x.astype(jnp.float32) @ wg
        use_jitter = self.router_jitter_eps > 0.0 and not deterministic
        jrng = self.make_rng("router") if use_jitter else None
        dispatch, combine, aux = top2_gating(
            logits, cap, jitter_rng=jrng,
            jitter_eps=self.router_jitter_eps if use_jitter else 0.0)

        # (T, E, C) x (T, H) -> (E, C, H)
        xe = jnp.einsum("tec,th->ech", dispatch.astype(dt), x.astype(dt))
        if ep > 1:
            # tokens to their expert's owner: split E into (ep, E/ep)
            # and all_to_all the ep dim over the mesh axis
            xe = xe.reshape(ep, e_local, cap, h)
            xe = jax.lax.all_to_all(xe, self.axis, split_axis=0,
                                    concat_axis=0, tiled=False)
            # (ep, e_local, C, H): dim 0 now enumerates source ranks
            xe = jnp.moveaxis(xe, 0, 1).reshape(e_local, ep * cap, h)
        else:
            xe = xe.reshape(e_local, cap, h)

        he = self.activation(
            jnp.einsum("ech,ehf->ecf", xe, w1.astype(dt)))
        ye = jnp.einsum("ecf,efh->ech", he, w2.astype(dt))

        if ep > 1:
            ye = jnp.moveaxis(ye.reshape(e_local, ep, cap, h), 1, 0)
            ye = jax.lax.all_to_all(ye, self.axis, split_axis=0,
                                    concat_axis=0, tiled=False)
            ye = ye.reshape(e, cap, h)
        out = jnp.einsum("tec,ech->th", combine.astype(jnp.float32),
                         ye.astype(jnp.float32))
        return out.astype(x.dtype), aux


def moe_ref(x, router, w1, w2, capacity, activation=jax.nn.gelu):
    """Dense oracle: same gating, every expert applied to every token,
    output = gate-weighted mixture.  w1 (E, H, F), w2 (E, F, H)."""
    logits = x.astype(jnp.float32) @ router
    dispatch, combine, aux = top2_gating(logits, capacity)
    h = activation(jnp.einsum("th,ehf->tef", x.astype(jnp.float32),
                              w1.astype(jnp.float32)))
    y = jnp.einsum("tef,efh->teh", h, w2.astype(jnp.float32))
    weight = jnp.sum(combine, axis=-1)                 # (T, E)
    return jnp.einsum("te,teh->th", weight, y).astype(x.dtype), aux
