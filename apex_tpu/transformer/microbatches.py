"""Microbatch calculators (reference: apex/transformer/microbatches.py).

Constant and rampup-capable calculators deciding how many microbatches a
global batch splits into, given data-parallel size — pure bookkeeping,
identical math to the reference.
"""

from __future__ import annotations

from typing import List, Optional


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches = None
        self.current_global_batch_size = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        assert global_batch_size % micro_batch_times_dp == 0, (
            f"global batch size ({global_batch_size}) is not divisible by "
            f"micro batch size ({micro_batch_size}) times data parallel "
            f"size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        assert self.num_micro_batches >= 1
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        assert self.micro_batch_times_data_parallel_size > 0
        assert start_batch_size % self.micro_batch_times_data_parallel_size \
            == 0
        diff = global_batch_size - start_batch_size
        assert diff >= 0 and diff % batch_size_increment == 0
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples * (self.global_batch_size -
                                            self.start_batch_size) /
                        self.ramup_samples / self.batch_size_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            self.current_global_batch_size = (
                self.current_global_batch_size //
                self.micro_batch_times_data_parallel_size *
                self.micro_batch_times_data_parallel_size)
            self.current_global_batch_size = max(
                self.current_global_batch_size,
                self.micro_batch_times_data_parallel_size)
        if consistency_check:
            assert self.current_global_batch_size % \
                self.micro_batch_times_data_parallel_size == 0
        self.num_micro_batches = (
            self.current_global_batch_size //
            self.micro_batch_times_data_parallel_size)


def build_num_microbatches_calculator(
        rank: int = 0,
        rampup_batch_size: Optional[List[int]] = None,
        global_batch_size: int = 1,
        micro_batch_size: int = 1,
        data_parallel_size: int = 1) -> NumMicroBatchesCalculator:
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(global_batch_size, micro_batch_size,
                                       data_parallel_size)
    assert len(rampup_batch_size) == 3
    return RampupBatchsizeNumMicroBatches(
        int(rampup_batch_size[0]), int(rampup_batch_size[1]),
        int(rampup_batch_size[2]), global_batch_size, micro_batch_size,
        data_parallel_size)
