"""Microbatch calculators (reference: apex/transformer/microbatches.py).

Constant and rampup-capable calculators deciding how many microbatches a
global batch splits into, given data-parallel size — pure bookkeeping,
identical math to the reference.
"""

from __future__ import annotations

from typing import List, Optional


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches = None
        self.current_global_batch_size = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        assert global_batch_size % micro_batch_times_dp == 0, (
            f"global batch size ({global_batch_size}) is not divisible by "
            f"micro batch size ({micro_batch_size}) times data parallel "
            f"size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        assert self.num_micro_batches >= 1
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        assert self.micro_batch_times_data_parallel_size > 0
        assert start_batch_size % self.micro_batch_times_data_parallel_size \
            == 0
        diff = global_batch_size - start_batch_size
        assert diff >= 0 and diff % batch_size_increment == 0
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples * (self.global_batch_size -
                                            self.start_batch_size) /
                        self.ramup_samples / self.batch_size_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            self.current_global_batch_size = (
                self.current_global_batch_size //
                self.micro_batch_times_data_parallel_size *
                self.micro_batch_times_data_parallel_size)
            self.current_global_batch_size = max(
                self.current_global_batch_size,
                self.micro_batch_times_data_parallel_size)
        if consistency_check:
            assert self.current_global_batch_size % \
                self.micro_batch_times_data_parallel_size == 0
        self.num_micro_batches = (
            self.current_global_batch_size //
            self.micro_batch_times_data_parallel_size)


def build_num_microbatches_calculator(
        rank: int = 0,
        rampup_batch_size: Optional[List[int]] = None,
        global_batch_size: int = 1,
        micro_batch_size: int = 1,
        data_parallel_size: int = 1) -> NumMicroBatchesCalculator:
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(global_batch_size, micro_batch_size,
                                       data_parallel_size)
    assert len(rampup_batch_size) == 3
    return RampupBatchsizeNumMicroBatches(
        int(rampup_batch_size[0]), int(rampup_batch_size[1]),
        int(rampup_batch_size[2]), global_batch_size, micro_batch_size,
        data_parallel_size)


def accumulate_gradients(loss_fn, params, microbatches, *,
                         use_remat: bool = True):
    """Gradient accumulation over stacked microbatches, in one program.

    The reference accumulates microbatch grads imperatively inside its
    pipeline schedules (grads summed into main_grad buffers across the
    1F1B loop); standalone accumulation is the degenerate single-stage
    case.  TPU-native: one ``lax.scan`` over the microbatch axis with a
    running f32 grad sum — XLA keeps ONE grad buffer alive (the fp32
    main_grad behavior of fused_weight_gradient_mlp_cuda) instead of M.

    loss_fn(params, microbatch) -> scalar loss.
    microbatches: pytree whose leaves have a leading microbatch dim M.
    use_remat: rematerialize each microbatch's forward (the usual
    pairing — accumulation exists to cut activation memory).

    Returns (mean_loss, mean_grads) with grads in f32.
    """
    import jax as _jax
    import jax.numpy as _jnp

    # remat must wrap the PRIMAL and be differentiated through —
    # checkpointing the already-differentiated function is a no-op
    fn = _jax.checkpoint(loss_fn) if use_remat else loss_fn
    vg = _jax.value_and_grad(fn)

    leaves = _jax.tree_util.tree_leaves(microbatches)
    if not leaves:
        raise ValueError("accumulate_gradients: empty microbatch pytree")
    m = leaves[0].shape[0]

    def body(carry, mb):
        loss_sum, gsum = carry
        loss, g = vg(params, mb)
        gsum = _jax.tree_util.tree_map(
            lambda a, b: a + b.astype(_jnp.float32), gsum, g)
        return (loss_sum + loss.astype(_jnp.float32), gsum), None

    g0 = _jax.tree_util.tree_map(
        lambda p: _jnp.zeros(p.shape, _jnp.float32), params)
    (loss_sum, gsum), _ = _jax.lax.scan(
        body, (_jnp.float32(0.0), g0), microbatches)
    inv = 1.0 / m
    return loss_sum * inv, _jax.tree_util.tree_map(
        lambda g: g * inv, gsum)
