"""apex_tpu.transformer — Megatron-style parallelism stack (reference:
apex/transformer, SURVEY.md §2.2)."""

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel
from apex_tpu.transformer import pipeline_parallel
from apex_tpu.transformer import functional
from apex_tpu.transformer import amp
from apex_tpu.transformer import moe
from apex_tpu.transformer.enums import (AttnMaskType, AttnType, LayerType,
                                        ModelType)
from apex_tpu.transformer.log_util import (get_transformer_logger,
                                           set_logging_level)
from apex_tpu.transformer.microbatches import build_num_microbatches_calculator

__all__ = [
    "parallel_state", "tensor_parallel", "pipeline_parallel", "functional",
    "amp", "moe",
    "AttnMaskType", "AttnType", "LayerType", "ModelType",
    "get_transformer_logger", "set_logging_level",
    "build_num_microbatches_calculator",
]
