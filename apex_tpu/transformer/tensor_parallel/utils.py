"""Shard-arithmetic helpers (reference:
apex/transformer/tensor_parallel/utils.py)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    assert numerator % denominator == 0, (
        f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x, num_partitions: int):
    """Reference helper: split the last dim into `num_partitions` views."""
    last = divide(x.shape[-1], num_partitions)
    return jnp.split(x, num_partitions, axis=-1)


class VocabUtility:
    """Vocab-range arithmetic for vocab-parallel embeddings/losses."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple[int, int]:
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank,
                                           world_size: int):
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size)
