"""Parallel RNG management + activation checkpointing (reference:
apex/transformer/tensor_parallel/random.py).

The reference keeps a CudaRNGStatesTracker so dropout inside
tensor-parallel regions draws DIFFERENT randomness per tp rank while
everything else stays replicated, and its ``checkpoint`` saves/restores
those states around recomputation.  JAX's key-based RNG makes both
structural: a key folded with the tp rank is the "model-parallel-rng"
state, and ``jax.checkpoint`` replays identical keys on recompute by
construction — no state juggling to get deterministic recomputation.
"""

from __future__ import annotations

import contextlib
from typing import Dict

import jax

from apex_tpu import comm

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


class RNGStatesTracker:
    """API-parity tracker: named base keys; ``fork`` yields a key folded
    with the tp rank (so each rank's dropout decorrelates) and bumps a
    counter so successive forks differ."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}
        self.counters_: Dict[str, int] = {}

    def reset(self):
        self.states_.clear()
        self.counters_.clear()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.key(seed)
        self.counters_[name] = 0

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        key = self.states_[name]
        key = jax.random.fold_in(key, self.counters_[name])
        if comm.axis_is_bound(comm.AXIS_MODEL):
            key = jax.random.fold_in(
                key, jax.lax.axis_index(comm.AXIS_MODEL))
        self.counters_[name] += 1
        yield key


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:
    """Reference name kept for drop-in compatibility."""
    return _RNG_STATE_TRACKER


def model_parallel_cuda_manual_seed(seed: int) -> None:
    """Reference contract: default stream gets `seed`; the
    model-parallel stream gets a rank-offset seed (offsetting is implicit
    here — fork() folds the rank in)."""
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, seed + 2718)


def checkpoint(function, *args, distribute_saved_activations: bool = False,
               **kwargs):
    """Activation checkpointing (reference ``tensor_parallel.checkpoint``).

    jax.checkpoint replays the primal with identical RNG keys, which is
    the whole point of the reference's RNG-state save/restore.
    ``distribute_saved_activations`` (sharding the stashed input over tp
    ranks) is subsumed by XLA's SPMD partitioner, which shards residuals
    according to their producers' shardings.
    """
    del distribute_saved_activations
    return jax.checkpoint(function)(*args, **kwargs)
