"""Tensor/sequence-parallel region mappings (reference:
apex/transformer/tensor_parallel/mappings.py).

The reference implements these as autograd.Functions pairing a forward
collective with its transpose in backward (f/g of the Megatron paper).
Here each is a ``jax.custom_vjp`` over XLA collectives, usable inside
shard_map over the "model" mesh axis:

  copy_to_tensor_model_parallel_region      fwd identity   / bwd psum
  reduce_from_tensor_model_parallel_region  fwd psum       / bwd identity
  scatter_to_tensor_model_parallel_region   fwd split      / bwd all_gather
  gather_from_tensor_model_parallel_region  fwd all_gather / bwd split
  scatter_to_sequence_parallel_region       fwd seq-split  / bwd seq all_gather
  gather_from_sequence_parallel_region      fwd seq all_gather / bwd r-scatter
  reduce_scatter_to_sequence_parallel_region fwd psum_scatter / bwd all_gather

Sequence-parallel mappings operate on axis 0 (the sequence dim in
Megatron's [s, b, h] layout); tensor-parallel scatter/gather operate on
the LAST dim.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu import comm

AXIS = comm.AXIS_MODEL


def _rank(axis):
    return jax.lax.axis_index(axis)


def _world(axis):
    return comm.bound_axis_size(axis)


def _split_along(x, dim, axis):
    """Take this rank's slice of x along `dim` (x is replicated)."""
    world = _world(axis)
    size = x.shape[dim] // world
    idx = _rank(axis) * size
    return jax.lax.dynamic_slice_in_dim(x, idx, size, axis=dim)


def _all_gather_along(x, dim, axis):
    return jax.lax.all_gather(x, axis, axis=dim, tiled=True)


def _reduce_scatter_along(x, dim, axis):
    return jax.lax.psum_scatter(x, axis, scatter_dimension=dim,
                                tiled=True)


# --- tensor-parallel (last-dim) mappings ----------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis=AXIS):
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _, dy):
    return (jax.lax.psum(dy, axis),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis=AXIS):
    return jax.lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _reduce_bwd(axis, _, dy):
    return (dy,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis=AXIS):
    return _split_along(x, x.ndim - 1, axis)


def _scatter_fwd(x, axis):
    return _split_along(x, x.ndim - 1, axis), None


def _scatter_bwd(axis, _, dy):
    return (_all_gather_along(dy, dy.ndim - 1, axis),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis=AXIS):
    return _all_gather_along(x, x.ndim - 1, axis)


def _gather_fwd(x, axis):
    return _all_gather_along(x, x.ndim - 1, axis), None


def _gather_bwd(axis, _, dy):
    return (_split_along(dy, dy.ndim - 1, axis),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# --- sequence-parallel (dim 0) mappings -----------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis=AXIS):
    return _split_along(x, 0, axis)


def _sp_scatter_fwd(x, axis):
    return _split_along(x, 0, axis), None


def _sp_scatter_bwd(axis, _, dy):
    return (_all_gather_along(dy, 0, axis),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, axis=AXIS,
                                         tensor_parallel_output_grad=True):
    return _all_gather_along(x, 0, axis)


def _sp_gather_fwd(x, axis, tensor_parallel_output_grad):
    return _all_gather_along(x, 0, axis), None


def _sp_gather_bwd(axis, tensor_parallel_output_grad, _, dy):
    # column-linear fwd gathers the seq dim; its bwd REDUCE-scatters
    # (grads from all tp ranks are partial sums).  When the consumer is
    # not tensor-parallel, a plain split suffices (reference flag).
    if tensor_parallel_output_grad:
        return (_reduce_scatter_along(dy, 0, axis),)
    return (_split_along(dy, 0, axis),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis=AXIS):
    return _reduce_scatter_along(x, 0, axis)


def _sp_rs_fwd(x, axis):
    return _reduce_scatter_along(x, 0, axis), None


def _sp_rs_bwd(axis, _, dy):
    return (_all_gather_along(dy, 0, axis),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
