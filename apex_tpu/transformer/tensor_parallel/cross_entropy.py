"""Vocab-parallel cross entropy (reference:
apex/transformer/tensor_parallel/cross_entropy.py).

Logits arrive sharded along the vocab dim ((..., V/tp) per rank).  The
stable log-softmax needs two tiny collectives — pmax of the row max and
psum of the exp-sum — plus a psum to fetch the target logit from
whichever rank owns it.  The reference hand-writes the backward
(softmax - one_hot); here jax differentiates through the psums and
produces exactly that, so no custom_vjp is needed.  Label smoothing
matches the reference's later-era kwarg.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.transformer.tensor_parallel.mappings import (
    reduce_from_tensor_model_parallel_region as _reduce)
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility

AXIS = comm.AXIS_MODEL


def _tp_bound(axis) -> bool:
    return comm.axis_is_bound(axis)


def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 label_smoothing: float = 0.0,
                                 axis: str = AXIS):
    """Per-token CE loss from vocab-sharded logits.

    vocab_parallel_logits: (..., V/tp) f32/bf16; target: (...) int ids in
    [0, V).  Returns per-token loss (...) in f32.
    """
    logits = vocab_parallel_logits.astype(jnp.float32)
    v_local = logits.shape[-1]
    synced = _tp_bound(axis)

    if synced:
        tp = comm.bound_axis_size(axis)
        rank = jax.lax.axis_index(axis)
    else:
        tp, rank = 1, 0

    # stable log-sum-exp over the GLOBAL vocab; the shift cancels in the
    # loss, so it is taken out of the grad path (pmax has no JVP rule)
    local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    gmax = jax.lax.pmax(local_max, axis) if synced else local_max
    # NOTE: cross-rank sums use the f/g mapping (fwd psum, bwd identity):
    # the result is consumed identically on every tp rank, so a raw psum
    # would double-count cotangents in backward (the same reason the
    # reference hand-writes these as autograd.Functions).
    shifted = logits - gmax[..., None]
    local_sumexp = jnp.sum(jnp.exp(shifted), axis=-1)
    sumexp = _reduce(local_sumexp, axis) if synced else local_sumexp
    logZ = jnp.log(sumexp)

    # target logit: owned by exactly one rank
    first, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
        v_local, rank, tp)
    local_t = target - first
    in_range = (local_t >= 0) & (local_t < v_local)
    local_t = jnp.where(in_range, local_t, 0)
    tgt_shifted = jnp.take_along_axis(
        shifted, local_t[..., None], axis=-1)[..., 0]
    tgt_shifted = jnp.where(in_range, tgt_shifted, 0.0)
    if synced:
        tgt_shifted = _reduce(tgt_shifted, axis)

    loss = logZ - tgt_shifted

    if label_smoothing > 0.0:
        # smoothed loss: (1-eps)*nll + eps/V * sum_i -log p_i
        vocab = v_local * tp
        eps = label_smoothing
        mean_logprob = jnp.sum(shifted, axis=-1)
        if synced:
            mean_logprob = _reduce(mean_logprob, axis)
        mean_logprob = mean_logprob / vocab - logZ
        loss = (1.0 - eps) * loss - eps * mean_logprob

    return loss


def cross_entropy_ref(logits, target, label_smoothing: float = 0.0):
    """Full-vocab oracle for tests."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
    if label_smoothing > 0.0:
        eps = label_smoothing
        nll = (1 - eps) * nll - eps * jnp.mean(logp, axis=-1)
    return nll
