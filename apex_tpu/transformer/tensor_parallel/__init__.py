"""apex_tpu.transformer.tensor_parallel (reference:
apex/transformer/tensor_parallel)."""

from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    cross_entropy_ref,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.random import (
    RNGStatesTracker,
    checkpoint,
    get_cuda_rng_tracker,
    model_parallel_cuda_manual_seed,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data
from apex_tpu.transformer.tensor_parallel.utils import (
    VocabUtility,
    divide,
    ensure_divisibility,
    split_tensor_along_last_dim,
)

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "copy_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "cross_entropy_ref", "vocab_parallel_cross_entropy",
    "RNGStatesTracker", "checkpoint", "get_cuda_rng_tracker",
    "model_parallel_cuda_manual_seed",
    "broadcast_data",
    "VocabUtility", "divide", "ensure_divisibility",
    "split_tensor_along_last_dim",
]
