"""Data broadcast utilities (reference:
apex/transformer/tensor_parallel/data.py).

The reference broadcasts each batch from tp-rank-0 so all tensor-parallel
ranks see identical data.  Under single-controller SPMD every rank
already traces the same host values, so broadcast_data reduces to
validation + dtype checking + device_put with a replicated sharding.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from apex_tpu import comm


def _build_key_size_numel(keys: List[str], data: Dict[str, jax.Array]):
    key_size = {}
    key_numel = {}
    total = 0
    for k in keys:
        key_size[k] = data[k].shape
        key_numel[k] = int(data[k].size)
        total += key_numel[k]
    return key_size, key_numel, total


def broadcast_data(keys: List[str], data: Dict[str, jax.Array], datatype
                   ) -> Dict[str, jax.Array]:
    for k in keys:
        if data[k].dtype != datatype:
            raise ValueError(
                f"{k} has dtype {data[k].dtype}, expected {datatype}")
    if not comm.is_initialized():
        return {k: jnp.asarray(data[k]) for k in keys}
    sharding = comm.replicated_sharding()
    return {k: jax.device_put(jnp.asarray(data[k]), sharding) for k in keys}
