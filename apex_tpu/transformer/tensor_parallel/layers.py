"""Tensor-parallel layers (reference:
apex/transformer/tensor_parallel/layers.py).

ColumnParallelLinear / RowParallelLinear / VocabParallelEmbedding as flax
modules holding PER-SHARD parameters, written to run inside shard_map
over the "model" mesh axis (the Megatron per-rank view, which is also
what XLA compiles best: local matmuls + explicit collectives on ICI).
With tensor_model_parallel_size 1 they degrade to plain layers and run
anywhere.

Sequence parallelism (reference ``sequence_parallel_enabled``): column
fwd all-gathers the seq dim before the matmul, row fwd reduce-scatters
after — exactly the reference's substitution of all-reduce by
all_gather + reduce_scatter (SURVEY.md §2.2).

Weight init: each rank initializes its own shard with the master RNG
folded by tensor-parallel rank (see random.py), the TPU analog of the
reference's per-rank CUDA RNG tracker.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.transformer.tensor_parallel.utils import (VocabUtility,
                                                        divide)

AXIS = comm.AXIS_MODEL


def _tp_world() -> int:
    return comm.model_parallel_size()


def _fold_tp_rank(key):
    if comm.axis_is_bound(AXIS):
        return jax.random.fold_in(key, jax.lax.axis_index(AXIS))
    return key


def _sharded_init(base_init: Callable):
    """Decorrelate per-rank shards by folding the tp rank into the rng."""
    def init(key, shape, dtype=jnp.float32):
        return base_init(_fold_tp_rank(key), shape, dtype)
    return init


def _local_matmul(x, w, fp8):
    """The per-rank local matmul shared by Column/RowParallelLinear:
    plain bf16/f32 dot, or — with an ``fp8``
    :class:`~apex_tpu.amp.fp8.Fp8Policy` — the e4m3-forward /
    e5m2-backward quantized path (``fused_dense.fp8_matmul``); the
    surrounding tensor-parallel collectives are unchanged (reductions
    always run on the DEQUANTIZED f32/compute-dtype output — never on
    raw fp8 values, the APX204 discipline)."""
    if fp8 is not None:
        from apex_tpu.fused_dense import fp8_matmul
        return fp8_matmul(x, w, policy=fp8)
    return jnp.dot(x, w, preferred_element_type=jnp.float32
                   ).astype(x.dtype)


class ColumnParallelLinear(nn.Module):
    """Y = X A + b with A sharded along its OUTPUT dim.

    Per-shard weight: (in, out/tp).  gather_output=True restores the full
    output (reference default); False leaves it model-parallel for a
    following RowParallelLinear.
    """
    input_size: int
    output_size: int
    bias: bool = True
    gather_output: bool = True
    init_method: Callable = nn.initializers.lecun_normal()
    stride: int = 1
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    params_dtype: jnp.dtype = jnp.float32
    compute_dtype: Optional[jnp.dtype] = None
    fp8: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        tp = _tp_world()
        out_local = divide(self.output_size, tp)
        w = self.param("weight", _sharded_init(self.init_method),
                       (self.input_size, out_local), self.params_dtype)
        b = (self.param("bias", nn.initializers.zeros, (out_local,),
                        self.params_dtype) if self.bias else None)
        if self.sequence_parallel_enabled:
            # x: (s/tp, b, in) -> gather full sequence
            x = mappings.gather_from_sequence_parallel_region(x, AXIS)
        elif tp > 1:
            x = mappings.copy_to_tensor_model_parallel_region(x, AXIS)
        dt = self.compute_dtype or x.dtype
        y = _local_matmul(x.astype(dt), w.astype(dt), self.fp8)
        if b is not None and not self.skip_bias_add:
            y = y + b.astype(dt)
        if self.gather_output and tp > 1:
            assert not self.sequence_parallel_enabled
            y = mappings.gather_from_tensor_model_parallel_region(y, AXIS)
        if self.skip_bias_add:
            return y, b
        return y


class RowParallelLinear(nn.Module):
    """Y = X A + b with A sharded along its INPUT dim.

    Per-shard weight: (in/tp, out).  input_is_parallel=True consumes the
    un-gathered output of a ColumnParallelLinear; the partial products
    are summed with psum (or reduce-scattered over the sequence dim under
    sequence parallelism).  Bias is added AFTER the reduction, once.
    """
    input_size: int
    output_size: int
    bias: bool = True
    input_is_parallel: bool = False
    init_method: Callable = nn.initializers.lecun_normal()
    stride: int = 1
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    params_dtype: jnp.dtype = jnp.float32
    compute_dtype: Optional[jnp.dtype] = None
    fp8: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        tp = _tp_world()
        in_local = divide(self.input_size, tp)
        if self.sequence_parallel_enabled and not self.input_is_parallel:
            raise RuntimeError(
                "To enable `sequence_parallel_enabled`, "
                "`input_is_parallel` must be `True`")
        w = self.param("weight", _sharded_init(self.init_method),
                       (in_local, self.output_size), self.params_dtype)
        b = (self.param("bias", nn.initializers.zeros, (self.output_size,),
                        self.params_dtype) if self.bias else None)
        if not self.input_is_parallel and tp > 1:
            x = mappings.scatter_to_tensor_model_parallel_region(x, AXIS)
        dt = self.compute_dtype or x.dtype
        y = _local_matmul(x.astype(dt), w.astype(dt), self.fp8)
        if tp > 1:
            if self.sequence_parallel_enabled:
                y = mappings.reduce_scatter_to_sequence_parallel_region(
                    y, AXIS)
            else:
                y = mappings.reduce_from_tensor_model_parallel_region(
                    y, AXIS)
        if b is not None and self.sequence_parallel_enabled and tp > 1:
            # the bias (added here or by a skip_bias_add caller) lands
            # on a SEQUENCE-SHARDED y: its grad is a local-shard sum,
            # so sync like the SP layernorm params (fwd identity / bwd
            # psum) — on BOTH return paths
            b = mappings.copy_to_tensor_model_parallel_region(b, AXIS)
        if self.skip_bias_add:
            return y, b
        if b is not None:
            y = y + b.astype(dt)
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding table sharded along the VOCAB dim.

    Each rank holds rows [rank*V/tp, (rank+1)*V/tp); out-of-range token
    lookups contribute zeros and the psum assembles the full embedding —
    the reference's masked-lookup + all-reduce."""
    num_embeddings: int
    embedding_dim: int
    init_method: Callable = nn.initializers.normal(stddev=0.02)
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        tp = _tp_world()
        v_local = divide(self.num_embeddings, tp)
        w = self.param("weight", _sharded_init(self.init_method),
                       (v_local, self.embedding_dim), self.params_dtype)
        if tp == 1:
            return jnp.take(w, ids, axis=0)
        rank = jax.lax.axis_index(AXIS)
        first, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
            v_local, rank, tp)
        local_ids = ids - first
        in_range = (local_ids >= 0) & (local_ids < v_local)
        local_ids = jnp.where(in_range, local_ids, 0)
        emb = jnp.take(w, local_ids, axis=0)
        emb = jnp.where(in_range[..., None], emb, 0.0)
        return mappings.reduce_from_tensor_model_parallel_region(emb, AXIS)
