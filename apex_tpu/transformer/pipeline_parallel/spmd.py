"""SPMD collective pipeline over the "pipe" mesh axis — the TPU-native
replacement for NCCL-p2p pipelining (reference:
apex/transformer/pipeline_parallel/*, SURVEY.md §2.5 "PP").

Design: every pipeline stage lives on its own slice of the mesh's "pipe"
axis and runs the SAME program (SPMD).  One ``lax.scan`` steps the
pipeline clock: each tick, every stage applies its layer chunk to its
current activation, then activations rotate one hop along the ring with
``lax.ppermute`` (ICI-neighbor traffic, which XLA overlaps with the next
tick's compute).  A T = M + L - 1 tick scan drains M microbatches
through L stages (GPipe-style fill/drain); jax autodiff through the scan
+ ppermute yields the pipelined backward automatically (the transpose of
ppermute is the reverse rotation), so fwd+bwd compile into ONE XLA
program — no host round-trips, no schedule interpreter.

Use inside shard_map over a mesh with a "pipe" axis; params are the
stage-local chunk (sharded on "pipe" by the caller's in_specs).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from apex_tpu import comm

Pytree = Any


def spmd_pipeline(stage_fn: Callable,
                  params_local: Pytree,
                  microbatches: jax.Array,
                  *, axis: str = comm.AXIS_PIPE) -> jax.Array:
    """Run microbatches through the stage pipeline; returns last-stage
    outputs, replicated across the pipe axis.

    stage_fn(params_local, x) -> y     (same shapes for x and y)
    microbatches: (M, mb, ...) — the caller provides the SAME stacked
    array on every stage (replicated on "pipe"); only stage 0 reads it.

    Returns (M, mb, ...) outputs of the LAST stage (zeros elsewhere are
    masked out and psum-broadcast so every stage holds the result).
    """
    L = comm.bound_axis_size(axis)
    stage = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + L - 1
    mb_shape = microbatches.shape[1:]

    state0 = jnp.zeros(mb_shape, microbatches.dtype)
    ybuf0 = jnp.zeros((M,) + mb_shape, microbatches.dtype)
    perm = [(i, (i + 1) % L) for i in range(L)]

    def tick(carry, t):
        state, ybuf = carry
        # stage 0 ingests microbatch t (or junk past the end, masked off)
        mb_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb_t, state)
        y = stage_fn(params_local, x)
        # last stage collects microbatch t-(L-1) at tick t
        out_idx = t - (L - 1)
        collect = (stage == L - 1) & (out_idx >= 0)
        ybuf = jax.lax.dynamic_update_index_in_dim(
            ybuf,
            jnp.where(collect, y, jax.lax.dynamic_index_in_dim(
                ybuf, jnp.maximum(out_idx, 0), axis=0, keepdims=False)),
            jnp.maximum(out_idx, 0), axis=0)
        # rotate activations one hop down the ring
        state = jax.lax.ppermute(y, axis, perm)
        return (state, ybuf), None

    (state, ybuf), _ = jax.lax.scan(tick, (state0, ybuf0),
                                    jnp.arange(T))
    # Broadcast the last stage's collected outputs to every stage with
    # the f/g mapping (fwd psum, bwd identity): the result is consumed
    # identically on all pipe ranks, so a raw psum would multiply
    # cotangents by the pipe world size in backward.
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as _reduce)
    mask = (stage == L - 1).astype(ybuf.dtype)
    return _reduce(ybuf * mask, axis)


def spmd_pipeline_interleaved(stage_fn: Callable,
                              params_chunks: Pytree,
                              microbatches: jax.Array,
                              *, axis: str = comm.AXIS_PIPE
                              ) -> jax.Array:
    """Interleaved virtual stages under SPMD (reference:
    _forward_backward_pipelining_with_interleaving's model-chunk
    placement, SURVEY.md §2.2; VERDICT r2 #7).

    ``params_chunks``: the stage-local stack of V model chunks (leading
    dim V on every leaf).  Global chunk ``g = c * P + s`` lives on
    physical stage ``s = g mod P`` at local slot ``c = g div P`` — the
    same placement as the host interleaved schedule in schedules.py —
    so an activation at hop ``h`` is at stage ``h mod P`` applying local
    slot ``h div P``.  The ring rotation realizes the chunk traversal
    for free: after P hops an activation wraps back to stage 0 for its
    next chunk (a "circular" pipeline).

    Scheduling: wrapped activations take priority at stage 0; a new
    microbatch is ingested only when no live activation arrives.  This
    greedy rule reproduces the grouped circular schedule (groups of P
    microbatches cycle V rounds before the next group enters) and keeps
    exactly one live activation per stage per tick.  The interleaving
    cuts the fill/drain bubble per microbatch group from (P-1)·t_stage
    to (P-1)·t_chunk = (P-1)/V·t_stage, the reference's motivation for
    virtual stages.

    Differentiable (jax autodiff through the scan, GPipe-style memory);
    returns (M, mb, ...) last-chunk outputs replicated on the pipe axis,
    like ``spmd_pipeline``.
    """
    L = comm.bound_axis_size(axis)
    stage = jax.lax.axis_index(axis)
    from apex_tpu.transformer.pipeline_parallel.interleaved_1f1b import (
        chunk_count)
    V = chunk_count(params_chunks)
    PV = L * V
    M = microbatches.shape[0]
    G = -(-M // L)                        # microbatch groups of size P
    T = (G - 1) * V * L + (L - 1) + PV    # last completion tick bound
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    perm = [(i, (i + 1) % L) for i in range(L)]
    i32 = jnp.int32

    x0 = jnp.zeros(mb_shape, dtype)
    ybuf0 = jnp.zeros((M,) + mb_shape, dtype)
    carry0 = (x0, i32(0), i32(0), i32(0), i32(0), ybuf0)

    def tick(carry, t):
        x, h, mb, valid, n_in, ybuf = carry
        # stage 0 ingests microbatch n_in iff no live wrap arrived
        can_in = (stage == 0) & (valid == 0) & (n_in < M)
        mb_new = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(n_in, 0, M - 1), axis=0,
            keepdims=False)
        x = jnp.where(can_in, mb_new, x)
        h = jnp.where(can_in, 0, h)
        mb = jnp.where(can_in, n_in, mb)
        valid = valid | can_in.astype(i32)
        n_in = n_in + can_in.astype(i32)
        # apply this hop's local chunk (h div P)
        slot = jnp.clip(h // L, 0, V - 1)
        p_chunk = jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(
                p, slot, axis=0, keepdims=False), params_chunks)
        y = stage_fn(p_chunk, x)
        h_out = h + 1
        # an activation finishes after chunk PV-1, always at stage P-1
        done = (valid == 1) & (h_out == PV) & (stage == L - 1)
        mbi = jnp.clip(mb, 0, M - 1)
        old = jax.lax.dynamic_index_in_dim(ybuf, mbi, axis=0,
                                           keepdims=False)
        ybuf = jax.lax.dynamic_update_index_in_dim(
            ybuf, jnp.where(done, y, old), mbi, axis=0)
        # rotate (activation + metadata) one hop down the ring
        send_valid = valid * (1 - done.astype(i32))
        x_n = jax.lax.ppermute(y, axis, perm)
        h_n = jax.lax.ppermute(h_out, axis, perm)
        mb_n = jax.lax.ppermute(mb, axis, perm)
        valid_n = jax.lax.ppermute(send_valid, axis, perm)
        return (x_n, h_n, mb_n, valid_n, n_in, ybuf), None

    (_, _, _, _, _, ybuf), _ = jax.lax.scan(tick, carry0, jnp.arange(T))
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as _reduce)
    mask = (stage == L - 1).astype(ybuf.dtype)
    return _reduce(ybuf * mask, axis)


def spmd_pipeline_loss(stage_fn: Callable, loss_fn: Callable,
                       params_local: Pytree,
                       microbatches: jax.Array,
                       targets: jax.Array,
                       *, axis: str = comm.AXIS_PIPE):
    """Mean loss over microbatches of a pipelined model.

    loss_fn(y, target_mb) -> scalar.  Differentiable wrt params_local:
    jax.grad of this function yields each stage's local grads (the
    pipelined backward)."""
    y = spmd_pipeline(stage_fn, params_local, microbatches, axis=axis)
    losses = jax.vmap(loss_fn)(y, targets)
    return jnp.mean(losses)


def spmd_pipeline_1f1b(stage_fn: Callable, loss_fn: Callable,
                       params_local: Pytree,
                       microbatches: jax.Array,
                       targets: jax.Array,
                       *, axis: str = comm.AXIS_PIPE):
    """One-forward-one-backward SPMD pipeline: returns
    (mean_loss, stage-local grads) in ONE compiled scan.

    The GPipe path above leans on jax autodiff of the forward scan, so
    its saved residuals grow with the microbatch count M.  This variant
    writes the 1F1B schedule out explicitly — each tick every stage runs
    one forward AND one backward (vjp with forward recomputation, the
    1F1B + activation-remat combination) with cotangents rotating up the
    ring — so the live activation window is a circular buffer of depth
    2L-1, INDEPENDENT of M (reference bubble/memory profile:
    apex/transformer/pipeline_parallel/schedules/
    fwd_bwd_pipelining_without_interleaving.py; VERDICT r1 #5).

    Timing: stage s forwards microbatch i at tick s+i and backwards it
    at tick 2(L-1)-s+i; the last stage seeds its own cotangent from
    loss_fn's gradient in the same tick as the forward, which is exactly
    the reference's "last stage turns straight around" steady state.

    Not itself differentiable (it IS the backward); use in place of
    jax.grad(spmd_pipeline_loss).
    """
    L = comm.bound_axis_size(axis)
    stage = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + 2 * (L - 1)
    DB = max(2 * L - 1, 1)               # circular activation buffer
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    perm_down = [(i, (i + 1) % L) for i in range(L)]
    perm_up = [(i, (i - 1) % L) for i in range(L)]

    state0 = jnp.zeros(mb_shape, dtype)
    cot0 = jnp.zeros(mb_shape, dtype)
    xbuf0 = jnp.zeros((DB,) + mb_shape, dtype)
    g0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p),
                                params_local)

    def tick(carry, t):
        state, cot_in, xbuf, gacc, loss_acc = carry

        # ---- forward half: stage s runs microbatch f = t - s ----
        f = t - stage
        f_ok = (f >= 0) & (f < M)
        mb_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(f, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb_t, state)
        y = stage_fn(params_local, x)
        # save the stage input for the backward's recompute (masked so
        # junk ticks never clobber a live slot)
        slot = jnp.mod(t, DB)
        old = jax.lax.dynamic_index_in_dim(xbuf, slot, axis=0,
                                           keepdims=False)
        xbuf = jax.lax.dynamic_update_index_in_dim(
            xbuf, jnp.where(f_ok, x, old), slot, axis=0)
        state_next = jax.lax.ppermute(y, axis, perm_down)

        # ---- backward half: stage s backwards microbatch b ----
        b = t - (2 * (L - 1) - stage)
        b_ok = (b >= 0) & (b < M)
        tf = t - 2 * (L - 1 - stage)          # that microbatch's fwd tick
        xb = jax.lax.dynamic_index_in_dim(
            xbuf, jnp.mod(tf, DB), axis=0, keepdims=False)

        def fwd_for_vjp(p, xx):
            return stage_fn(p, xx)

        yb, vjp_fn = jax.vjp(fwd_for_vjp, params_local, xb)
        # cotangent of this stage's output: the loss gradient on the
        # last stage (same-tick turnaround), the neighbor's rotated
        # input-cotangent elsewhere
        tgt_b = jax.lax.dynamic_index_in_dim(
            targets, jnp.clip(b, 0, M - 1), axis=0, keepdims=False)
        loss_b, gy_loss = jax.value_and_grad(
            lambda yy: loss_fn(yy, tgt_b))(yb)
        cot_y = jnp.where(stage == L - 1, gy_loss.astype(dtype), cot_in)
        gp, gx = vjp_fn(cot_y)
        gacc = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(b_ok, g, 0.0).astype(acc.dtype),
            gacc, gp)
        loss_acc = loss_acc + jnp.where(
            b_ok & (stage == L - 1), loss_b, 0.0)
        cot_next = jax.lax.ppermute(
            jnp.where(b_ok, gx, jnp.zeros_like(gx)), axis, perm_up)

        return (state_next, cot_next, xbuf, gacc, loss_acc), None

    (_, _, _, gacc, loss_acc), _ = jax.lax.scan(
        tick, (state0, cot0, xbuf0, g0, jnp.float32(0.0)),
        jnp.arange(T))

    # mean over microbatches; grads scale the same way.  Broadcast the
    # last stage's loss with the f/g mapping (fwd psum, bwd identity).
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as _reduce)
    loss = _reduce(loss_acc, axis) / M
    grads = jax.tree_util.tree_map(lambda g: g / M, gacc)
    return loss, grads


# ---------------------------------------------------------------------
# Differentiable 1F1B: spmd_pipeline drop-in with the production
# schedule as its BACKWARD (VERDICT r2 #5).
# ---------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _pipeline_1f1b_apply(stage_fn, axis, params_local, microbatches):
    return spmd_pipeline(stage_fn, params_local, microbatches, axis=axis)


def _pipeline_1f1b_apply_fwd(stage_fn, axis, params_local, microbatches):
    out = spmd_pipeline(stage_fn, params_local, microbatches, axis=axis)
    # residuals are the INPUTS only — the backward recomputes stage
    # activations inside its own interleaved scan (O(L) live window),
    # never storing per-microbatch-per-stage activations
    return out, (params_local, microbatches)


def _pipeline_1f1b_apply_bwd(stage_fn, axis, res, ct):
    params_local, microbatches = res
    L = comm.bound_axis_size(axis)
    stage = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + 2 * (L - 1)
    DB = max(2 * L - 1, 1)
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    perm_down = [(i, (i + 1) % L) for i in range(L)]
    perm_up = [(i, (i - 1) % L) for i in range(L)]

    state0 = jnp.zeros(mb_shape, dtype)
    cot0 = jnp.zeros(mb_shape, dtype)
    xbuf0 = jnp.zeros((DB,) + mb_shape, dtype)
    g0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p),
                                params_local)
    gub0 = jnp.zeros((M,) + mb_shape, dtype)

    def tick(carry, t):
        state, cot_in, xbuf, gacc, gub = carry

        # ---- forward half (recompute): stage s forwards f = t - s ----
        f = t - stage
        f_ok = (f >= 0) & (f < M)
        mb_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(f, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb_t, state)
        y = stage_fn(params_local, x)
        slot = jnp.mod(t, DB)
        old = jax.lax.dynamic_index_in_dim(xbuf, slot, axis=0,
                                           keepdims=False)
        xbuf = jax.lax.dynamic_update_index_in_dim(
            xbuf, jnp.where(f_ok, x, old), slot, axis=0)
        state_next = jax.lax.ppermute(y, axis, perm_down)

        # ---- backward half: stage s backwards b, seeded from ct ----
        b = t - (2 * (L - 1) - stage)
        b_ok = (b >= 0) & (b < M)
        tf = t - 2 * (L - 1 - stage)
        xb = jax.lax.dynamic_index_in_dim(
            xbuf, jnp.mod(tf, DB), axis=0, keepdims=False)
        _, vjp_fn = jax.vjp(lambda p, xx: stage_fn(p, xx),
                            params_local, xb)
        ct_b = jax.lax.dynamic_index_in_dim(
            ct, jnp.clip(b, 0, M - 1), axis=0, keepdims=False)
        cot_y = jnp.where(stage == L - 1, ct_b.astype(dtype), cot_in)
        gp, gx = vjp_fn(cot_y)
        gacc = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(b_ok, g, 0.0).astype(acc.dtype),
            gacc, gp)
        # stage 0's input-cotangent is d/d microbatches[b] (flows to
        # whatever produced the microbatch stream, e.g. the embedding)
        bi = jnp.clip(b, 0, M - 1)
        old_g = jax.lax.dynamic_index_in_dim(gub, bi, axis=0,
                                             keepdims=False)
        gub = jax.lax.dynamic_update_index_in_dim(
            gub, jnp.where(b_ok & (stage == 0), gx.astype(dtype), old_g),
            bi, axis=0)
        cot_next = jax.lax.ppermute(
            jnp.where(b_ok, gx, jnp.zeros_like(gx)), axis, perm_up)
        return (state_next, cot_next, xbuf, gacc, gub), None

    (_, _, _, gacc, gub), _ = jax.lax.scan(
        tick, (state0, cot0, xbuf0, g0, gub0), jnp.arange(T))
    return gacc, gub


_pipeline_1f1b_apply.defvjp(_pipeline_1f1b_apply_fwd,
                            _pipeline_1f1b_apply_bwd)


# re-export: the interleaved 1F1B lives in its own module (the static
# scheduler is sizeable) but belongs to this family's namespace
from apex_tpu.transformer.pipeline_parallel.interleaved_1f1b import (  # noqa: E402,E501
    spmd_pipeline_interleaved_1f1b,
    spmd_pipeline_interleaved_1f1b_apply,
)


def spmd_pipeline_1f1b_apply(stage_fn: Callable,
                             params_local: Pytree,
                             microbatches: jax.Array,
                             *, axis: str = comm.AXIS_PIPE) -> jax.Array:
    """``spmd_pipeline`` drop-in whose BACKWARD is the explicit 1F1B
    schedule with forward recomputation.

    Forward: the same GPipe-style fill/drain scan as ``spmd_pipeline``
    (same outputs, replicated across the pipe axis).  Backward: instead
    of jax autodiff through the forward scan (whose saved residuals
    grow O(M) per stage), a custom VJP re-runs an interleaved
    one-forward-one-backward scan — each tick every stage recomputes
    one microbatch's forward from its saved stage INPUT (circular
    buffer of depth 2L-1, independent of M) and backwards another, with
    cotangents rotating up the ring.  This is the production memory
    profile of the reference's 1F1B + activation-checkpointing
    combination (apex/transformer/pipeline_parallel/schedules/
    fwd_bwd_pipelining_without_interleaving.py with
    tensor_parallel.random.checkpoint), and unlike
    ``spmd_pipeline_1f1b`` it is COMPOSABLE: ops before the pipeline
    (embedding) and after it (final norm, head, loss) differentiate
    through, including the input-cotangent path d loss / d microbatches.
    """
    return _pipeline_1f1b_apply(stage_fn, axis, params_local,
                                microbatches)
