"""SPMD collective pipeline over the "pipe" mesh axis — the TPU-native
replacement for NCCL-p2p pipelining (reference:
apex/transformer/pipeline_parallel/*, SURVEY.md §2.5 "PP").

Design: every pipeline stage lives on its own slice of the mesh's "pipe"
axis and runs the SAME program (SPMD).  One ``lax.scan`` steps the
pipeline clock: each tick, every stage applies its layer chunk to its
current activation, then activations rotate one hop along the ring with
``lax.ppermute`` (ICI-neighbor traffic, which XLA overlaps with the next
tick's compute).  A T = M + L - 1 tick scan drains M microbatches
through L stages (GPipe-style fill/drain); jax autodiff through the scan
+ ppermute yields the pipelined backward automatically (the transpose of
ppermute is the reverse rotation), so fwd+bwd compile into ONE XLA
program — no host round-trips, no schedule interpreter.

Use inside shard_map over a mesh with a "pipe" axis; params are the
stage-local chunk (sharded on "pipe" by the caller's in_specs).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from apex_tpu import comm

Pytree = Any


def spmd_pipeline(stage_fn: Callable,
                  params_local: Pytree,
                  microbatches: jax.Array,
                  *, axis: str = comm.AXIS_PIPE) -> jax.Array:
    """Run microbatches through the stage pipeline; returns last-stage
    outputs, replicated across the pipe axis.

    stage_fn(params_local, x) -> y     (same shapes for x and y)
    microbatches: (M, mb, ...) — the caller provides the SAME stacked
    array on every stage (replicated on "pipe"); only stage 0 reads it.

    Returns (M, mb, ...) outputs of the LAST stage (zeros elsewhere are
    masked out and psum-broadcast so every stage holds the result).
    """
    L = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + L - 1
    mb_shape = microbatches.shape[1:]

    state0 = jnp.zeros(mb_shape, microbatches.dtype)
    ybuf0 = jnp.zeros((M,) + mb_shape, microbatches.dtype)
    perm = [(i, (i + 1) % L) for i in range(L)]

    def tick(carry, t):
        state, ybuf = carry
        # stage 0 ingests microbatch t (or junk past the end, masked off)
        mb_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb_t, state)
        y = stage_fn(params_local, x)
        # last stage collects microbatch t-(L-1) at tick t
        out_idx = t - (L - 1)
        collect = (stage == L - 1) & (out_idx >= 0)
        ybuf = jax.lax.dynamic_update_index_in_dim(
            ybuf,
            jnp.where(collect, y, jax.lax.dynamic_index_in_dim(
                ybuf, jnp.maximum(out_idx, 0), axis=0, keepdims=False)),
            jnp.maximum(out_idx, 0), axis=0)
        # rotate activations one hop down the ring
        state = jax.lax.ppermute(y, axis, perm)
        return (state, ybuf), None

    (state, ybuf), _ = jax.lax.scan(tick, (state0, ybuf0),
                                    jnp.arange(T))
    # Broadcast the last stage's collected outputs to every stage with
    # the f/g mapping (fwd psum, bwd identity): the result is consumed
    # identically on all pipe ranks, so a raw psum would multiply
    # cotangents by the pipe world size in backward.
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as _reduce)
    mask = (stage == L - 1).astype(ybuf.dtype)
    return _reduce(ybuf * mask, axis)


def spmd_pipeline_loss(stage_fn: Callable, loss_fn: Callable,
                       params_local: Pytree,
                       microbatches: jax.Array,
                       targets: jax.Array,
                       *, axis: str = comm.AXIS_PIPE):
    """Mean loss over microbatches of a pipelined model.

    loss_fn(y, target_mb) -> scalar.  Differentiable wrt params_local:
    jax.grad of this function yields each stage's local grads (the
    pipelined backward)."""
    y = spmd_pipeline(stage_fn, params_local, microbatches, axis=axis)
    losses = jax.vmap(loss_fn)(y, targets)
    return jnp.mean(losses)


def spmd_pipeline_1f1b(stage_fn: Callable, loss_fn: Callable,
                       params_local: Pytree,
                       microbatches: jax.Array,
                       targets: jax.Array,
                       *, axis: str = comm.AXIS_PIPE):
    """One-forward-one-backward SPMD pipeline: returns
    (mean_loss, stage-local grads) in ONE compiled scan.

    The GPipe path above leans on jax autodiff of the forward scan, so
    its saved residuals grow with the microbatch count M.  This variant
    writes the 1F1B schedule out explicitly — each tick every stage runs
    one forward AND one backward (vjp with forward recomputation, the
    1F1B + activation-remat combination) with cotangents rotating up the
    ring — so the live activation window is a circular buffer of depth
    2L-1, INDEPENDENT of M (reference bubble/memory profile:
    apex/transformer/pipeline_parallel/schedules/
    fwd_bwd_pipelining_without_interleaving.py; VERDICT r1 #5).

    Timing: stage s forwards microbatch i at tick s+i and backwards it
    at tick 2(L-1)-s+i; the last stage seeds its own cotangent from
    loss_fn's gradient in the same tick as the forward, which is exactly
    the reference's "last stage turns straight around" steady state.

    Not itself differentiable (it IS the backward); use in place of
    jax.grad(spmd_pipeline_loss).
    """
    L = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + 2 * (L - 1)
    DB = max(2 * L - 1, 1)               # circular activation buffer
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    perm_down = [(i, (i + 1) % L) for i in range(L)]
    perm_up = [(i, (i - 1) % L) for i in range(L)]

    state0 = jnp.zeros(mb_shape, dtype)
    cot0 = jnp.zeros(mb_shape, dtype)
    xbuf0 = jnp.zeros((DB,) + mb_shape, dtype)
    g0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p),
                                params_local)

    def tick(carry, t):
        state, cot_in, xbuf, gacc, loss_acc = carry

        # ---- forward half: stage s runs microbatch f = t - s ----
        f = t - stage
        f_ok = (f >= 0) & (f < M)
        mb_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(f, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb_t, state)
        y = stage_fn(params_local, x)
        # save the stage input for the backward's recompute (masked so
        # junk ticks never clobber a live slot)
        slot = jnp.mod(t, DB)
        old = jax.lax.dynamic_index_in_dim(xbuf, slot, axis=0,
                                           keepdims=False)
        xbuf = jax.lax.dynamic_update_index_in_dim(
            xbuf, jnp.where(f_ok, x, old), slot, axis=0)
        state_next = jax.lax.ppermute(y, axis, perm_down)

        # ---- backward half: stage s backwards microbatch b ----
        b = t - (2 * (L - 1) - stage)
        b_ok = (b >= 0) & (b < M)
        tf = t - 2 * (L - 1 - stage)          # that microbatch's fwd tick
        xb = jax.lax.dynamic_index_in_dim(
            xbuf, jnp.mod(tf, DB), axis=0, keepdims=False)

        def fwd_for_vjp(p, xx):
            return stage_fn(p, xx)

        yb, vjp_fn = jax.vjp(fwd_for_vjp, params_local, xb)
        # cotangent of this stage's output: the loss gradient on the
        # last stage (same-tick turnaround), the neighbor's rotated
        # input-cotangent elsewhere
        tgt_b = jax.lax.dynamic_index_in_dim(
            targets, jnp.clip(b, 0, M - 1), axis=0, keepdims=False)
        loss_b, gy_loss = jax.value_and_grad(
            lambda yy: loss_fn(yy, tgt_b))(yb)
        cot_y = jnp.where(stage == L - 1, gy_loss.astype(dtype), cot_in)
        gp, gx = vjp_fn(cot_y)
        gacc = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(b_ok, g, 0.0).astype(acc.dtype),
            gacc, gp)
        loss_acc = loss_acc + jnp.where(
            b_ok & (stage == L - 1), loss_b, 0.0)
        cot_next = jax.lax.ppermute(
            jnp.where(b_ok, gx, jnp.zeros_like(gx)), axis, perm_up)

        return (state_next, cot_next, xbuf, gacc, loss_acc), None

    (_, _, _, gacc, loss_acc), _ = jax.lax.scan(
        tick, (state0, cot0, xbuf0, g0, jnp.float32(0.0)),
        jnp.arange(T))

    # mean over microbatches; grads scale the same way.  Broadcast the
    # last stage's loss with the f/g mapping (fwd psum, bwd identity).
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as _reduce)
    loss = _reduce(loss_acc, axis) / M
    grads = jax.tree_util.tree_map(lambda g: g / M, gacc)
    return loss, grads
