"""SPMD collective pipeline over the "pipe" mesh axis — the TPU-native
replacement for NCCL-p2p pipelining (reference:
apex/transformer/pipeline_parallel/*, SURVEY.md §2.5 "PP").

Design: every pipeline stage lives on its own slice of the mesh's "pipe"
axis and runs the SAME program (SPMD).  One ``lax.scan`` steps the
pipeline clock: each tick, every stage applies its layer chunk to its
current activation, then activations rotate one hop along the ring with
``lax.ppermute`` (ICI-neighbor traffic, which XLA overlaps with the next
tick's compute).  A T = M + L - 1 tick scan drains M microbatches
through L stages (GPipe-style fill/drain); jax autodiff through the scan
+ ppermute yields the pipelined backward automatically (the transpose of
ppermute is the reverse rotation), so fwd+bwd compile into ONE XLA
program — no host round-trips, no schedule interpreter.

Use inside shard_map over a mesh with a "pipe" axis; params are the
stage-local chunk (sharded on "pipe" by the caller's in_specs).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from apex_tpu import comm

Pytree = Any


def spmd_pipeline(stage_fn: Callable,
                  params_local: Pytree,
                  microbatches: jax.Array,
                  *, axis: str = comm.AXIS_PIPE) -> jax.Array:
    """Run microbatches through the stage pipeline; returns last-stage
    outputs, replicated across the pipe axis.

    stage_fn(params_local, x) -> y     (same shapes for x and y)
    microbatches: (M, mb, ...) — the caller provides the SAME stacked
    array on every stage (replicated on "pipe"); only stage 0 reads it.

    Returns (M, mb, ...) outputs of the LAST stage (zeros elsewhere are
    masked out and psum-broadcast so every stage holds the result).
    """
    L = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + L - 1
    mb_shape = microbatches.shape[1:]

    state0 = jnp.zeros(mb_shape, microbatches.dtype)
    ybuf0 = jnp.zeros((M,) + mb_shape, microbatches.dtype)
    perm = [(i, (i + 1) % L) for i in range(L)]

    def tick(carry, t):
        state, ybuf = carry
        # stage 0 ingests microbatch t (or junk past the end, masked off)
        mb_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb_t, state)
        y = stage_fn(params_local, x)
        # last stage collects microbatch t-(L-1) at tick t
        out_idx = t - (L - 1)
        collect = (stage == L - 1) & (out_idx >= 0)
        ybuf = jax.lax.dynamic_update_index_in_dim(
            ybuf,
            jnp.where(collect, y, jax.lax.dynamic_index_in_dim(
                ybuf, jnp.maximum(out_idx, 0), axis=0, keepdims=False)),
            jnp.maximum(out_idx, 0), axis=0)
        # rotate activations one hop down the ring
        state = jax.lax.ppermute(y, axis, perm)
        return (state, ybuf), None

    (state, ybuf), _ = jax.lax.scan(tick, (state0, ybuf0),
                                    jnp.arange(T))
    # Broadcast the last stage's collected outputs to every stage with
    # the f/g mapping (fwd psum, bwd identity): the result is consumed
    # identically on all pipe ranks, so a raw psum would multiply
    # cotangents by the pipe world size in backward.
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as _reduce)
    mask = (stage == L - 1).astype(ybuf.dtype)
    return _reduce(ybuf * mask, axis)


def spmd_pipeline_loss(stage_fn: Callable, loss_fn: Callable,
                       params_local: Pytree,
                       microbatches: jax.Array,
                       targets: jax.Array,
                       *, axis: str = comm.AXIS_PIPE):
    """Mean loss over microbatches of a pipelined model.

    loss_fn(y, target_mb) -> scalar.  Differentiable wrt params_local:
    jax.grad of this function yields each stage's local grads (the
    pipelined backward)."""
    y = spmd_pipeline(stage_fn, params_local, microbatches, axis=axis)
    losses = jax.vmap(loss_fn)(y, targets)
    return jnp.mean(losses)
