"""apex_tpu.transformer.pipeline_parallel (reference:
apex/transformer/pipeline_parallel)."""

from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    _forward_backward_pipelining_with_interleaving,
    get_forward_backward_func,
)
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    P2PContext,
)
from apex_tpu.transformer.pipeline_parallel.interleaved_1f1b import (
    spmd_pipeline_interleaved_1f1b,
    spmd_pipeline_interleaved_1f1b_apply,
)
from apex_tpu.transformer.pipeline_parallel.spmd import (
    spmd_pipeline,
    spmd_pipeline_1f1b,
    spmd_pipeline_1f1b_apply,
    spmd_pipeline_interleaved,
    spmd_pipeline_loss,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    get_kth_microbatch,
    get_num_microbatches,
    listify_model,
    setup_microbatch_calculator,
    split_into_microbatches,
    update_num_microbatches,
)

__all__ = [
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "get_forward_backward_func",
    "P2PContext",
    "spmd_pipeline", "spmd_pipeline_1f1b",
    "spmd_pipeline_1f1b_apply", "spmd_pipeline_interleaved",
    "spmd_pipeline_interleaved_1f1b",
    "spmd_pipeline_interleaved_1f1b_apply",
    "spmd_pipeline_loss",
    "get_kth_microbatch", "get_num_microbatches", "listify_model",
    "setup_microbatch_calculator", "split_into_microbatches",
    "update_num_microbatches",
]
