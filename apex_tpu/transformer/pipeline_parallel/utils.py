"""Pipeline utilities (reference:
apex/transformer/pipeline_parallel/utils.py)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.transformer.microbatches import (
    NumMicroBatchesCalculator, build_num_microbatches_calculator)

_GLOBAL_NUM_MICROBATCHES_CALCULATOR: Optional[NumMicroBatchesCalculator] \
    = None


def setup_microbatch_calculator(rank: int = 0,
                                rampup_batch_size=None,
                                global_batch_size: int = 1,
                                micro_batch_size: int = 1,
                                data_parallel_size: int = 1) -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def get_num_microbatches() -> int:
    assert _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    assert _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.\
        get_current_global_batch_size()


def update_num_microbatches(consumed_samples,
                            consistency_check: bool = True) -> None:
    assert _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples,
                                               consistency_check)


def listify_model(model) -> List[Any]:
    return model if isinstance(model, (list, tuple)) else [model]


def get_kth_microbatch(batch, k: int):
    """Slice the k-th microbatch out of a stacked batch pytree."""
    if batch is None:
        return None
    return jax.tree_util.tree_map(lambda x: x[k], batch)


def split_into_microbatches(batch, num_microbatches: int):
    """(B, ...) pytree -> (num_microbatches, B/num, ...)."""
    def split(x):
        b = x.shape[0]
        assert b % num_microbatches == 0
        return x.reshape((num_microbatches, b // num_microbatches)
                         + x.shape[1:])
    return jax.tree_util.tree_map(split, batch)
