"""SPMD interleaved 1F1B: the reference's production schedule
(_forward_backward_pipelining_with_interleaving — virtual model chunks
AND one-forward-one-backward steady state) as ONE compiled scan over
the "pipe" mesh axis.

Design: schedule-as-data.  All of the schedule's notorious index
arithmetic runs in plain Python at trace time
(:func:`build_schedule`): a greedy list-scheduler assigns every
forward/backward work item ``(virtual stage v, microbatch j)`` to a
synchronous tick under the pipeline's dataflow dependencies, with
backwards preferred over forwards (the 1F1B invariant that bounds
in-flight activations).  The result is a set of static integer tables
``[T, P]`` — per tick, per physical stage: which chunk/microbatch to
forward, which to backward, and which statically-colored buffer slots
to write arrivals into and read operands from.  The jax scan body then
does no scheduling at all: it gathers its tick's table row, computes,
scatters, and rotates payloads one hop along the ring
(``ppermute`` down for activations, up for cotangents).

Placement matches the host schedule and ``spmd_pipeline_interleaved``:
global chunk ``v = c*P + s`` lives on physical stage ``s = v mod P``
at local slot ``c = v div P``, so both activation hops and cotangent
hops are always exactly one ring neighbor.

Memory: saved forward inputs (for the recompute-style backward),
arrived activations, and arrived cotangents each live in per-stage
ring buffers whose slots are assigned by interval coloring of the
static lifetimes — the live window tracks the schedule's actual
concurrency (O(P·V)), independent of the microbatch count.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu import comm

Pytree = Any


def chunk_count(params_chunks: Pytree) -> int:
    """Validated leading chunk dim V shared by every leaf (used by both
    interleaved pipelines)."""
    leaves = jax.tree_util.tree_leaves(params_chunks)
    if not leaves:
        raise ValueError("params_chunks must have at least one leaf")
    V = leaves[0].shape[0]
    for lf in leaves:
        if lf.shape[0] != V:
            raise ValueError(
                "every params_chunks leaf needs the same leading "
                f"chunk dim; got {lf.shape[0]} vs {V}")
    return V


# ---------------------------------------------------------------------
# Static scheduling (plain Python/numpy; unit-tested directly)
# ---------------------------------------------------------------------

def _greedy_ticks(P: int, V: int, M: int):
    """Assign every F/B work item a tick.

    Returns (f_tick, b_tick): dicts (v, j) -> tick.  Dependencies:

    - F(v, j) needs F(v-1, j)'s output, which arrives one tick after
      its producer ran (ppermute hop); F(0, j) reads the local
      microbatch stream and is always ready.
    - B(v, j) needs B(v+1, j)'s input-cotangent (one-tick hop); the
      LAST virtual stage seeds its cotangent from the loss in the same
      tick as its own forward (in-tick turnaround, as in the
      non-interleaved 1F1B scan).
    - Each physical stage runs at most one F and one B per tick, and
      same-type items execute in (v-major, then j) dependency order
      per stage automatically via readiness.

    Greedy rule per tick per stage: schedule the oldest READY backward
    if any (1F1B: drain before fill), and independently the oldest
    READY forward — but only while the stage's in-flight count
    (forwarded-not-yet-backwarded items, i.e. saved activations) is
    below ``2·P·V − 1``.  That cap is what makes this 1F1B rather
    than GPipe: the activation window stays O(P·V), independent of the
    microbatch count (for V=1 it reduces to the non-interleaved scan's
    2L−1 circular buffer).

    Because the scan body computes the tick's forward half before its
    backward half (and writes the saved input before the recompute
    read), a forward of the LAST virtual stage assigned this tick can
    seed its loss cotangent and run its backward in the SAME tick — so
    after the forward assignment the backward check is retried once if
    the stage's backward slot is still free (advisor r3: without the
    retry every schedule was one tick longer than the scan supports).
    """
    PV = P * V
    cap = 2 * PV - 1
    f_tick: Dict[Tuple[int, int], int] = {}
    b_tick: Dict[Tuple[int, int], int] = {}
    # Within one chunk, readiness is monotone in j (microbatch j's
    # producer runs after j-1's), so each (stage, chunk) work queue is
    # a FIFO and only its HEAD can be ready: O(V) candidates per stage
    # per tick, O(T·P·V) total.
    f_head = {s: {v: 0 for v in range(s, PV, P)} for s in range(P)}
    b_head = {s: {v: 0 for v in range(s, PV, P)} for s in range(P)}
    remaining = 2 * PV * M
    inflight = {s: 0 for s in range(P)}

    def try_backward(s, t):
        # lowest ready (v, j) — per-chunk heads, ascending v
        nonlocal remaining
        for v in sorted(b_head[s]):
            j = b_head[s][v]
            if j >= M:
                continue
            if v == PV - 1:
                tf = f_tick.get((v, j))
                ready = tf is not None and tf <= t
            else:
                tb = b_tick.get((v + 1, j))
                ready = tb is not None and tb + 1 <= t
            # recompute needs the saved input: fwd ran at <= t
            if ready:
                tf_own = f_tick.get((v, j))
                ready = tf_own is not None and tf_own <= t
            if ready:
                b_tick[(v, j)] = t
                b_head[s][v] = j + 1
                inflight[s] -= 1
                remaining -= 1
                return True
        return False

    def try_forward(s, t):
        # Among ready forwards pick the DEEPEST chunk (highest v):
        # pushing microbatches toward the loss is what unlocks
        # backwards — shallow-first hoarding fills the cap with
        # chunk-0 activations and deadlocks the ring.
        nonlocal remaining
        for v in sorted(f_head[s], reverse=True):
            j = f_head[s][v]
            if j >= M:
                continue
            if v == 0:
                ready = True
            else:
                tp = f_tick.get((v - 1, j))
                ready = tp is not None and tp + 1 <= t
            if ready:
                f_tick[(v, j)] = t
                f_head[s][v] = j + 1
                inflight[s] += 1
                remaining -= 1
                return v
        return None

    t = 0
    limit = 4 * (M * V + 2 * P * V) + 16
    while remaining:
        if t > limit:
            raise RuntimeError(
                f"interleaved-1f1b scheduler did not converge "
                f"(P={P}, V={V}, M={M}, tick {t})")
        for s in range(P):
            # backward first (does not consume the fwd slot)
            did_b = try_backward(s, t)
            # one forward, gated by the in-flight (activation) cap
            fv = try_forward(s, t) if inflight[s] < cap else None
            # same-tick turnaround: the forward just assigned is the
            # last virtual stage, whose backward seeds from the loss —
            # the scan body runs fwd-half before bwd-half, so it can
            # drain in this very tick if the bwd slot is still free
            if not did_b and fv == PV - 1:
                try_backward(s, t)
        t += 1
    return f_tick, b_tick


def _color_intervals(intervals: List[Tuple[int, int]]) -> Tuple[List[int], int]:
    """Greedy interval-graph coloring: [(start, end)] inclusive ->
    (slot per interval, n_slots).  Two intervals may share a slot iff
    they don't overlap."""
    order = sorted(range(len(intervals)), key=lambda i: intervals[i][0])
    free: List[int] = []
    slots = [0] * len(intervals)
    n = 0
    import heapq
    heap: List[Tuple[int, int]] = []
    for i in order:
        s0, e0 = intervals[i]
        while heap and heap[0][0] < s0:
            _, sl = heapq.heappop(heap)
            free.append(sl)
        if free:
            sl = free.pop()
        else:
            sl = n
            n += 1
        slots[i] = sl
        heapq.heappush(heap, (e0, sl))
    return slots, max(n, 1)


def build_schedule(P: int, V: int, M: int):
    """All static tables for the interleaved-1F1B scan.

    Returns a dict of numpy int32 arrays, each ``[T, P]`` unless noted:

      f_ok/f_chunk/f_mb       — this tick's forward work
      b_ok/b_chunk/b_mb       — this tick's backward work
      f_src_slot              — abuf slot holding the fwd input
                                 (-1: read the local microbatch stream)
      a_wr_slot               — abuf slot to store the arriving
                                 activation into (-1: discard)
      x_wr_slot / x_rd_slot   — xbuf slot for the fwd input save /
                                 the bwd recompute read
      c_rd_slot               — cbuf slot holding the bwd cotangent
                                 (-1: seed from the loss in-tick)
      c_wr_slot               — cbuf slot for the arriving cotangent
                                 (-1: discard)
      sizes                   — dict: abuf/xbuf/cbuf slot counts, T
    """
    PV = P * V
    f_tick, b_tick = _greedy_ticks(P, V, M)
    T = 1 + max(max(f_tick.values()), max(b_tick.values()))

    def table(fill=0):
        return np.full((T, P), fill, np.int32)

    f_ok, f_chunk, f_mb = table(), table(), table()
    b_ok, b_chunk, b_mb = table(), table(), table()
    f_src, a_wr = table(-1), table(-1)
    x_wr, x_rd = table(-1), table(-1)
    c_rd, c_wr = table(-1), table(-1)

    # ---- lifetimes -> slots, per physical stage ----
    ab_n = xb_n = cb_n = 1
    for s in range(P):
        # xbuf: fwd input saved at f_tick, read at b_tick (recompute)
        items = [(v, j) for v in range(PV) if v % P == s
                 for j in range(M)]
        x_iv = [(f_tick[it], b_tick[it]) for it in items]
        x_slots, xn = _color_intervals(x_iv)
        xb_n = max(xb_n, xn)
        # abuf: activation for F(v, j), v>0: arrives f_tick[v-1]+1,
        # consumed at f_tick[v]
        a_items = [it for it in items if it[0] > 0]
        a_iv = [(f_tick[(v - 1, j)] + 1, f_tick[(v, j)])
                for (v, j) in a_items]
        a_slots, an = _color_intervals(a_iv) if a_iv else ([], 1)
        ab_n = max(ab_n, an)
        # cbuf: cotangent for B(v, j), v < PV-1: arrives
        # b_tick[v+1]+1, consumed at b_tick[v]
        c_items = [it for it in items if it[0] < PV - 1]
        c_iv = [(b_tick[(v + 1, j)] + 1, b_tick[(v, j)])
                for (v, j) in c_items]
        c_slots, cn = _color_intervals(c_iv) if c_iv else ([], 1)
        cb_n = max(cb_n, cn)

        for idx, (v, j) in enumerate(items):
            tf, tb = f_tick[(v, j)], b_tick[(v, j)]
            f_ok[tf, s], f_chunk[tf, s], f_mb[tf, s] = 1, v // P, j
            b_ok[tb, s], b_chunk[tb, s], b_mb[tb, s] = 1, v // P, j
            x_wr[tf, s] = x_slots[idx]
            x_rd[tb, s] = x_slots[idx]
        for idx, (v, j) in enumerate(a_items):
            arr_t = f_tick[(v - 1, j)] + 1
            a_wr[arr_t, s] = a_slots[idx]
            f_src[f_tick[(v, j)], s] = a_slots[idx]
        for idx, (v, j) in enumerate(c_items):
            arr_t = b_tick[(v + 1, j)] + 1
            c_wr[arr_t, s] = c_slots[idx]
            c_rd[b_tick[(v, j)], s] = c_slots[idx]

    return {
        "f_ok": f_ok, "f_chunk": f_chunk, "f_mb": f_mb,
        "b_ok": b_ok, "b_chunk": b_chunk, "b_mb": b_mb,
        "f_src_slot": f_src, "a_wr_slot": a_wr,
        "x_wr_slot": x_wr, "x_rd_slot": x_rd,
        "c_rd_slot": c_rd, "c_wr_slot": c_wr,
        "sizes": {"abuf": ab_n, "xbuf": xb_n, "cbuf": cb_n, "T": T},
        "_f_tick": f_tick, "_b_tick": b_tick,     # for tests
    }


# ---------------------------------------------------------------------
# The scan (SPMD; use inside shard_map over the pipe axis)
# ---------------------------------------------------------------------

def _interleaved_scan(stage_fn: Callable, seed_fn: Callable,
                      params_chunks: Pytree,
                      microbatches: jax.Array,
                      axis: str, collect_gub: bool):
    """Shared interleaved-1F1B scan.  ``seed_fn(yb, bj) ->
    (cotangent, loss_contrib)`` provides the last virtual stage's
    cotangent (from a loss, or from a downstream output-cotangent
    slice).  Returns (gacc, loss_acc, gub) — gub is the
    d/d microbatches buffer (zeros unless collect_gub)."""
    L = comm.bound_axis_size(axis)
    stage = jax.lax.axis_index(axis)
    V = chunk_count(params_chunks)
    M = microbatches.shape[0]
    sched = build_schedule(L, V, M)
    sizes = sched["sizes"]
    T = sizes["T"]
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    # tables as device arrays [T, P]; each rank slices its own column
    tbl = {k: jnp.asarray(v) for k, v in sched.items()
           if not k.startswith("_") and k != "sizes"}

    perm_down = [(i, (i + 1) % L) for i in range(L)]
    perm_up = [(i, (i - 1) % L) for i in range(L)]
    i32 = jnp.int32

    abuf0 = jnp.zeros((sizes["abuf"],) + mb_shape, dtype)
    xbuf0 = jnp.zeros((sizes["xbuf"],) + mb_shape, dtype)
    cbuf0 = jnp.zeros((sizes["cbuf"],) + mb_shape, dtype)
    g0 = jax.tree_util.tree_map(jnp.zeros_like, params_chunks)
    y0 = jnp.zeros(mb_shape, dtype)

    def col(name, t):
        row = jax.lax.dynamic_index_in_dim(tbl[name], t, axis=0,
                                           keepdims=False)
        return jax.lax.dynamic_index_in_dim(row, stage, axis=0,
                                            keepdims=False)

    def buf_write(buf, slot, val):
        """Store val at slot (slot<0: keep old)."""
        sl = jnp.clip(slot, 0, buf.shape[0] - 1)
        old = jax.lax.dynamic_index_in_dim(buf, sl, axis=0,
                                           keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            buf, jnp.where(slot >= 0, val, old), sl, axis=0)

    def buf_read(buf, slot):
        return jax.lax.dynamic_index_in_dim(
            buf, jnp.clip(slot, 0, buf.shape[0] - 1), axis=0,
            keepdims=False)

    def chunk_params(c):
        return jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(
                p, jnp.clip(c, 0, V - 1), axis=0, keepdims=False),
            params_chunks)

    def tick(carry, t):
        # gub (O(M)) rides the carry ONLY when the caller wants the
        # d/d microbatches path — the loss variant keeps the stated
        # O(P*V) memory contract without leaning on XLA DCE
        if collect_gub:
            y_in, gx_in, abuf, xbuf, cbuf, gacc, loss_acc, gub = carry
        else:
            y_in, gx_in, abuf, xbuf, cbuf, gacc, loss_acc = carry
            gub = None

        # ---- arrivals land in their statically-colored slots ----
        abuf = buf_write(abuf, col("a_wr_slot", t), y_in)
        cbuf = buf_write(cbuf, col("c_wr_slot", t), gx_in)

        # ---- forward half ----
        f_ok = col("f_ok", t) == 1
        fc = col("f_chunk", t)
        fj = col("f_mb", t)
        src = col("f_src_slot", t)
        mb_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(fj, 0, M - 1), axis=0,
            keepdims=False)
        x = jnp.where(src >= 0, buf_read(abuf, src), mb_t)
        pf = chunk_params(fc)
        y = stage_fn(pf, x)
        xbuf = buf_write(xbuf, jnp.where(f_ok, col("x_wr_slot", t),
                                         -1), x)

        # ---- backward half ----
        b_ok = col("b_ok", t) == 1
        bc = col("b_chunk", t)
        bj = col("b_mb", t)
        xb = buf_read(xbuf, col("x_rd_slot", t))
        pb = chunk_params(bc)
        yb, vjp_fn = jax.vjp(lambda p, xx: stage_fn(p, xx), pb, xb)
        seed_cot, loss_b = seed_fn(yb, bj)
        crd = col("c_rd_slot", t)
        cot_y = jnp.where(crd >= 0, buf_read(cbuf, crd),
                          seed_cot.astype(dtype))
        gp, gx = vjp_fn(cot_y)
        # scatter-add this chunk's grads at local slot bc
        def acc_one(acc, g):
            sl = jnp.clip(bc, 0, V - 1)
            cur = jax.lax.dynamic_index_in_dim(acc, sl, axis=0,
                                               keepdims=False)
            upd = cur + jnp.where(b_ok, g, 0.0).astype(cur.dtype)
            return jax.lax.dynamic_update_index_in_dim(acc, upd, sl,
                                                       axis=0)
        gacc = jax.tree_util.tree_map(acc_one, gacc, gp)
        # the loss is counted where it is seeded (crd < 0 == last
        # virtual stage's in-tick turnaround)
        loss_acc = loss_acc + jnp.where(b_ok & (crd < 0), loss_b, 0.0)
        # virtual stage 0 (stage 0, chunk 0): gx is d/d microbatches
        if collect_gub:
            bi = jnp.clip(bj, 0, M - 1)
            take = b_ok & (stage == 0) & (bc == 0)
            old_g = jax.lax.dynamic_index_in_dim(gub, bi, axis=0,
                                                 keepdims=False)
            gub = jax.lax.dynamic_update_index_in_dim(
                gub, jnp.where(take, gx.astype(dtype), old_g), bi,
                axis=0)

        # ---- rotate payloads ----
        y_next = jax.lax.ppermute(
            jnp.where(f_ok, y, jnp.zeros_like(y)), axis, perm_down)
        gx_next = jax.lax.ppermute(
            jnp.where(b_ok, gx, jnp.zeros_like(gx)), axis, perm_up)
        out = (y_next, gx_next, abuf, xbuf, cbuf, gacc, loss_acc)
        return (out + (gub,) if collect_gub else out), None

    carry0 = (y0, jnp.zeros(mb_shape, dtype), abuf0, xbuf0, cbuf0, g0,
              jnp.float32(0.0))
    if collect_gub:
        carry0 = carry0 + (jnp.zeros((M,) + mb_shape, dtype),)
    final, _ = jax.lax.scan(tick, carry0, jnp.arange(T, dtype=i32))
    gacc, loss_acc = final[5], final[6]
    gub = final[7] if collect_gub else None
    return gacc, loss_acc, gub


def spmd_pipeline_interleaved_1f1b(stage_fn: Callable,
                                   loss_fn: Callable,
                                   params_chunks: Pytree,
                                   microbatches: jax.Array,
                                   targets: jax.Array,
                                   *, axis: str = comm.AXIS_PIPE):
    """Interleaved 1F1B over the pipe axis: returns
    ``(mean_loss, grads)`` with grads shaped like ``params_chunks``
    (leading dim V = local chunks, global chunk ``c*P + s``).

    ``stage_fn(params_chunk, x) -> y`` (one chunk's forward, same
    shapes in and out); ``loss_fn(y, target_mb) -> scalar`` seeds the
    last virtual stage's cotangent.  Not itself differentiable (it IS
    the backward), like ``spmd_pipeline_1f1b``; for a composable
    drop-in see ``spmd_pipeline_interleaved_1f1b_apply``.
    """
    M = microbatches.shape[0]

    def seed(yb, bj):
        tgt_b = jax.lax.dynamic_index_in_dim(
            targets, jnp.clip(bj, 0, M - 1), axis=0, keepdims=False)
        return tuple(reversed(jax.value_and_grad(
            lambda yy: loss_fn(yy, tgt_b))(yb)))

    gacc, loss_acc, _ = _interleaved_scan(
        stage_fn, seed, params_chunks, microbatches, axis, False)
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as _reduce)
    loss = _reduce(loss_acc, axis) / M
    grads = jax.tree_util.tree_map(lambda g: g / M, gacc)
    return loss, grads


# ---------------------------------------------------------------------
# Composable variant: interleaved forward, interleaved-1F1B backward
# ---------------------------------------------------------------------

import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _interleaved_apply(stage_fn, axis, params_chunks, microbatches):
    from apex_tpu.transformer.pipeline_parallel.spmd import (
        spmd_pipeline_interleaved)
    return spmd_pipeline_interleaved(stage_fn, params_chunks,
                                     microbatches, axis=axis)


def _interleaved_apply_fwd(stage_fn, axis, params_chunks, microbatches):
    from apex_tpu.transformer.pipeline_parallel.spmd import (
        spmd_pipeline_interleaved)
    out = spmd_pipeline_interleaved(stage_fn, params_chunks,
                                    microbatches, axis=axis)
    return out, (params_chunks, microbatches)


def _interleaved_apply_bwd(stage_fn, axis, res, ct):
    params_chunks, microbatches = res
    M = microbatches.shape[0]

    def seed(yb, bj):
        ct_b = jax.lax.dynamic_index_in_dim(
            ct, jnp.clip(bj, 0, M - 1), axis=0, keepdims=False)
        return ct_b, jnp.float32(0.0)

    gacc, _, gub = _interleaved_scan(
        stage_fn, seed, params_chunks, microbatches, axis, True)
    return gacc, gub


_interleaved_apply.defvjp(_interleaved_apply_fwd,
                          _interleaved_apply_bwd)


def spmd_pipeline_interleaved_1f1b_apply(
        stage_fn: Callable, params_chunks: Pytree,
        microbatches: jax.Array, *, axis: str = comm.AXIS_PIPE):
    """``spmd_pipeline_interleaved`` drop-in whose BACKWARD is the
    interleaved-1F1B table scan (O(P·V) activation window, recompute
    from saved stage inputs).  Composable: layers before the pipeline
    (embedding) and after it (head/loss) differentiate through,
    including the d/d microbatches path — the virtual-chunk analog of
    ``spmd_pipeline_1f1b_apply``."""
    return _interleaved_apply(stage_fn, axis, params_chunks,
                              microbatches)
