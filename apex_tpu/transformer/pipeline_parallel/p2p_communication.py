"""Stage-to-stage activation/grad transfer (reference:
apex/transformer/pipeline_parallel/p2p_communication.py).

The reference wraps batched NCCL isend/irecv between pipeline ranks.
Under a single JAX controller the host-driven schedule owns every
stage's arrays in one process, so "send" is placing an array in the
neighbor stage's mailbox (device placement happens lazily when the
stage's jitted function consumes it; on a real pod the transfer rides
ICI via the resulting device-to-device copy).  The SPMD fast path in
``spmd.py`` replaces this module entirely with ``lax.ppermute``.

The mailbox keeps the reference's API shape: send_forward/recv_forward/
send_backward/recv_backward (+fused variants).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple


class P2PContext:
    """Per-schedule mailbox: {(direction, stage): tensor}."""

    def __init__(self, num_stages: int):
        self.num_stages = num_stages
        self.fwd: Dict[int, Any] = {}    # activations destined TO stage k
        self.bwd: Dict[int, Any] = {}    # grads destined TO stage k

    # --- reference-named API (stage-explicit because single-controller) ---
    def send_forward(self, output_tensor, from_stage: int) -> None:
        if from_stage + 1 < self.num_stages:
            self.fwd[from_stage + 1] = output_tensor

    def recv_forward(self, at_stage: int):
        if at_stage == 0:
            return None
        return self.fwd.pop(at_stage)

    def send_backward(self, input_grad, from_stage: int) -> None:
        if from_stage - 1 >= 0:
            self.bwd[from_stage - 1] = input_grad

    def recv_backward(self, at_stage: int):
        if at_stage == self.num_stages - 1:
            return None
        return self.bwd.pop(at_stage)

    def send_forward_recv_backward(self, output_tensor, from_stage: int):
        self.send_forward(output_tensor, from_stage)
        return self.recv_backward(from_stage)

    def send_backward_recv_forward(self, input_grad, from_stage: int):
        self.send_backward(input_grad, from_stage)
        return self.recv_forward(from_stage)
