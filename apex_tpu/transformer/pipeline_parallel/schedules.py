"""Pipeline-parallel microbatch schedules (reference:
apex/transformer/pipeline_parallel/schedules/... — no-pipelining, 1F1B
non-interleaved, 1F1B interleaved; call stack SURVEY.md §3.5).

The reference's schedules are imperative host loops issuing NCCL p2p ops
and torch autograd calls.  Here each stage's forward runs under
``jax.vjp`` so the 1F1B dataflow can replay backwards in the reference's
order (warmup fwds -> steady 1F1B -> cooldown bwds), exchanging
activations/grads through the P2PContext mailbox; per-stage grads
accumulate across microbatches.  The last stage differentiates its
scalar loss directly (no seed plumbing).

For production TPU throughput use apex_tpu.transformer.pipeline_parallel
.spmd — ONE compiled program over the "pipe" mesh axis with ppermute
transfers, where XLA overlaps compute and ICI traffic.  These host
schedules are the semantics reference and run anywhere.

Contract (mirroring the reference's forward_step_func):
  forward_step_func(microbatch, input_tensor, apply_fn, params)
      -> (output, loss_fn)
  - input_tensor is None on the first stage (read the microbatch).
  - loss_fn(output) -> scalar; consulted on the LAST stage only (it may
    close over the microbatch's labels).
  fwd_bwd(...) -> (losses_per_microbatch, grads_per_stage | None)
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    P2PContext)

Pytree = Any


def _add_trees(a, b):
    if a is None:
        return b
    return jax.tree_util.tree_map(jnp.add, a, b)


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        pipeline_model_parallel_size: int = 1) -> Callable:
    """Reference dispatch: schedule by pp size / virtual size."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            import functools
            return functools.partial(
                _forward_backward_pipelining_with_interleaving,
                pipeline_model_parallel_size=pipeline_model_parallel_size,
                virtual_pipeline_model_parallel_size=(
                    virtual_pipeline_model_parallel_size))
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def forward_backward_no_pipelining(
        forward_step_func: Callable,
        batch: Sequence,
        model: Sequence[Tuple[Callable, Pytree]],
        *, forward_only: bool = False, **kwargs):
    """Single stage: loop microbatches, accumulate grads (the reference's
    no-sync context + final sync collapses to plain accumulation)."""
    (apply_fn, params), = model
    losses, grad_acc = [], None
    for mb in batch:
        def loss_of(p):
            out, loss_fn = forward_step_func(mb, None, apply_fn, p)
            return loss_fn(out)

        if forward_only:
            losses.append(loss_of(params))
        else:
            loss, g = jax.value_and_grad(loss_of)(params)
            losses.append(loss)
            grad_acc = _add_trees(grad_acc, g)
    return losses, None if forward_only else [grad_acc]


class _StageRunner:
    """One pipeline stage: runs forwards under vjp, replays backwards."""

    def __init__(self, stage: int, num_stages: int, apply_fn, params,
                 forward_step_func, batch, ctx: P2PContext,
                 forward_only: bool):
        self.stage = stage
        self.num_stages = num_stages
        self.is_first = stage == 0
        self.is_last = stage == num_stages - 1
        self.apply_fn = apply_fn
        self.params = params
        self.fsf = forward_step_func
        self.batch = batch
        self.ctx = ctx
        self.forward_only = forward_only
        self.fwd_done = 0
        self.bwd_done = 0
        self.vjps: List[Any] = []     # FIFO
        self.grads = None
        self.losses: List[jax.Array] = []

    def can_forward(self, prev_done: int) -> bool:
        if self.fwd_done >= len(self.batch):
            return False
        return self.is_first or self.fwd_done < prev_done

    def forward(self):
        mb = self.batch[self.fwd_done]
        x = None if self.is_first else self.ctx.recv_forward(self.stage)

        if self.is_last:
            def g(p, xx):
                out, loss_fn = self.fsf(mb, xx, self.apply_fn, p)
                return loss_fn(out)
            loss, vjp = jax.vjp(g, self.params, x)
            self.losses.append(loss)
        else:
            def f(p, xx):
                out, _ = self.fsf(mb, xx, self.apply_fn, p)
                return out
            out, vjp = jax.vjp(f, self.params, x)
            self.ctx.send_forward(out, self.stage)
        if not self.forward_only:
            self.vjps.append(vjp)
        self.fwd_done += 1

    def can_backward(self, next_bwd_done: int) -> bool:
        if self.forward_only or self.bwd_done >= len(self.batch):
            return False
        if self.bwd_done >= self.fwd_done:
            return False
        return self.is_last or next_bwd_done > self.bwd_done

    def backward(self):
        vjp = self.vjps.pop(0)
        if self.is_last:
            dy = jnp.ones((), jnp.float32)
        else:
            dy = self.ctx.recv_backward(self.stage)
        gp, gx = vjp(dy)
        self.grads = _add_trees(self.grads, gp)
        if not self.is_first:
            self.ctx.send_backward(gx, self.stage)
        self.bwd_done += 1


def forward_backward_pipelining_without_interleaving(
        forward_step_func: Callable,
        batch: Sequence,
        model: Sequence[Tuple[Callable, Pytree]],
        *, forward_only: bool = False, **kwargs):
    """Literal 1F1B (non-interleaved): warmup forwards fill the pipe,
    then each stage alternates one-forward-one-backward, then cooldown
    drains the backwards — the reference's schedule order, executed by a
    dataflow-driven loop on the single controller."""
    num_stages = len(model)
    m = len(batch)
    ctx = P2PContext(num_stages)
    stages = [
        _StageRunner(s, num_stages, model[s][0], model[s][1],
                     forward_step_func, batch, ctx, forward_only)
        for s in range(num_stages)
    ]

    def all_done():
        for st in stages:
            if st.fwd_done < m:
                return False
            if not forward_only and st.bwd_done < m:
                return False
        return True

    while not all_done():
        progressed = False
        # 1F1B order: prefer backwards on drained stages (reverse order),
        # then forwards (dataflow order)
        for s in reversed(range(num_stages)):
            nxt = stages[s + 1].bwd_done if s + 1 < num_stages else None
            if stages[s].can_backward(nxt if nxt is not None else 0):
                stages[s].backward()
                progressed = True
        for s in range(num_stages):
            prev = stages[s - 1].fwd_done if s > 0 else 0
            if stages[s].can_forward(prev):
                stages[s].forward()
                progressed = True
        if not progressed:
            raise RuntimeError("1F1B schedule deadlocked (bug)")

    losses = stages[-1].losses
    grads = None if forward_only else [st.grads for st in stages]
    return losses, grads


class _KeyedP2P:
    """Interleaved mailbox: values keyed by (virtual position, microbatch)
    so out-of-order consumption across chunks can never alias."""

    def __init__(self):
        self.fwd: dict = {}
        self.bwd: dict = {}

    def has_fwd(self, v, mb):
        return (v, mb) in self.fwd

    def has_bwd(self, v, mb):
        return (v, mb) in self.bwd


def _interleaved_orders(P: int, V: int, m: int):
    """The reference's per-rank processing order
    (…schedules/fwd_bwd_pipelining_with_interleaving + get_model_chunk_id):
    microbatches advance in groups of P; within a group every chunk runs
    its P microbatches before the next chunk.  Backward mirrors with the
    chunk order reversed.  Returns (fwd_seq, bwd_seq) of (chunk, mb),
    identical for every rank."""
    fwd, bwd = [], []
    for k in range(m * V):
        kp = k % (P * V)
        mb = (k // (P * V)) * P + kp % P
        fwd.append((kp // P, mb))
        bwd.append((V - 1 - kp // P, mb))
    return fwd, bwd


def _forward_backward_pipelining_with_interleaving(
        forward_step_func: Callable,
        batch: Sequence,
        model: Sequence[Tuple[Callable, Pytree]],
        *, forward_only: bool = False,
        pipeline_model_parallel_size: Optional[int] = None,
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        schedule_trace: Optional[List] = None, **kwargs):
    """Interleaved 1F1B — virtual pipeline stages (reference:
    apex/transformer/pipeline_parallel/schedules/
    fwd_bwd_pipelining_with_interleaving.py, SURVEY.md §2.2/§3.5).

    ``model`` lists every model CHUNK in dataflow order: virtual
    position v = c*P + s is chunk c living on physical stage s, the
    reference's chunk-to-stage assignment.  Each rank executes the
    reference's schedule — warmup of
    (P - rank - 1)*2 + (V - 1)*P forwards (the interleaved pipe fills
    V times deeper but drains V times more often, shrinking the bubble
    by ~1/V), then strict one-forward-one-backward, then cooldown —
    driven here by a round-based single-controller executor whose every
    action is appended to ``schedule_trace`` as
    (rank, "fwd"|"bwd", chunk, microbatch).
    """
    L = len(model)
    V = virtual_pipeline_model_parallel_size
    P = pipeline_model_parallel_size or (L if V is None else L // V)
    V = V if V is not None else L // P
    if P * V != L:
        raise ValueError(
            f"{L} model chunks != pipeline size {P} * virtual size {V}")
    m = len(batch)
    if m % P != 0:
        raise ValueError(
            "interleaved schedule requires num_microbatches "
            f"({m}) % pipeline size ({P}) == 0 (reference constraint)")

    ctx = _KeyedP2P()
    fwd_seq, bwd_seq = _interleaved_orders(P, V, m)
    total = m * V

    # per-rank action list: warmup fwds, steady 1F1B, cooldown bwds
    actions = []
    for r in range(P):
        if m == P:
            # reference special case: with exactly one microbatch group
            # the schedule degenerates to all-forward-then-all-backward
            w = total
        else:
            w = min((P - r - 1) * 2 + (V - 1) * P, total)
        acts = [("fwd",) + fwd_seq[i] for i in range(w)]
        bi = 0
        for i in range(w, total):
            acts.append(("fwd",) + fwd_seq[i])
            acts.append(("bwd",) + bwd_seq[bi])
            bi += 1
        acts += [("bwd",) + bwd_seq[i] for i in range(bi, total)]
        if forward_only:
            acts = [a for a in acts if a[0] == "fwd"]
        actions.append(acts)

    vjps: dict = {}                 # (v, mb) -> vjp
    grads: List[Optional[Pytree]] = [None] * L
    losses: List[jax.Array] = []
    ptr = [0] * P

    def ready(r, act):
        kind, c, mb = act
        v = c * P + r
        if kind == "fwd":
            return v == 0 or ctx.has_fwd(v, mb)
        return v == L - 1 or ctx.has_bwd(v, mb)

    def run(r, act):
        kind, c, mb = act
        v = c * P + r
        apply_fn, params = model[v]
        if kind == "fwd":
            x = None if v == 0 else ctx.fwd.pop((v, mb))
            if forward_only:
                # no linearization: run the plain forward
                out, loss_fn = forward_step_func(batch[mb], x,
                                                 apply_fn, params)
                if v == L - 1:
                    losses.append(loss_fn(out))
                else:
                    ctx.fwd[(v + 1, mb)] = out
            elif v == L - 1:
                def g(p, xx):
                    out, loss_fn = forward_step_func(
                        batch[mb], xx, apply_fn, p)
                    return loss_fn(out)
                loss, vjp = jax.vjp(g, params, x)
                losses.append(loss)
                vjps[(v, mb)] = vjp
            else:
                def f(p, xx):
                    out, _ = forward_step_func(
                        batch[mb], xx, apply_fn, p)
                    return out
                out, vjp = jax.vjp(f, params, x)
                ctx.fwd[(v + 1, mb)] = out
                vjps[(v, mb)] = vjp
        else:
            vjp = vjps.pop((v, mb))
            dy = (jnp.ones((), jnp.float32) if v == L - 1
                  else ctx.bwd.pop((v, mb)))
            gp, gx = vjp(dy)
            grads[v] = _add_trees(grads[v], gp)
            if v > 0:
                ctx.bwd[(v - 1, mb)] = gx
        if schedule_trace is not None:
            schedule_trace.append((r, kind, c, mb))

    while any(ptr[r] < len(actions[r]) for r in range(P)):
        progressed = False
        for r in range(P):
            if ptr[r] < len(actions[r]) and ready(r, actions[r][ptr[r]]):
                run(r, actions[r][ptr[r]])
                ptr[r] += 1
                progressed = True
        if not progressed:
            raise RuntimeError("interleaved 1F1B deadlocked (bug)")

    return losses, None if forward_only else grads
