"""Reference parity: apex/transformer/testing/global_vars.py — a
module-global args namespace the megatron-style test harnesses read
(get_args/set_global_variables)."""

from __future__ import annotations

import argparse
from typing import Optional

_GLOBAL_ARGS: Optional[argparse.Namespace] = None


def set_global_variables(args=None, **overrides):
    global _GLOBAL_ARGS
    ns = args or argparse.Namespace(
        micro_batch_size=2,
        global_batch_size=8,
        num_layers=2,
        hidden_size=64,
        num_attention_heads=4,
        seq_length=32,
        padded_vocab_size=128,
        tensor_model_parallel_size=1,
        pipeline_model_parallel_size=1,
        seed=1234,
    )
    for k, v in overrides.items():
        setattr(ns, k, v)
    _GLOBAL_ARGS = ns
    return ns


def get_args() -> argparse.Namespace:
    if _GLOBAL_ARGS is None:
        raise RuntimeError("call set_global_variables() first")
    return _GLOBAL_ARGS


def destroy_global_vars():
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = None
