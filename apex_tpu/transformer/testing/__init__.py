from apex_tpu.transformer.testing import commons, global_vars  # noqa: F401
