"""Shared transformer-test fixtures (reference:
apex/transformer/testing/commons.py — initialize_distributed, seeds,
tiny model builders for the TP/PP suites, SURVEY.md §2.2/§4)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.transformer import parallel_state


def initialize_distributed(tensor_model_parallel_size: int = 1,
                           pipeline_model_parallel_size: int = 1,
                           data_parallel_size: int = 0):
    """Build the mesh + parallel_state for a test (the reference's
    torch.distributed.init_process_group + initialize_model_parallel).

    data_parallel_size 0 = use all remaining devices."""
    n = len(jax.devices())
    tp, pp = tensor_model_parallel_size, pipeline_model_parallel_size
    dp = data_parallel_size or n // (tp * pp)
    comm.destroy()
    comm.initialize(data=dp, pipe=pp, ctx=1, model=tp)
    parallel_state.initialize_model_parallel(tp, pp)
    return comm.mesh()


def destroy_distributed():
    parallel_state.destroy_model_parallel()
    comm.destroy()


def set_random_seed(seed: int):
    """Reference helper: one call seeding everything; JAX is functional
    so this just returns the key (and seeds numpy for test data)."""
    import numpy as np
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def build_tiny_gpt(vocab=128, layers=2, hidden=64, heads=4, seq=32):
    """Tiny GPT config for schedule/parallel tests."""
    from apex_tpu.models.gpt import GPTModel
    return GPTModel(vocab_size=vocab, num_layers=layers,
                    hidden_size=hidden, num_heads=heads, max_seq_len=seq)


def rand_tokens(key, batch, seq, vocab=128):
    return jax.random.randint(key, (batch, seq), 0, vocab)


def print_separator(msg: str):
    print(f"{' ' + msg + ' ':-^70}")
