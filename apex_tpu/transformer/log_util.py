"""Logging helpers (reference: apex/transformer/log_util.py)."""

import logging
import os

_LOGGER_NAME = "apex_tpu.transformer"


def get_transformer_logger(name: str = _LOGGER_NAME) -> logging.Logger:
    return logging.getLogger(name)


def set_logging_level(verbosity) -> None:
    """Reference contract: set the package logger's level; also honors the
    APEX_TPU_LOG_LEVEL env var at import."""
    logging.getLogger(_LOGGER_NAME).setLevel(verbosity)


_env_level = os.environ.get(  # apexlint: disable=APX601
    "APEX_TPU_LOG_LEVEL")  # deliberate: the reference contract is
# "honors the env var at import"; later changes go via
# set_logging_level()
if _env_level:
    set_logging_level(_env_level)
