"""Platform selection that works under hosted-TPU python images.

Some TPU environments register the TPU PJRT plugin via a sitecustomize
hook in EVERY python process and pin ``JAX_PLATFORMS`` there, so the
standard ``JAX_PLATFORMS=cpu python script.py`` idiom is silently
overridden.  The only reliable override is flipping the live jax config
before the first backend use — which is what ``select_platform`` does.

Used by the examples' ``--cpu`` flags; honors ``APEX_TPU_PLATFORM``
(e.g. ``APEX_TPU_PLATFORM=cpu``) so any entry point can be redirected
without editing it.

(Reference context: the reference picks devices with CUDA_VISIBLE_DEVICES
+ ``torch.cuda.set_device``; device selection there is an env concern
too, see examples/imagenet/main_amp.py in SURVEY.md §1 L6.)
"""

from __future__ import annotations

import os
from typing import Optional


def select_platform(platform: Optional[str] = None) -> Optional[str]:
    """Force the jax backend platform ("cpu", "tpu", ...).

    Call before any jax backend use.  ``platform=None`` falls back to
    the ``APEX_TPU_PLATFORM`` env var; returns the platform applied (or
    None if left at the environment default).
    """
    import jax

    p = platform or os.environ.get("APEX_TPU_PLATFORM") or None
    if p:
        jax.config.update("jax_platforms", p)
    return p


def enable_compilation_cache(min_compile_secs: float = 1.0) -> None:
    """Point jax at the repo's persistent executable cache (best
    effort) so repeat tool runs skip the slow first compile.  Shared by
    bench.py / tools/kernel_bench.py / tools/profile_step.py."""
    import jax

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:
        pass
