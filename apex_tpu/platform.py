"""Platform selection that works under hosted-TPU python images.

Some TPU environments register the TPU PJRT plugin via a sitecustomize
hook in EVERY python process and pin ``JAX_PLATFORMS`` there, so the
standard ``JAX_PLATFORMS=cpu python script.py`` idiom is silently
overridden.  The only reliable override is flipping the live jax config
before the first backend use — which is what ``select_platform`` does.

Used by the examples' ``--cpu`` flags; honors ``APEX_TPU_PLATFORM``
(e.g. ``APEX_TPU_PLATFORM=cpu``) so any entry point can be redirected
without editing it.

(Reference context: the reference picks devices with CUDA_VISIBLE_DEVICES
+ ``torch.cuda.set_device``; device selection there is an env concern
too, see examples/imagenet/main_amp.py in SURVEY.md §1 L6.)
"""

from __future__ import annotations

import os
from typing import Optional


def select_platform(platform: Optional[str] = None) -> Optional[str]:
    """Force the jax backend platform ("cpu", "tpu", ...).

    Call before any jax backend use.  ``platform=None`` falls back to
    the ``APEX_TPU_PLATFORM`` env var; returns the platform applied (or
    None if left at the environment default).
    """
    import jax

    p = platform or os.environ.get("APEX_TPU_PLATFORM") or None
    if p:
        jax.config.update("jax_platforms", p)
    return p


# Async-collective / latency-hiding-scheduler flags: the lowering-side
# half of the interleaved grad-reduce schedule (amp/flat_pipeline.py's
# chunked buckets + reduce-in-backward seam give XLA per-bucket
# collectives with bucket-local dependency cones; these flags tell the
# TPU compiler to actually SCHEDULE them under the remaining backward
# compute).  DebugOptions-level flags ride XLA_FLAGS; libtpu-scoped
# ones ride LIBTPU_INIT_ARGS (unknown XLA_FLAGS entries are fatal at
# backend init, so the split matters).
_LHS_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
)
_LHS_LIBTPU_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)

_LHS_PROVENANCE: Optional[dict] = None


def latency_hiding_provenance() -> Optional[dict]:
    """The record of what :func:`enable_latency_hiding_scheduler` did
    this process (None if never called) — bench artifacts embed it so
    a measured overlap fraction names the schedule it ran under."""
    return _LHS_PROVENANCE


def enable_latency_hiding_scheduler(force: bool = False,
                                    target: Optional[str] = None) -> dict:
    """Arm XLA's latency-hiding scheduler + async collectives (TPU).

    Appends the flag sets above to ``XLA_FLAGS`` / ``LIBTPU_INIT_ARGS``
    — idempotent (already-present flags are recorded as skipped, never
    duplicated) and effective only if called BEFORE the first jax
    backend use; a late call is recorded as ``applied=False`` with a
    RuntimeWarning, never a silent half-configuration.  The flags are
    applied only when the resolved target IS tpu — ``target="tpu"``
    explicitly (what bench.py passes on its hardware path), or the
    APEX_TPU_PLATFORM / JAX_PLATFORMS env saying so; anything else
    (cpu, or no platform selection at all) withholds them
    (``force=True`` overrides): a non-TPU backend may reject unknown
    ``XLA_FLAGS`` entries at init, and a CPU timing run under TPU
    scheduler flags would carry false provenance.

    Returns (and stashes, see :func:`latency_hiding_provenance`) a
    provenance dict: target backend, flags added, flags skipped,
    whether the environment mutation can still take effect.
    """
    import warnings

    global _LHS_PROVENANCE

    if target is None:
        target = (os.environ.get("APEX_TPU_PLATFORM")
                  or os.environ.get("JAX_PLATFORMS") or "").split(",")[0]
    try:
        from jax._src import xla_bridge as _xb
        backend_up = bool(getattr(_xb, "_backends", {}))
    except Exception:
        backend_up = False
    prov = {"target": target or "default", "applied": False,
            "xla_flags_added": [], "libtpu_flags_added": [],
            "skipped": [], "reason": None}
    if target != "tpu" and not force:
        prov["reason"] = (f"target {target or 'default'!r} is not tpu:"
                          " TPU scheduler flags withheld (pass "
                          "target='tpu' or force=True)")
        _LHS_PROVENANCE = prov
        return prov
    if backend_up:
        prov["reason"] = ("jax backend already initialized — flags "
                          "appended to the env take effect only in a "
                          "NEW process")
        warnings.warn(
            "apex_tpu.platform.enable_latency_hiding_scheduler called "
            "after jax backend init: the schedule flags cannot apply "
            "to this process", RuntimeWarning, stacklevel=2)
    for env_var, flags, key in (
            ("XLA_FLAGS", _LHS_XLA_FLAGS, "xla_flags_added"),
            ("LIBTPU_INIT_ARGS", _LHS_LIBTPU_FLAGS,
             "libtpu_flags_added")):
        current = os.environ.get(env_var, "")
        # whole-token presence, never substring: `..._fusion` must not
        # read as present because `..._fusion_fuse_all_gather` is
        present = {t.split("=", 1)[0] for t in current.split()}
        added = []
        for f in flags:
            if f.split("=", 1)[0] in present:
                prov["skipped"].append(f)
            else:
                added.append(f)
        if added:
            os.environ[env_var] = (current + " " + " ".join(added)).strip()
        prov[key] = added
    prov["applied"] = not backend_up
    _LHS_PROVENANCE = prov
    return prov


def enable_compilation_cache(min_compile_secs: float = 1.0) -> None:
    """Point jax at the repo's persistent executable cache (best
    effort) so repeat tool runs skip the slow first compile.  Shared by
    bench.py / tools/kernel_bench.py / tools/profile_step.py."""
    import jax

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:
        pass
