"""Host-memory offload utilities (beyond-reference TPU extension).

HBM is the scarce resource on TPU; pinned host memory rides the same
PCIe/DMA engines XLA already overlaps with compute.  Two offload tiers:

- **Optimizer state**: ``FusedAdam(..., offload_state=True)`` (see
  apex_tpu.optimizers) — helpers ``place_on_host`` / ``place_on_device``
  re-exported here.
- **Activations under rematerialization**: ``offload_checkpoint`` is
  ``jax.checkpoint`` with a save-to-host policy — activations tagged
  with ``checkpoint_name`` stream to pinned host memory in the forward
  pass and back for backward, instead of being recomputed (FLOPs) or
  held in HBM (memory).  The reference has no analog (its
  ``tensor_parallel.checkpoint`` recomputes only).

Example::

    from apex_tpu.offload import offload_checkpoint, checkpoint_name

    def block(params, x):
        h = checkpoint_name(big_ffn_hidden(params, x), "ffn_hidden")
        return out_proj(params, h)

    y = offload_checkpoint(block, offload_names=("ffn_hidden",))(p, x)

GPT layers pre-tag their two largest activations as ``"attn_out"`` and
``"ffn_hidden"`` (apex_tpu.models.gpt), so
``offload_checkpoint(layer.apply, offload_names=("ffn_hidden",))`` works
out of the box.  ``checkpoint_name`` is a no-op marker outside a remat
scope.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
from jax.ad_checkpoint import checkpoint_name

from apex_tpu.optimizers._base import place_on_device, place_on_host

__all__ = ["checkpoint_name", "offload_checkpoint", "offload_policy",
           "place_on_host", "place_on_device"]


def offload_policy(offload_names: Sequence[str],
                   save_names: Sequence[str] = (),
                   offload_dst: str = "pinned_host"):
    """The remat policy behind ``offload_checkpoint``, exposed for
    wrappers that take a policy directly (e.g. ``flax.linen.remat(Block,
    policy=offload_policy(("ffn_hidden",)))``)."""
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=list(save_names),
        names_which_can_be_offloaded=list(offload_names),
        offload_src="device", offload_dst=offload_dst)


def offload_checkpoint(fn: Callable,
                       offload_names: Sequence[str],
                       save_names: Sequence[str] = (),
                       offload_dst: str = "pinned_host") -> Callable:
    """Rematerialize ``fn`` with named activations offloaded to host.

    offload_names: ``checkpoint_name`` tags whose values are saved to
    ``offload_dst`` (streamed back for backward).  save_names: tags kept
    in device memory.  Everything untagged is recomputed.
    """
    return jax.checkpoint(fn, policy=offload_policy(
        offload_names, save_names, offload_dst))
