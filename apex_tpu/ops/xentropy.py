"""Fused softmax cross-entropy with label smoothing (reference:
apex/contrib/csrc/xentropy/ — `xentropy_cuda.forward/backward`,
SURVEY.md §2.3/§2.4).

The reference fuses logsumexp + target-logit gather into one kernel and
computes the backward in-place from the saved `max_log_sum_exp`.  Here the
same fusion is one Pallas row pass: forward computes, per row of logits,

    lse    = logsumexp(x)
    loss   = lse - (1-eps) * x[target] - eps * mean(x)

(the standard label-smoothing decomposition: (1-eps)*NLL + eps*uniform-KL
up to a constant, exactly the reference's formula).  The gather is done
in-register via an iota==target one-hot — no HBM gather op.  Backward
recomputes softmax from the saved per-row lse (cheaper than saving the
full probability matrix):

    dx = dy * (softmax(x) - (1-eps)*onehot - eps/C)

All math in f32 regardless of input dtype; `half_to_float` keeps the
reference's contract of emitting f32 losses/grads from half inputs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import interpret_mode, op_enabled

LANE = 128
_MAX_C = 65536          # beyond this, the XLA path wins anyway


def _block_rows(c: int) -> int:
    rows = max(8, min(256, (512 * 1024) // (c * 4)))
    return rows - rows % 8


def _use_pallas(c: int) -> bool:
    return op_enabled("xentropy") and c % LANE == 0 and c <= _MAX_C


def _fwd_kernel(smoothing, x_ref, t_ref, loss_ref, lse_ref):
    x = x_ref[...].astype(jnp.float32)              # (br, C)
    t = t_ref[...]                                  # (br, LANE) broadcast
    br, c = x.shape
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    lse = m + jnp.log(jnp.sum(e, axis=1, keepdims=True))
    cols = jax.lax.broadcasted_iota(jnp.int32, (br, c), 1)
    onehot = cols == t[:, :1]
    xt = jnp.sum(jnp.where(onehot, x, 0.0), axis=1, keepdims=True)
    loss = lse - (1.0 - smoothing) * xt
    if smoothing:
        loss = loss - smoothing * jnp.mean(x, axis=1, keepdims=True)
    loss_ref[...] = jnp.broadcast_to(loss, (br, LANE))
    lse_ref[...] = jnp.broadcast_to(lse, (br, LANE))


def _bwd_kernel(smoothing, x_ref, t_ref, lse_ref, dy_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    t = t_ref[...]
    lse = lse_ref[...][:, :1]
    dy = dy_ref[...][:, :1].astype(jnp.float32)
    br, c = x.shape
    p = jnp.exp(x - lse)
    cols = jax.lax.broadcasted_iota(jnp.int32, (br, c), 1)
    onehot = (cols == t[:, :1]).astype(jnp.float32)
    dx = p - (1.0 - smoothing) * onehot
    if smoothing:
        dx = dx - smoothing / c
    dx_ref[...] = (dy * dx).astype(dx_ref.dtype)


def _pad_rows(a, rows):
    return jnp.pad(a, ((0, rows - a.shape[0]),) + ((0, 0),) * (a.ndim - 1))


def _lane_bcast(v, rows):
    return jnp.broadcast_to(_pad_rows(v.reshape(-1, 1), rows), (rows, LANE))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy(logits, labels, smoothing=0.0, half_to_float=False):
    """Per-example label-smoothed cross entropy.

    logits (N, C) float, labels (N,) int.  Returns losses (N,) — f32 when
    `half_to_float` or logits are f32, else logits.dtype.  Parity:
    xentropy_cuda.forward (losses tensor; the saved max_log_sum_exp is an
    internal residual here).
    """
    return _xent_fwd(logits, labels, smoothing, half_to_float)[0]


def _loss_dtype(logits, half_to_float):
    return jnp.float32 if half_to_float else logits.dtype


def _xent_fwd(logits, labels, smoothing, half_to_float):
    n, c = logits.shape
    labels = labels.astype(jnp.int32)
    if not _use_pallas(c):
        xf = logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(xf, axis=1)
        xt = jnp.take_along_axis(xf, labels[:, None], axis=1)[:, 0]
        loss = lse - (1.0 - smoothing) * xt - smoothing * jnp.mean(xf, axis=1)
        loss = loss.astype(_loss_dtype(logits, half_to_float))
        return loss, (logits, labels, lse)
    br = _block_rows(c)
    rows = (n + br - 1) // br * br
    loss2d, lse2d = pl.pallas_call(
        functools.partial(_fwd_kernel, smoothing),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                  pl.BlockSpec((br, LANE), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, LANE), lambda i: (i, 0))] * 2,
        out_shape=[jax.ShapeDtypeStruct((rows, LANE), jnp.float32)] * 2,
        interpret=interpret_mode(),
        name="apex_xentropy_fwd",
    )(_pad_rows(logits, rows), _lane_bcast(labels, rows).astype(jnp.int32))
    loss = loss2d[:n, 0].astype(_loss_dtype(logits, half_to_float))
    return loss, (logits, labels, lse2d[:n, 0])


def _xent_bwd(smoothing, half_to_float, res, dy):
    logits, labels, lse = res
    n, c = logits.shape
    out_dtype = _loss_dtype(logits, half_to_float)
    if not _use_pallas(c):
        p = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
        onehot = jax.nn.one_hot(labels, c, dtype=jnp.float32)
        dx = p - (1.0 - smoothing) * onehot - smoothing / c
        dx = dy.astype(jnp.float32)[:, None] * dx
        return dx.astype(out_dtype), None
    br = _block_rows(c)
    rows = (n + br - 1) // br * br
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, smoothing),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                  pl.BlockSpec((br, LANE), lambda i: (i, 0)),
                  pl.BlockSpec((br, LANE), lambda i: (i, 0)),
                  pl.BlockSpec((br, LANE), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, c), out_dtype),
        interpret=interpret_mode(),
        name="apex_xentropy_bwd",
    )(_pad_rows(logits, rows),
      _lane_bcast(labels, rows).astype(jnp.int32),
      _lane_bcast(lse, rows),
      _lane_bcast(dy.astype(jnp.float32), rows))
    return dx[:n], None


softmax_cross_entropy.defvjp(_xent_fwd, _xent_bwd)


def softmax_cross_entropy_ref(logits, labels, smoothing=0.0,
                              half_to_float=False):
    """Pure-XLA oracle (the reference's test oracle is label-smoothed
    log_softmax NLL in stock torch)."""
    xf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(xf, axis=1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                               axis=1)[:, 0]
    smooth = -jnp.mean(logp, axis=1)
    loss = (1.0 - smoothing) * nll + smoothing * smooth
    return loss.astype(_loss_dtype(logits, half_to_float))
