"""apex_tpu.ops — the Pallas/XLA kernel layer.

TPU-native replacement for the reference's ``csrc/`` CUDA extension suite
(SURVEY.md §2.4).  Every reference extension module maps to a submodule
here; Python callers get `jax.custom_vjp`-wired functions instead of
pybind11 modules.

  amp_C (multi_tensor_*)        -> apex_tpu.ops.multi_tensor
  fused_layer_norm_cuda         -> apex_tpu.ops.layer_norm
  scaled_*_softmax_cuda         -> apex_tpu.ops.softmax
  fused_rotary_positional_emb.. -> apex_tpu.ops.rope
  xentropy_cuda                 -> apex_tpu.ops.xentropy
  fast_multihead_attn / fmhalib -> apex_tpu.ops.attention
  syncbn (welford)              -> apex_tpu.ops.welford
  transducer_*_cuda             -> apex_tpu.ops.transducer
"""

from apex_tpu.ops._dispatch import interpret_mode, on_tpu
