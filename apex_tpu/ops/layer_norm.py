"""Fused LayerNorm / RMSNorm forward+backward Pallas kernels.

TPU-native replacement for the reference's ``fused_layer_norm_cuda``
extension (csrc/layer_norm_cuda.cpp + layer_norm_cuda_kernel.cu,
SURVEY.md §2.4) and the contrib ``fast_layer_norm`` ext.  Row-tiled
kernels, f32 accumulation regardless of storage dtype (bf16 x f32-param
"mixed" variants fall out for free), wired into autodiff via
``jax.custom_vjp``.

Design notes (vs the CUDA original):
  - The backward RECOMPUTES mean/rstd from the saved input instead of
    plumbing per-row statistics through HBM — on TPU the op is
    HBM-bandwidth-bound, so dropping two (rows,) side arrays is a win and
    subsumes the reference's ``memory_efficient`` flag.
  - dgamma/dbeta accumulate across the sequential TPU grid into one
    (1, H) f32 block (the reference needs a two-stage cross-CTA
    reduction).
  - Hidden sizes not divisible by 128 (VPU lane width) fall back to the
    pure-XLA path, which XLA fuses well; the Pallas fast path covers the
    transformer-shaped cases, like the reference's fast_layer_norm covers
    hidden <= ~8k.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import interpret_mode, op_enabled

LANE = 128
_VMEM_BUDGET = 1024 * 1024  # per-operand block budget (bytes, f32)


def _block_rows(h: int) -> int:
    rows = max(8, min(512, _VMEM_BUDGET // (h * 4)))
    return rows - rows % 8 if rows >= 8 else 8


def _pad_rows(x2d: jax.Array, br: int) -> jax.Array:
    r = x2d.shape[0]
    pad = (-r) % br
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d


def _use_pallas(h: int) -> bool:
    # 8 is the minimum block-row count: even at the floor, one block must
    # fit the per-operand budget (the backward holds ~6 operand blocks)
    return op_enabled("layer_norm") and h % LANE == 0 and 8 * h * 4 <= _VMEM_BUDGET


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(rms, eps, x_ref, w_ref, b_ref, y_ref):
    x = x_ref[...].astype(jnp.float32)
    if rms:
        ms = jnp.mean(x * x, axis=1, keepdims=True)
        xhat = x * jax.lax.rsqrt(ms + eps)
    else:
        mu = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mu
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        xhat = xc * jax.lax.rsqrt(var + eps)
    y = xhat * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(rms, eps, x_ref, w_ref, dy_ref, dx_ref, dw_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    if rms:
        ms = jnp.mean(x * x, axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(ms + eps)
        xhat = x * rstd
        dyw = dy * w
        m2 = jnp.mean(dyw * xhat, axis=1, keepdims=True)
        dx = (dyw - xhat * m2) * rstd
    else:
        mu = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mu
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        xhat = xc * rstd
        dyw = dy * w
        m1 = jnp.mean(dyw, axis=1, keepdims=True)
        m2 = jnp.mean(dyw * xhat, axis=1, keepdims=True)
        dx = (dyw - m1 - xhat * m2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dw_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def _row_spec(br, h):
    return pl.BlockSpec((br, h), lambda i: (i, 0))


def _param_spec(h):
    return pl.BlockSpec((1, h), lambda i: (0, 0))


def _fwd_2d(x2d, w, b, eps, rms):
    r, h = x2d.shape
    br = _block_rows(h)
    xp = _pad_rows(x2d, br)
    y = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, rms, eps),
        grid=(xp.shape[0] // br,),
        in_specs=[_row_spec(br, h), _param_spec(h), _param_spec(h)],
        out_specs=_row_spec(br, h),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x2d.dtype),
        interpret=interpret_mode(),
        name="apex_fused_layer_norm_fwd" if not rms else
             "apex_fused_rms_norm_fwd",
    )(xp, w.reshape(1, h), b.reshape(1, h))
    return y[:r]


def _bwd_2d(x2d, w, dy2d, eps, rms):
    r, h = x2d.shape
    br = _block_rows(h)
    xp = _pad_rows(x2d, br)
    dyp = _pad_rows(dy2d, br)
    dx, dw, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, rms, eps),
        grid=(xp.shape[0] // br,),
        in_specs=[_row_spec(br, h), _param_spec(h), _row_spec(br, h)],
        out_specs=[_row_spec(br, h), _param_spec(h), _param_spec(h)],
        out_shape=[
            jax.ShapeDtypeStruct(xp.shape, x2d.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=interpret_mode(),
        name="apex_fused_layer_norm_bwd" if not rms else
             "apex_fused_rms_norm_bwd",
    )(xp, w.reshape(1, h), dyp)
    return dx[:r], dw.reshape(h), db.reshape(h)


# ---------------------------------------------------------------------------
# XLA fallback (also the test oracle)
# ---------------------------------------------------------------------------

def layer_norm_ref(x, weight=None, bias=None, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_ref(x, weight=None, eps=1e-5):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# custom_vjp wiring  (replaces the reference's autograd.Function classes,
# apex/normalization/fused_layer_norm.py::FusedLayerNormAffineFunction)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _norm_affine(x, w, b, eps, rms):
    return _norm_affine_fwd(x, w, b, eps, rms)[0]


def _norm_affine_fwd(x, w, b, eps, rms):
    h = x.shape[-1]
    x2d = x.reshape(-1, h)
    if _use_pallas(h):
        y = _fwd_2d(x2d, w, b, eps, rms).reshape(x.shape)
    else:
        y = (rms_norm_ref(x, w, eps) if rms
             else layer_norm_ref(x, w, b, eps))
    return y, (x, w, b)


def _norm_affine_bwd(eps, rms, res, dy):
    x, w, b = res
    h = x.shape[-1]
    if _use_pallas(h):
        dx2d, dw, db = _bwd_2d(x.reshape(-1, h), w,
                               dy.reshape(-1, h), eps, rms)
        dx = dx2d.reshape(x.shape)
        dw = dw.astype(w.dtype)
        db = db.astype(b.dtype)
    else:
        def f(x, w, b):
            return (rms_norm_ref(x, w, eps) if rms
                    else layer_norm_ref(x, w, b, eps))
        _, vjp = jax.vjp(f, x, w, b)
        dx, dw, db = vjp(dy)
    if rms:
        db = jnp.zeros_like(b)
    return dx, dw, db


_norm_affine.defvjp(_norm_affine_fwd, _norm_affine_bwd)


def fused_layer_norm(x, weight: Optional[jax.Array] = None,
                     bias: Optional[jax.Array] = None, eps: float = 1e-5,
                     memory_efficient: bool = True):
    """LayerNorm over the last dim (reference fused_layer_norm_cuda fwd).

    ``memory_efficient`` is accepted for API parity; the TPU kernel is
    always memory-efficient (stats recomputed in backward).
    """
    del memory_efficient
    h = x.shape[-1]
    w = weight if weight is not None else jnp.ones((h,), jnp.float32)
    b = bias if bias is not None else jnp.zeros((h,), jnp.float32)
    y = _norm_affine(x, w, b, float(eps), False)
    return y


def fused_rms_norm(x, weight: Optional[jax.Array] = None, eps: float = 1e-5,
                   memory_efficient: bool = True):
    """RMSNorm over the last dim (reference fused_layer_norm_cuda RMS fwd)."""
    del memory_efficient
    h = x.shape[-1]
    w = weight if weight is not None else jnp.ones((h,), jnp.float32)
    b = jnp.zeros((h,), jnp.float32)
    return _norm_affine(x, w, b, float(eps), True)
