"""Welford batch-statistics kernels (reference: csrc/syncbn.cpp +
csrc/welford.cu, SURVEY.md §2.4).

The reference computes per-GPU Welford mean/var, all-gathers the partial
(mean, var, count) triples, and merges them with Chan's parallel combine.
The TPU design is identical in structure: a Pallas kernel produces the
LOCAL (per-shard) triple with one pass over (rows, C) data, and
``welford_combine`` merges triples — either across grid blocks (inside
the kernel) or across mesh devices (via all_gather in
apex_tpu.parallel.sync_batchnorm).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import interpret_mode, op_enabled

LANE = 128
_BLOCK_ROWS = 256


def welford_combine(n_a, mean_a, m2_a, n_b, mean_b, m2_b):
    """Chan's parallel combine of two (count, mean, M2) triples.

    Shapes broadcast; counts are scalars or (1, C).  Guarded for empty
    partitions (n == 0).
    """
    n = n_a + n_b
    safe_n = jnp.where(n > 0, n, 1.0)
    delta = mean_b - mean_a
    mean = mean_a + delta * (n_b / safe_n)
    m2 = m2_a + m2_b + delta * delta * (n_a * n_b / safe_n)
    return n, mean, m2


def _welford_kernel(total_rows, x_ref, cnt_ref, mean_ref, m2_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        # SMEM refs take SCALAR stores under Mosaic (interpret mode is
        # laxer — this was round 2's hardware-validation catch)
        cnt_ref[0, 0] = jnp.float32(0.0)
        mean_ref[...] = jnp.zeros_like(mean_ref)
        m2_ref[...] = jnp.zeros_like(m2_ref)

    x = x_ref[...].astype(jnp.float32)
    br = x.shape[0]
    row_ids = i * br + jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0)
    valid = (row_ids < total_rows).astype(jnp.float32)
    n_b = jnp.sum(valid)
    safe_nb = jnp.maximum(n_b, 1.0)
    xm = x * valid
    mean_b = jnp.sum(xm, axis=0, keepdims=True) / safe_nb
    m2_b = jnp.sum(valid * (x - mean_b) ** 2, axis=0, keepdims=True)
    n, mean, m2 = welford_combine(
        cnt_ref[0, 0], mean_ref[...], m2_ref[...], n_b, mean_b, m2_b)
    cnt_ref[0, 0] = n
    mean_ref[...] = mean
    m2_ref[...] = m2


def welford_mean_var(x2d: jax.Array) -> Tuple[jax.Array, jax.Array,
                                              jax.Array]:
    """Local Welford stats of an (N, C) array, reduced over N.

    Returns (mean (C,), biased var (C,), count scalar) — the reference's
    syncbn.welford_mean_var contract.  C must be a multiple of 128 for
    the Pallas path; otherwise the XLA fallback runs.
    """
    n, c = x2d.shape
    if not (op_enabled("welford") and c % LANE == 0):
        return welford_mean_var_ref(x2d)
    rows = (n + _BLOCK_ROWS - 1) // _BLOCK_ROWS * _BLOCK_ROWS
    xp = jnp.pad(x2d, ((0, rows - n), (0, 0)))
    cnt, mean, m2 = pl.pallas_call(
        functools.partial(_welford_kernel, n),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, c), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        interpret=interpret_mode(),
        name="apex_syncbn_welford",
    )(xp)
    count = cnt[0, 0]
    var = m2[0] / jnp.maximum(count, 1.0)
    return mean[0], var, count


def welford_mean_var_ref(x2d: jax.Array):
    xf = x2d.astype(jnp.float32)
    n = xf.shape[0]
    mean = jnp.mean(xf, axis=0)
    var = jnp.mean((xf - mean) ** 2, axis=0)
    return mean, var, jnp.float32(n)
