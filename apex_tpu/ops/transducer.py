"""RNN-T transducer joint + loss (reference: apex/contrib/csrc/transducer/
— `transducer_joint_cuda`, `transducer_loss_cuda`, SURVEY.md §2.3/§2.4).

Joint: h[b,t,u] = f[b,t] + g[b,u] broadcast-add (optionally ReLU), the
reference's packed layouts replaced by masking — XLA needs static shapes,
so padding positions are zeroed instead of physically dropped (the
reference packs purely to save HBM on ragged batches; on TPU the masked
form keeps the add a single fused broadcast).

Loss: the forward-backward alpha recursion

    alpha[t,u] = lse(alpha[t-1,u] + blank[t-1,u],
                     alpha[t,u-1] + label[t,u-1])

is computed as a `lax.scan` over ANTI-DIAGONALS d = t+u: both
dependencies sit on diagonal d-1, so every cell of a diagonal is computed
in one vectorized step — the standard wavefront schedule the CUDA kernel
implements with a thread per u; here the VPU lanes are the wavefront.
Backward comes from autodiff through the scan (the transpose of the
wavefront IS the beta recursion the reference hand-codes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG = -1e30


def transducer_joint(f, g, f_len=None, g_len=None, *, relu=False,
                     dropout_rate=0.0, dropout_rng=None):
    """f (B, T, H), g (B, U, H) -> (B, T, U, H) broadcast add.

    Positions with t >= f_len[b] or u >= g_len[b] are zeroed (the masked
    equivalent of the reference's pack_output).  Reference:
    transducer_joint_cuda.forward.
    """
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
    if (f_len is None) != (g_len is None):
        raise ValueError(
            "transducer_joint: f_len and g_len must be passed together "
            f"(got f_len={'set' if f_len is not None else None}, "
            f"g_len={'set' if g_len is not None else None})")
    if f_len is not None:
        b, t, u, _ = h.shape
        tmask = jnp.arange(t)[None, :] < f_len[:, None]        # (B, T)
        umask = jnp.arange(u)[None, :] < g_len[:, None]        # (B, U)
        h = h * (tmask[:, :, None, None] & umask[:, None, :, None])
    return h


def transducer_joint_ref(f, g, f_len=None, g_len=None, *, relu=False):
    return transducer_joint(f, g, f_len, g_len, relu=relu)


def _gather_t(x, t_idx):
    """x (B, T, U), t_idx (U,) -> y (B, U) with y[b,u] = x[b, t_idx[u], u]
    (t clipped to range; caller masks invalid cells)."""
    b, t, u = x.shape
    idx = jnp.clip(t_idx, 0, t - 1)[None, :, None]             # (1, U, 1)
    xt = jnp.swapaxes(x, 1, 2)                                 # (B, U, T)
    return jnp.take_along_axis(xt, jnp.broadcast_to(idx, (b, u, 1)),
                               axis=2)[..., 0]


def transducer_loss(x, label, f_len, y_len, blank_idx=0):
    """RNN-T loss.  x (B, T, U, V) joint logits with U = max_y_len + 1;
    label (B, U-1) int; f_len (B,), y_len (B,).  Returns per-example
    negative log-likelihood (B,) f32.  Reference:
    transducer_loss_cuda.forward.
    """
    b, t, u, v = x.shape
    acc = jnp.promote_types(x.dtype, jnp.float32)   # f32, or f64 under x64
    logp = jax.nn.log_softmax(x.astype(acc), axis=-1)
    blank_lp = logp[..., blank_idx]                            # (B, T, U)
    # label_lp[b,t,u] = logp[b,t,u,label[b,u]] for u < U-1; pad last col
    lab = jnp.concatenate(
        [label.astype(jnp.int32),
         jnp.zeros((b, 1), jnp.int32)], axis=1)                # (B, U)
    label_lp = jnp.take_along_axis(
        logp, lab[:, None, :, None], axis=3)[..., 0]           # (B, T, U)

    us = jnp.arange(u)
    alpha0 = jnp.full((b, u), _NEG, acc).at[:, 0].set(0.0)
    # label_lp_shift[b,t,u] = label_lp[b,t,u-1] (the label emitted to
    # REACH column u lives in column u-1)
    label_lp_shift = jnp.roll(label_lp, 1, axis=2)

    def diag_step(alpha_prev, d):
        # cell (t, u) on diagonal d has t = d - u
        t_here = d - us                                        # (U,)
        # blank path: from (t-1, u) on diag d-1
        blank_term = alpha_prev + _gather_t(blank_lp, t_here - 1)
        blank_term = jnp.where((t_here >= 1)[None, :], blank_term, _NEG)
        # label path: from (t, u-1) on diag d-1 (same t)
        lab_term = (jnp.roll(alpha_prev, 1, axis=1)
                    + _gather_t(label_lp_shift, t_here))
        lab_term = jnp.where((us >= 1)[None, :], lab_term, _NEG)
        new = jnp.logaddexp(blank_term, lab_term)
        # out-of-range cells stay inert
        on_diag = (t_here >= 0) & (t_here < t)
        new = jnp.where(on_diag[None, :], new, _NEG)
        return new, new

    n_diag = t + u - 1
    _, alphas = jax.lax.scan(diag_step, alpha0,
                             jnp.arange(1, n_diag))            # (D-1, B, U)
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)   # (D, B, U)

    # terminal: alpha[f_len-1, y_len] + blank[f_len-1, y_len]
    t_last = f_len.astype(jnp.int32) - 1                       # (B,)
    u_last = y_len.astype(jnp.int32)                           # (B,)
    d_last = t_last + u_last
    alpha_last = alphas[d_last, jnp.arange(b), u_last]
    blank_last = blank_lp[jnp.arange(b), t_last, u_last]
    return -(alpha_last + blank_last)


def transducer_loss_ref(x, label, f_len, y_len, blank_idx=0):
    """Naive per-example dynamic-programming oracle (host loop, numpy
    semantics via jnp; used by tests only)."""
    import numpy as np
    x = np.asarray(x, np.float64)
    label = np.asarray(label)
    f_len = np.asarray(f_len)
    y_len = np.asarray(y_len)
    b, t, u, v = x.shape
    lp = x - np.log(np.sum(np.exp(x - x.max(-1, keepdims=True)), -1,
                           keepdims=True)) - x.max(-1, keepdims=True)
    losses = []
    for i in range(b):
        ti, ui = int(f_len[i]), int(y_len[i]) + 1
        alpha = np.full((ti, ui), -np.inf)
        alpha[0, 0] = 0.0
        for tt in range(ti):
            for uu in range(ui):
                if tt == 0 and uu == 0:
                    continue
                cands = []
                if tt > 0:
                    cands.append(alpha[tt - 1, uu]
                                 + lp[i, tt - 1, uu, blank_idx])
                if uu > 0:
                    cands.append(alpha[tt, uu - 1]
                                 + lp[i, tt, uu - 1, label[i, uu - 1]])
                m = max(cands)
                alpha[tt, uu] = m + np.log(
                    sum(np.exp(c - m) for c in cands))
        losses.append(-(alpha[ti - 1, ui - 1]
                        + lp[i, ti - 1, ui - 1, blank_idx]))
    return jnp.asarray(losses, jnp.float32)
