"""Weight-gradient GEMM with f32 accumulation into a persistent main_grad
buffer (reference: csrc/megatron/fused_weight_gradient_dense.cpp —
`fused_weight_gradient_mlp_cuda.wgrad_gemm_accum_fp32/_fp16`, SURVEY.md
§2.4).

The reference exists because Megatron accumulates many microbatches'
weight grads into one fp32 buffer without materializing per-microbatch
fp16 grads.  TPU-native: `dot_general` with
preferred_element_type=f32 IS the mixed-precision wgrad GEMM (MXU
accumulates in f32 natively); the running accumulation is an add into a
DONATED buffer, which XLA performs in place — the same zero-copy
accumulate the CUDA kernel hand-rolls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def wgrad_gemm_accum_fp32(input_, grad_output, main_grad):
    """main_grad += grad_output^T @ input, accumulated in f32.

    input_ (..., In) activations; grad_output (..., Out) upstream grads;
    main_grad (Out, In) f32 accumulator — the reference's nn.Linear
    weight layout (out_features, in_features), so the accumulator adds
    straight onto weight.main_grad.  Leading dims are flattened (the
    reference's sequence*batch collapse).  Returns the new accumulator —
    jit with donate_argnums on main_grad for true in-place accumulation.
    """
    x = input_.reshape(-1, input_.shape[-1])
    dy = grad_output.reshape(-1, grad_output.shape[-1])
    acc = jax.lax.dot_general(
        dy, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return main_grad + acc


# the _fp16 variant differs only in accumulator dtype upstream; on TPU
# f32 accumulation is free on the MXU, so both names map to one impl
wgrad_gemm_accum_fp16 = wgrad_gemm_accum_fp32


def wgrad_gemm_accum_ref(input_, grad_output, main_grad):
    x = input_.reshape(-1, input_.shape[-1]).astype(jnp.float32)
    dy = grad_output.reshape(-1, grad_output.shape[-1]).astype(jnp.float32)
    return main_grad + dy.T @ x
