"""Rotary position embedding application (reference:
csrc/megatron/fused_rotary_positional_embedding.h/.cpp, SURVEY.md §2.4).

On TPU this op is pure elementwise math that XLA fuses into the
surrounding QKV matmuls, so a hand-written kernel buys nothing; the value
of the reference ext was avoiding CUDA launch+materialization overhead.
We keep the fusion guarantee with a ``jax.custom_vjp`` whose backward
applies the inverse rotation analytically (rotation matrices are
orthogonal: the VJP is rotation by -theta), sidestepping autodiff
residuals entirely — zero saved activations, like the reference's
in-place backward.

Layout matches the reference: t (s, b, np, hn), freqs (s, 1, 1, hn).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rotate_half(t):
    half = t.shape[-1] // 2
    t1 = t[..., :half]
    t2 = t[..., half:]
    return jnp.concatenate([-t2, t1], axis=-1)


def _rotate_half_interleaved(t):
    t1 = t[..., 0::2]
    t2 = t[..., 1::2]
    return jnp.stack([-t2, t1], axis=-1).reshape(t.shape)


def _rotate_half_T(t):
    # transpose of _rotate_half: (u1, u2) -> (u2, -u1)
    half = t.shape[-1] // 2
    return jnp.concatenate([t[..., half:], -t[..., :half]], axis=-1)


def _rotate_half_interleaved_T(t):
    t1 = t[..., 0::2]
    t2 = t[..., 1::2]
    return jnp.stack([t2, -t1], axis=-1).reshape(t.shape)


def _apply(t, cos, sin, interleaved):
    rot = _rotate_half_interleaved(t) if interleaved else _rotate_half(t)
    return t * cos + rot * sin


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_apply_rotary_pos_emb(t, freqs, interleaved=False):
    """t (s, b, np, hn) rotated by freqs (s, 1, 1, hn); rotary dim =
    freqs' last dim (trailing channels pass through, reference
    behavior)."""
    return _rope_fwd(t, freqs, interleaved)[0]


def _split_rotary(t, freqs):
    rot_dim = freqs.shape[-1]
    return t[..., :rot_dim], t[..., rot_dim:]


def _rope_fwd(t, freqs, interleaved):
    t_rot, t_pass = _split_rotary(t, freqs)
    cos = jnp.cos(freqs).astype(t.dtype)
    sin = jnp.sin(freqs).astype(t.dtype)
    y = _apply(t_rot, cos, sin, interleaved)
    out = jnp.concatenate([y, t_pass], axis=-1) if t_pass.shape[-1] else y
    return out, freqs


def _rope_bwd(interleaved, freqs, dy):
    dy_rot, dy_pass = _split_rotary(dy, freqs)
    cos = jnp.cos(freqs).astype(dy.dtype)
    sin = jnp.sin(freqs).astype(dy.dtype)
    # exact transpose of y = (C + S.R) t:  dt = C dy + R^T (S dy)
    rot_T = (_rotate_half_interleaved_T if interleaved else _rotate_half_T)
    dt = dy_rot * cos + rot_T(dy_rot * sin)
    if dy_pass.shape[-1]:
        dt = jnp.concatenate([dt, dy_pass], axis=-1)
    return dt, None


fused_apply_rotary_pos_emb.defvjp(_rope_fwd, _rope_bwd)


def rope_ref(t, freqs, interleaved=False):
    """Autodiff-friendly oracle."""
    t_rot, t_pass = _split_rotary(t, freqs)
    cos = jnp.cos(freqs).astype(t.dtype)
    sin = jnp.sin(freqs).astype(t.dtype)
    y = _apply(t_rot, cos, sin, interleaved)
    return jnp.concatenate([y, t_pass], axis=-1) if t_pass.shape[-1] else y
