"""Fused "foreach" kernels over flat parameter buffers.

TPU-native replacement for the reference's ``amp_C`` extension
(upstream-expected csrc/amp_C_frontend.cpp + multi_tensor_*.cu kernels,
SURVEY.md §2.4): scale with non-finite detection, axpby, L2 norm, and the
optimizer step math (Adam/SGD/...).  The reference chunks a list of CUDA
tensors into one grid launch to amortize launch overhead; the TPU design
concatenates pytree leaves into one flat HBM buffer (see
apex_tpu.multi_tensor_apply) and runs ONE pallas_call whose grid walks
(rows, 128)-shaped VMEM tiles.  All math accumulates in f32 regardless of
storage dtype; non-finite detection is an on-device i32 flag (never a host
sync — the reference's host-side overflow read is a known sync point,
SURVEY.md §3.2).

Every kernel has a pure-jnp oracle (suffix ``_ref``) used for testing and
as the XLA fallback when Pallas is disabled.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import interpret_mode, op_enabled

LANE = 128
SUBLANE = 8
BLOCK_ROWS = 256  # 256x128 f32 = 128 KiB per operand tile


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _as_tiles(x: jax.Array) -> Tuple[jax.Array, int]:
    """Pad a 1-D buffer with zeros and view it as (rows, 128) tiles.

    Rows are padded to a whole grid block so kernels never read
    out-of-bounds garbage (it would poison the non-finite flag).
    """
    n = x.size
    rows = _round_up(max(pl.cdiv(n, LANE), 1), BLOCK_ROWS)
    pad = rows * LANE - n
    if pad:
        x = jnp.pad(x, (0, pad))
    return x.reshape(rows, LANE), n


def _from_tiles(x2d: jax.Array, n: int) -> jax.Array:
    return x2d.reshape(-1)[:n]


def _grid(rows: int) -> int:
    return pl.cdiv(rows, BLOCK_ROWS)


def _vec_spec():
    return pl.BlockSpec((BLOCK_ROWS, LANE), lambda i: (i, 0))


def _scalar_out_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _f32(x):
    return x.astype(jnp.float32)


# ---------------------------------------------------------------------------
# scale (+ non-finite check)   [reference: multi_tensor_scale_kernel.cu]
# ---------------------------------------------------------------------------

def _scale_kernel(s_ref, x_ref, o_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        flag_ref[0] = 0

    x = _f32(x_ref[...])
    y = x * s_ref[0]
    o_ref[...] = y.astype(o_ref.dtype)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(y))).astype(jnp.int32)
    flag_ref[0] = jnp.maximum(flag_ref[0], bad)


def flat_scale(x: jax.Array, scale: jax.Array, out_dtype=None):
    """out = x * scale over a flat buffer; returns (out, found_inf i32).

    found_inf mirrors amp_C.multi_tensor_scale's overflow buffer but stays
    on device.
    """
    out_dtype = out_dtype or x.dtype
    if not op_enabled("multi_tensor"):
        return flat_scale_ref(x, scale, out_dtype)
    x2d, n = _as_tiles(x)
    scale = jnp.asarray([scale], jnp.float32).reshape(1)
    out, flag = pl.pallas_call(
        _scale_kernel,
        grid=(_grid(x2d.shape[0]),),
        in_specs=[_smem_spec(), _vec_spec()],
        out_specs=[_vec_spec(), _scalar_out_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, out_dtype),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        interpret=interpret_mode(),
        name="apex_multi_tensor_scale",
    )(scale, x2d)
    return _from_tiles(out, n), flag[0]


def flat_scale_ref(x, scale, out_dtype=None):
    out_dtype = out_dtype or x.dtype
    y = _f32(x) * jnp.float32(scale)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(y))).astype(jnp.int32)
    return y.astype(out_dtype), bad


# ---------------------------------------------------------------------------
# axpby (+ non-finite check)   [reference: multi_tensor_axpby_kernel.cu]
# ---------------------------------------------------------------------------

def _axpby_kernel(s_ref, x_ref, y_ref, o_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        flag_ref[0] = 0

    r = s_ref[0] * _f32(x_ref[...]) + s_ref[1] * _f32(y_ref[...])
    o_ref[...] = r.astype(o_ref.dtype)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(r))).astype(jnp.int32)
    flag_ref[0] = jnp.maximum(flag_ref[0], bad)


def flat_axpby(a, x: jax.Array, b, y: jax.Array, out_dtype=None):
    """out = a*x + b*y over flat buffers; returns (out, found_inf)."""
    out_dtype = out_dtype or x.dtype
    if not op_enabled("multi_tensor"):
        return flat_axpby_ref(a, x, b, y, out_dtype)
    x2d, n = _as_tiles(x)
    y2d, _ = _as_tiles(y)
    s = jnp.stack([jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)])
    out, flag = pl.pallas_call(
        _axpby_kernel,
        grid=(_grid(x2d.shape[0]),),
        in_specs=[_smem_spec(), _vec_spec(), _vec_spec()],
        out_specs=[_vec_spec(), _scalar_out_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, out_dtype),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        interpret=interpret_mode(),
        name="apex_multi_tensor_axpby",
    )(s, x2d, y2d)
    return _from_tiles(out, n), flag[0]


def flat_axpby_ref(a, x, b, y, out_dtype=None):
    out_dtype = out_dtype or x.dtype
    r = jnp.float32(a) * _f32(x) + jnp.float32(b) * _f32(y)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(r))).astype(jnp.int32)
    return r.astype(out_dtype), bad


# ---------------------------------------------------------------------------
# L2 norm   [reference: multi_tensor_l2norm_kernel.cu]
# ---------------------------------------------------------------------------

def _l2norm_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0] = jnp.float32(0.0)

    x = _f32(x_ref[...])
    acc_ref[0] += jnp.sum(x * x)


def flat_l2norm(x: jax.Array) -> jax.Array:
    """Global L2 norm of a flat buffer (f32 accumulation)."""
    if not op_enabled("multi_tensor"):
        return flat_l2norm_ref(x)
    x2d, _ = _as_tiles(x)
    acc = pl.pallas_call(
        _l2norm_kernel,
        grid=(_grid(x2d.shape[0]),),
        in_specs=[_vec_spec()],
        out_specs=_scalar_out_spec(),
        out_shape=jax.ShapeDtypeStruct((1,), jnp.float32),
        interpret=interpret_mode(),
        name="apex_multi_tensor_l2norm",
    )(x2d)
    return jnp.sqrt(acc[0])


def flat_l2norm_ref(x):
    x = _f32(x)
    return jnp.sqrt(jnp.sum(x * x))


# ---------------------------------------------------------------------------
# Adam / AdamW step   [reference: multi_tensor_adam.cu]
# ---------------------------------------------------------------------------

def _adam_kernel(adam_w_mode, s_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref):
    lr, b1, b2, eps, wd, c1r, c2r, inv_scale = (
        s_ref[0], s_ref[1], s_ref[2], s_ref[3],
        s_ref[4], s_ref[5], s_ref[6], s_ref[7],
    )
    p = _f32(p_ref[...])
    g = _f32(g_ref[...]) * inv_scale
    if not adam_w_mode:  # classic Adam: L2 term folded into the gradient
        g = g + wd * p
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    update = (m * c1r) / (jnp.sqrt(v * c2r) + eps)
    if adam_w_mode:  # decoupled weight decay
        update = update + wd * p
    po_ref[...] = (p - lr * update).astype(po_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def flat_adam(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
              adam_w_mode: bool = True, bias_correction: bool = True,
              grad_scale=1.0):
    """One fused Adam/AdamW step over flat buffers.

    p may be bf16 or f32; m/v must be f32.  ``step`` is the 1-based step
    count (traced scalar ok).  Returns (p, m, v).
    """
    if not op_enabled("multi_tensor"):
        return flat_adam_ref(
            p, g, m, v, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
            weight_decay=weight_decay, step=step, adam_w_mode=adam_w_mode,
            bias_correction=bias_correction, grad_scale=grad_scale)
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        c1r = 1.0 / (1.0 - jnp.asarray(beta1, jnp.float32) ** step)
        c2r = 1.0 / (1.0 - jnp.asarray(beta2, jnp.float32) ** step)
    else:
        c1r = jnp.float32(1.0)
        c2r = jnp.float32(1.0)
    s = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32), c1r, c2r,
        1.0 / jnp.asarray(grad_scale, jnp.float32),
    ])
    p2d, n = _as_tiles(p)
    g2d, _ = _as_tiles(g)
    m2d, _ = _as_tiles(m)
    v2d, _ = _as_tiles(v)
    kernel = functools.partial(_adam_kernel, adam_w_mode)
    po, mo, vo = pl.pallas_call(
        kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_smem_spec()] + [_vec_spec()] * 4,
        out_specs=[_vec_spec()] * 3,
        out_shape=[
            jax.ShapeDtypeStruct(p2d.shape, p.dtype),
            jax.ShapeDtypeStruct(m2d.shape, jnp.float32),
            jax.ShapeDtypeStruct(v2d.shape, jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret_mode(),
        name="apex_multi_tensor_adam",
    )(s, p2d, g2d, m2d, v2d)
    return _from_tiles(po, n), _from_tiles(mo, n), _from_tiles(vo, n)


def flat_adam_ref(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
                  adam_w_mode=True, bias_correction=True, grad_scale=1.0):
    step = jnp.asarray(step, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    if not adam_w_mode:
        gf = gf + wd * pf
    m = b1 * m + (1 - b1) * gf
    v = b2 * v + (1 - b2) * gf * gf
    if bias_correction:
        c1r = 1.0 / (1.0 - b1 ** step)
        c2r = 1.0 / (1.0 - b2 ** step)
    else:
        c1r = c2r = jnp.float32(1.0)
    update = (m * c1r) / (jnp.sqrt(v * c2r) + jnp.asarray(eps, jnp.float32))
    if adam_w_mode:
        update = update + wd * pf
    return (pf - jnp.asarray(lr, jnp.float32) * update).astype(p.dtype), m, v


# ---------------------------------------------------------------------------
# SGD (momentum/nesterov/wd) step   [reference: multi_tensor_sgd_kernel.cu]
# ---------------------------------------------------------------------------

def _sgd_kernel(nesterov, use_momentum, first_run,
                s_ref, p_ref, g_ref, b_ref, po_ref, bo_ref):
    lr, momentum, dampening, wd, inv_scale = (
        s_ref[0], s_ref[1], s_ref[2], s_ref[3], s_ref[4])
    p = _f32(p_ref[...])
    g = _f32(g_ref[...]) * inv_scale + wd * p
    if use_momentum:
        if first_run:
            buf = g
        else:
            buf = momentum * b_ref[...] + (1.0 - dampening) * g
        step_dir = (g + momentum * buf) if nesterov else buf
        bo_ref[...] = buf
    else:
        step_dir = g
        bo_ref[...] = b_ref[...]
    po_ref[...] = (p - lr * step_dir).astype(po_ref.dtype)


def flat_sgd(p, g, momentum_buf, *, lr, momentum=0.0, dampening=0.0,
             weight_decay=0.0, nesterov=False, first_run=False,
             grad_scale=1.0):
    """One fused SGD step over flat buffers; returns (p, momentum_buf)."""
    if not op_enabled("multi_tensor"):
        return flat_sgd_ref(
            p, g, momentum_buf, lr=lr, momentum=momentum, dampening=dampening,
            weight_decay=weight_decay, nesterov=nesterov, first_run=first_run,
            grad_scale=grad_scale)
    s = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(momentum, jnp.float32),
        jnp.asarray(dampening, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        1.0 / jnp.asarray(grad_scale, jnp.float32),
    ])
    p2d, n = _as_tiles(p)
    g2d, _ = _as_tiles(g)
    b2d, _ = _as_tiles(momentum_buf)
    kernel = functools.partial(
        _sgd_kernel, bool(nesterov), momentum != 0.0, bool(first_run))
    po, bo = pl.pallas_call(
        kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_smem_spec()] + [_vec_spec()] * 3,
        out_specs=[_vec_spec()] * 2,
        out_shape=[
            jax.ShapeDtypeStruct(p2d.shape, p.dtype),
            jax.ShapeDtypeStruct(b2d.shape, jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret_mode(),
        name="apex_multi_tensor_sgd",
    )(s, p2d, g2d, b2d)
    return _from_tiles(po, n), _from_tiles(bo, n)


def flat_sgd_ref(p, g, momentum_buf, *, lr, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False, first_run=False,
                 grad_scale=1.0):
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    gf = gf + jnp.asarray(weight_decay, jnp.float32) * pf
    mom = jnp.asarray(momentum, jnp.float32)
    if momentum != 0.0:
        if first_run:
            buf = gf
        else:
            buf = mom * momentum_buf + (1 - jnp.asarray(dampening, jnp.float32)) * gf
        step_dir = gf + mom * buf if nesterov else buf
    else:
        buf = momentum_buf
        step_dir = gf
    return (pf - jnp.asarray(lr, jnp.float32) * step_dir).astype(p.dtype), buf
