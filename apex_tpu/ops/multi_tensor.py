"""Fused "foreach" kernels over flat parameter buffers.

TPU-native replacement for the reference's ``amp_C`` extension
(upstream-expected csrc/amp_C_frontend.cpp + multi_tensor_*.cu kernels,
SURVEY.md §2.4): scale with non-finite detection, axpby, L2 norm, and the
optimizer step math (Adam/SGD/...).  The reference chunks a list of CUDA
tensors into one grid launch to amortize launch overhead; the TPU design
concatenates pytree leaves into one flat HBM buffer (see
apex_tpu.multi_tensor_apply) and runs ONE pallas_call whose grid walks
(rows, 128)-shaped VMEM tiles.  All math accumulates in f32 regardless of
storage dtype; non-finite detection is an on-device i32 flag (never a host
sync — the reference's host-side overflow read is a known sync point,
SURVEY.md §3.2).

Every kernel has a pure-jnp oracle (suffix ``_ref``) used for testing and
as the XLA fallback when Pallas is disabled.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import interpret_mode, op_enabled
from apex_tpu.telemetry import _tape

LANE = 128
SUBLANE = 8
BLOCK_ROWS = 256  # 256x128 f32 = 128 KiB per operand tile


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _as_tiles(x: jax.Array) -> Tuple[jax.Array, int]:
    """Pad a 1-D buffer with zeros and view it as (rows, 128) tiles.

    Rows are padded to a whole grid block so kernels never read
    out-of-bounds garbage (it would poison the non-finite flag).
    """
    n = x.size
    rows = _round_up(max(pl.cdiv(n, LANE), 1), BLOCK_ROWS)
    pad = rows * LANE - n
    if pad:
        x = jnp.pad(x, (0, pad))
    return x.reshape(rows, LANE), n


def _from_tiles(x2d: jax.Array, n: int) -> jax.Array:
    return x2d.reshape(-1)[:n]


def _grid(rows: int) -> int:
    return pl.cdiv(rows, BLOCK_ROWS)


def _vec_spec():
    return pl.BlockSpec((BLOCK_ROWS, LANE), lambda i: (i, 0))


def _scalar_out_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _f32(x):
    return x.astype(jnp.float32)


def _all_finite(x):
    """Kernel-safe finiteness reduction: Mosaic has no is_finite
    lowering, but abs+lt covers it — |nan| < inf and |inf| < inf are
    both False, so the complement flags exactly the non-finite lanes."""
    return jnp.all(jnp.abs(x) < jnp.float32(jnp.inf))


# ---------------------------------------------------------------------------
# scale (+ non-finite check)   [reference: multi_tensor_scale_kernel.cu]
# ---------------------------------------------------------------------------

def _scale_kernel(s_ref, x_ref, o_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        flag_ref[0] = 0

    x = _f32(x_ref[...])
    y = x * s_ref[0]
    o_ref[...] = y.astype(o_ref.dtype)
    bad = jnp.logical_not(_all_finite(y)).astype(jnp.int32)
    flag_ref[0] = jnp.maximum(flag_ref[0], bad)


def flat_scale(x: jax.Array, scale: jax.Array, out_dtype=None):
    """out = x * scale over a flat buffer; returns (out, found_inf i32).

    found_inf mirrors amp_C.multi_tensor_scale's overflow buffer but stays
    on device.
    """
    out_dtype = out_dtype or x.dtype
    if not op_enabled("multi_tensor"):
        return flat_scale_ref(x, scale, out_dtype)
    x2d, n = _as_tiles(x)
    scale = jnp.asarray([scale], jnp.float32).reshape(1)
    out, flag = pl.pallas_call(
        _scale_kernel,
        grid=(_grid(x2d.shape[0]),),
        in_specs=[_smem_spec(), _vec_spec()],
        out_specs=[_vec_spec(), _scalar_out_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, out_dtype),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        interpret=interpret_mode(),
        name="apex_multi_tensor_scale",
    )(scale, x2d)
    return _from_tiles(out, n), flag[0]


def flat_scale_ref(x, scale, out_dtype=None):
    out_dtype = out_dtype or x.dtype
    y = _f32(x) * jnp.float32(scale)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(y))).astype(jnp.int32)
    return y.astype(out_dtype), bad


# ---------------------------------------------------------------------------
# axpby (+ non-finite check)   [reference: multi_tensor_axpby_kernel.cu]
# ---------------------------------------------------------------------------

def _axpby_kernel(s_ref, x_ref, y_ref, o_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        flag_ref[0] = 0

    r = s_ref[0] * _f32(x_ref[...]) + s_ref[1] * _f32(y_ref[...])
    o_ref[...] = r.astype(o_ref.dtype)
    bad = jnp.logical_not(_all_finite(r)).astype(jnp.int32)
    flag_ref[0] = jnp.maximum(flag_ref[0], bad)


def flat_axpby(a, x: jax.Array, b, y: jax.Array, out_dtype=None):
    """out = a*x + b*y over flat buffers; returns (out, found_inf)."""
    out_dtype = out_dtype or x.dtype
    if not op_enabled("multi_tensor"):
        return flat_axpby_ref(a, x, b, y, out_dtype)
    x2d, n = _as_tiles(x)
    y2d, _ = _as_tiles(y)
    s = jnp.stack([jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)])
    out, flag = pl.pallas_call(
        _axpby_kernel,
        grid=(_grid(x2d.shape[0]),),
        in_specs=[_smem_spec(), _vec_spec(), _vec_spec()],
        out_specs=[_vec_spec(), _scalar_out_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, out_dtype),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        interpret=interpret_mode(),
        name="apex_multi_tensor_axpby",
    )(s, x2d, y2d)
    return _from_tiles(out, n), flag[0]


def flat_axpby_ref(a, x, b, y, out_dtype=None):
    out_dtype = out_dtype or x.dtype
    r = jnp.float32(a) * _f32(x) + jnp.float32(b) * _f32(y)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(r))).astype(jnp.int32)
    return r.astype(out_dtype), bad


# ---------------------------------------------------------------------------
# fused gradient accumulation   [reference: the grad-accum loops around
# amp.scale_loss — per-parameter p.grad += micro.grad walks; here ONE
# read-modify-write per bucket into a donated f32 accumulator]
# ---------------------------------------------------------------------------

def _accumulate_kernel(s_ref, a_ref, g_ref, o_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        flag_ref[0] = 0

    r = a_ref[...] + _f32(g_ref[...]) * s_ref[0]
    o_ref[...] = r
    # flag the RESULT: a non-finite microbatch gradient propagates into
    # the sum (inf+x=inf, inf-inf=nan, nan+x=nan), and f32 accumulator
    # overflow is caught too — the per-microbatch latch the step skip
    # needs, from the same HBM sweep as the add
    bad = jnp.logical_not(_all_finite(r)).astype(jnp.int32)
    flag_ref[0] = jnp.maximum(flag_ref[0], bad)


def flat_accumulate(acc: jax.Array, g: jax.Array, scale=1.0):
    """acc += g * scale over flat buffers in ONE read-modify-write.

    ``acc`` is the persistent f32 accumulator bucket (ALIASED to the
    output — inside a jit that donates it, the add is in place, so a
    microbatch accumulation step moves one gradient bucket through HBM
    once and never materializes a per-leaf tree).  ``g`` may be any
    float dtype (bf16 model grads accumulate in f32).  Returns
    ``(new_acc f32, found_inf i32)``; the flag covers the accumulated
    RESULT, so one bad microbatch latches through every later add.
    """
    if acc.dtype != jnp.float32:
        raise ValueError(f"accumulator must be f32, got {acc.dtype}")
    if not op_enabled("multi_tensor"):
        return flat_accumulate_ref(acc, g, scale)
    a2d, n = _as_tiles(acc)
    g2d, _ = _as_tiles(g)
    s = jnp.asarray([scale], jnp.float32).reshape(1)
    out, flag = pl.pallas_call(
        _accumulate_kernel,
        grid=(_grid(a2d.shape[0]),),
        in_specs=[_smem_spec(), _vec_spec(), _vec_spec()],
        out_specs=[_vec_spec(), _scalar_out_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(a2d.shape, jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        input_output_aliases={1: 0},
        interpret=interpret_mode(),
        name="apex_multi_tensor_accumulate",
    )(s, a2d, g2d)
    return _from_tiles(out, n), flag[0]


def flat_accumulate_ref(acc, g, scale=1.0):
    if acc.dtype != jnp.float32:
        raise ValueError(f"accumulator must be f32, got {acc.dtype}")
    r = acc + _f32(g) * jnp.asarray(scale, jnp.float32)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(r))).astype(jnp.int32)
    return r, bad


# ---------------------------------------------------------------------------
# fused unscale + non-finite check + squared-L2   [reference: amp+clip
# issue multi_tensor_scale and multi_tensor_l2norm back-to-back — two
# HBM sweeps; here ONE read feeds all three outputs]
# ---------------------------------------------------------------------------

def _unscale_norm_kernel(s_ref, x_ref, o_ref, acc_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0] = jnp.float32(0.0)
        flag_ref[0] = 0

    y = _f32(x_ref[...]) * s_ref[0]
    o_ref[...] = y.astype(o_ref.dtype)
    acc_ref[0] += jnp.sum(y * y)
    bad = jnp.logical_not(_all_finite(y)).astype(jnp.int32)
    flag_ref[0] = jnp.maximum(flag_ref[0], bad)


def flat_unscale_norm(x: jax.Array, inv_scale, out_dtype=None):
    """out = x * inv_scale over a flat gradient buffer, PLUS the squared
    L2 norm of the unscaled values and the non-finite flag, all from one
    HBM sweep.  Returns (out, norm_sq f32, found_inf i32).

    This is the amp gradient epilogue (unscale_grads + check_finite +
    clip_grad_norm's reduction) collapsed into a single kernel per
    bucket: the caller rss-combines the per-bucket ``norm_sq`` into the
    global norm and max-combines the flags.  The norm is accumulated in
    f32 from the PRE-rounding unscaled values (what the clip math
    wants), and zero padding contributes nothing to either reduction.
    """
    out_dtype = out_dtype or x.dtype
    if not op_enabled("multi_tensor"):
        return flat_unscale_norm_ref(x, inv_scale, out_dtype)
    x2d, n = _as_tiles(x)
    s = jnp.asarray([inv_scale], jnp.float32).reshape(1)
    out, acc, flag = pl.pallas_call(
        _unscale_norm_kernel,
        grid=(_grid(x2d.shape[0]),),
        in_specs=[_smem_spec(), _vec_spec()],
        out_specs=[_vec_spec(), _scalar_out_spec(), _scalar_out_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, out_dtype),
            jax.ShapeDtypeStruct((1,), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        interpret=interpret_mode(),
        name="apex_multi_tensor_unscale_norm",
    )(s, x2d)
    return _from_tiles(out, n), acc[0], flag[0]


def flat_unscale_norm_ref(x, inv_scale, out_dtype=None):
    out_dtype = out_dtype or x.dtype
    y = _f32(x) * jnp.asarray(inv_scale, jnp.float32)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(y))).astype(jnp.int32)
    return y.astype(out_dtype), jnp.sum(y * y), bad


# ---------------------------------------------------------------------------
# L2 norm   [reference: multi_tensor_l2norm_kernel.cu]
# ---------------------------------------------------------------------------

def _l2norm_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0] = jnp.float32(0.0)

    x = _f32(x_ref[...])
    acc_ref[0] += jnp.sum(x * x)


def flat_l2norm(x: jax.Array) -> jax.Array:
    """Global L2 norm of a flat buffer (f32 accumulation)."""
    if not op_enabled("multi_tensor"):
        return flat_l2norm_ref(x)
    x2d, _ = _as_tiles(x)
    acc = pl.pallas_call(
        _l2norm_kernel,
        grid=(_grid(x2d.shape[0]),),
        in_specs=[_vec_spec()],
        out_specs=_scalar_out_spec(),
        out_shape=jax.ShapeDtypeStruct((1,), jnp.float32),
        interpret=interpret_mode(),
        name="apex_multi_tensor_l2norm",
    )(x2d)
    return jnp.sqrt(acc[0])


def flat_l2norm_ref(x):
    x = _f32(x)
    return jnp.sqrt(jnp.sum(x * x))


# ---------------------------------------------------------------------------
# Adam / AdamW step   [reference: multi_tensor_adam.cu]
# ---------------------------------------------------------------------------

def _adam_kernel(adam_w_mode, s_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref):
    lr, b1, b2, eps, wd, c1r, c2r, inv_scale = (
        s_ref[0], s_ref[1], s_ref[2], s_ref[3],
        s_ref[4], s_ref[5], s_ref[6], s_ref[7],
    )
    p = _f32(p_ref[...])
    g = _f32(g_ref[...]) * inv_scale
    if not adam_w_mode:  # classic Adam: L2 term folded into the gradient
        g = g + wd * p
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    update = (m * c1r) / (jnp.sqrt(v * c2r) + eps)
    if adam_w_mode:  # decoupled weight decay
        update = update + wd * p
    po_ref[...] = (p - lr * update).astype(po_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def flat_adam(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
              adam_w_mode: bool = True, bias_correction: bool = True,
              grad_scale=1.0):
    """One fused Adam/AdamW step over flat buffers.

    p may be bf16 or f32; m/v must be f32.  ``step`` is the 1-based step
    count (traced scalar ok).  Returns (p, m, v).
    """
    if not op_enabled("multi_tensor"):
        return flat_adam_ref(
            p, g, m, v, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
            weight_decay=weight_decay, step=step, adam_w_mode=adam_w_mode,
            bias_correction=bias_correction, grad_scale=grad_scale)
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        c1r = 1.0 / (1.0 - jnp.asarray(beta1, jnp.float32) ** step)
        c2r = 1.0 / (1.0 - jnp.asarray(beta2, jnp.float32) ** step)
    else:
        c1r = jnp.float32(1.0)
        c2r = jnp.float32(1.0)
    s = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32), c1r, c2r,
        1.0 / jnp.asarray(grad_scale, jnp.float32),
    ])
    p2d, n = _as_tiles(p)
    g2d, _ = _as_tiles(g)
    m2d, _ = _as_tiles(m)
    v2d, _ = _as_tiles(v)
    kernel = functools.partial(_adam_kernel, adam_w_mode)
    po, mo, vo = pl.pallas_call(
        kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_smem_spec()] + [_vec_spec()] * 4,
        out_specs=[_vec_spec()] * 3,
        out_shape=[
            jax.ShapeDtypeStruct(p2d.shape, p.dtype),
            jax.ShapeDtypeStruct(m2d.shape, jnp.float32),
            jax.ShapeDtypeStruct(v2d.shape, jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret_mode(),
        name="apex_multi_tensor_adam",
    )(s, p2d, g2d, m2d, v2d)
    return _from_tiles(po, n), _from_tiles(mo, n), _from_tiles(vo, n)


def flat_adam_ref(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
                  adam_w_mode=True, bias_correction=True, grad_scale=1.0):
    step = jnp.asarray(step, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    if not adam_w_mode:
        gf = gf + wd * pf
    m = b1 * m + (1 - b1) * gf
    v = b2 * v + (1 - b2) * gf * gf
    if bias_correction:
        c1r = 1.0 / (1.0 - b1 ** step)
        c2r = 1.0 / (1.0 - b2 ** step)
    else:
        c1r = c2r = jnp.float32(1.0)
    update = (m * c1r) / (jnp.sqrt(v * c2r) + jnp.asarray(eps, jnp.float32))
    if adam_w_mode:
        update = update + wd * pf
    return (pf - jnp.asarray(lr, jnp.float32) * update).astype(p.dtype), m, v


# ---------------------------------------------------------------------------
# SGD (momentum/nesterov/wd) step   [reference: multi_tensor_sgd_kernel.cu]
# ---------------------------------------------------------------------------

def _sgd_kernel(nesterov, use_momentum,
                s_ref, p_ref, g_ref, b_ref, po_ref, bo_ref):
    lr, momentum, dampening, wd, inv_scale, first = (
        s_ref[0], s_ref[1], s_ref[2], s_ref[3], s_ref[4], s_ref[5])
    p = _f32(p_ref[...])
    g = _f32(g_ref[...]) * inv_scale + wd * p
    if use_momentum:
        # first_run may be traced (step == 1 inside a jitted facade
        # step): select instead of Python-branching
        buf = jnp.where(first > 0, g,
                        momentum * b_ref[...] + (1.0 - dampening) * g)
        step_dir = (g + momentum * buf) if nesterov else buf
        bo_ref[...] = buf
    else:
        step_dir = g
        bo_ref[...] = b_ref[...]
    po_ref[...] = (p - lr * step_dir).astype(po_ref.dtype)


def flat_sgd(p, g, momentum_buf, *, lr, momentum=0.0, dampening=0.0,
             weight_decay=0.0, nesterov=False, first_run=False,
             grad_scale=1.0):
    """One fused SGD step over flat buffers; returns (p, momentum_buf).

    ``first_run`` may be a Python bool or a traced bool scalar."""
    if not op_enabled("multi_tensor"):
        return flat_sgd_ref(
            p, g, momentum_buf, lr=lr, momentum=momentum, dampening=dampening,
            weight_decay=weight_decay, nesterov=nesterov, first_run=first_run,
            grad_scale=grad_scale)
    s = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(momentum, jnp.float32),
        jnp.asarray(dampening, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        1.0 / jnp.asarray(grad_scale, jnp.float32),
        jnp.asarray(first_run, jnp.float32),
    ])
    p2d, n = _as_tiles(p)
    g2d, _ = _as_tiles(g)
    b2d, _ = _as_tiles(momentum_buf)
    kernel = functools.partial(
        _sgd_kernel, bool(nesterov), momentum != 0.0)
    po, bo = pl.pallas_call(
        kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_smem_spec()] + [_vec_spec()] * 3,
        out_specs=[_vec_spec()] * 2,
        out_shape=[
            jax.ShapeDtypeStruct(p2d.shape, p.dtype),
            jax.ShapeDtypeStruct(b2d.shape, jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret_mode(),
        name="apex_multi_tensor_sgd",
    )(s, p2d, g2d, b2d)
    return _from_tiles(po, n), _from_tiles(bo, n)


def flat_sgd_ref(p, g, momentum_buf, *, lr, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False, first_run=False,
                 grad_scale=1.0):
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    gf = gf + jnp.asarray(weight_decay, jnp.float32) * pf
    mom = jnp.asarray(momentum, jnp.float32)
    if momentum != 0.0:
        # first_run may be traced: select, don't branch
        buf = jnp.where(
            jnp.asarray(first_run, jnp.bool_), gf,
            mom * momentum_buf
            + (1 - jnp.asarray(dampening, jnp.float32)) * gf)
        step_dir = gf + mom * buf if nesterov else buf
    else:
        buf = momentum_buf
        step_dir = gf
    return (pf - jnp.asarray(lr, jnp.float32) * step_dir).astype(p.dtype), buf


# ---------------------------------------------------------------------------
# Adagrad step   [reference: multi_tensor_adagrad.cu]
# ---------------------------------------------------------------------------

def _adagrad_kernel(s_ref, p_ref, g_ref, h_ref, po_ref, ho_ref):
    lr, eps, wd, inv_scale = s_ref[0], s_ref[1], s_ref[2], s_ref[3]
    p = _f32(p_ref[...])
    g = _f32(g_ref[...]) * inv_scale + wd * p
    h = h_ref[...] + g * g
    ho_ref[...] = h
    po_ref[...] = (p - lr * g / (jnp.sqrt(h) + eps)).astype(po_ref.dtype)


def flat_adagrad(p, g, h, *, lr, eps, weight_decay=0.0, grad_scale=1.0):
    """One fused Adagrad step over flat buffers; returns (p, h).

    h is the running sum of squared (decayed) gradients, f32.
    """
    if not op_enabled("multi_tensor"):
        return flat_adagrad_ref(p, g, h, lr=lr, eps=eps,
                                weight_decay=weight_decay,
                                grad_scale=grad_scale)
    s = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        1.0 / jnp.asarray(grad_scale, jnp.float32),
    ])
    p2d, n = _as_tiles(p)
    g2d, _ = _as_tiles(g)
    h2d, _ = _as_tiles(h)
    po, ho = pl.pallas_call(
        _adagrad_kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_smem_spec()] + [_vec_spec()] * 3,
        out_specs=[_vec_spec()] * 2,
        out_shape=[
            jax.ShapeDtypeStruct(p2d.shape, p.dtype),
            jax.ShapeDtypeStruct(h2d.shape, jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret_mode(),
        name="apex_multi_tensor_adagrad",
    )(s, p2d, g2d, h2d)
    return _from_tiles(po, n), _from_tiles(ho, n)


def flat_adagrad_ref(p, g, h, *, lr, eps, weight_decay=0.0, grad_scale=1.0):
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    gf = gf + jnp.asarray(weight_decay, jnp.float32) * pf
    h = h + gf * gf
    return (pf - jnp.asarray(lr, jnp.float32) * gf /
            (jnp.sqrt(h) + jnp.asarray(eps, jnp.float32))).astype(p.dtype), h


# ---------------------------------------------------------------------------
# segmented reductions over a bucket (per-TENSOR norms inside one flat
# buffer; segment ids come from the bucket plan and are SORTED because
# leaves are concatenated in order)
# ---------------------------------------------------------------------------

def flat_segment_sumsq(x, seg_ids, num_segments: int):
    """Per-segment sum of squares of a flat buffer, f32 accumulation.

    One XLA sorted-segment reduce — not a per-leaf loop; the elementwise
    heavy lifting around it stays in the flat Pallas kernels."""
    xf = _f32(x)
    return jax.ops.segment_sum(xf * xf, seg_ids,
                               num_segments=num_segments,
                               indices_are_sorted=True)


def flat_segment_absmax(x, seg_ids, num_segments: int):
    """Per-segment max(|x|) of a flat buffer, f32 accumulation.

    One XLA sorted-segment reduce per bucket — the per-TENSOR amax the
    fp8 delayed-scaling state needs, from the same segment metadata the
    LAMB/NovoGrad kernels already use.  Non-finite elements propagate
    (|nan| is nan, |inf| is inf) so the caller's overflow detection
    sees them."""
    return jax.ops.segment_max(jnp.abs(_f32(x)), seg_ids,
                               num_segments=num_segments,
                               indices_are_sorted=True)


# ---------------------------------------------------------------------------
# fused fp8 amax + delayed-scale update   [beyond-reference: the
# transformer-engine delayed-scaling recipe collapsed to ONE flat pass
# per bucket — per-tensor amax via a sorted-segment reduce, history
# roll, scale recompute and per-tensor overflow backoff all from that
# single sweep, never a per-leaf tree_map]
# ---------------------------------------------------------------------------

def flat_amax_scale_update(buf, seg_ids, num_segments: int,
                           amax_history, scale, *, fp8_max,
                           margin: float = 0.0,
                           backoff_factor: float = 0.5,
                           max_scale: float = 2.0 ** 24,
                           min_scale: float = 2.0 ** -24,
                           update=True):
    """One bucket's fp8 delayed-scaling bookkeeping in a single flat
    pass.  ``buf``: the bucket's flat buffer (any float dtype);
    ``amax_history``: (num_segments, H) f32, column 0 newest;
    ``scale``: (num_segments,) f32 — the CURRENT quantization scales
    (value * scale fills the fp8 range).

    Per segment (= per tensor): amax of this step's values rolls into
    the history; the new scale is ``fp8_max / (2**margin *
    max(history))`` clipped to [min_scale, max_scale].  A segment
    whose amax is NON-FINITE is an overflow: its history holds (inf
    must never poison the window) and its scale backs off by
    ``backoff_factor`` — the loss scaler's backoff discipline layered
    per bucket.  A segment with no signal yet (all-zero history)
    keeps its old scale.  ``update`` (bool, traced ok) gates the
    whole transition — False returns the inputs unchanged (the
    scale-update-interval cadence and the external step-skip both
    ride it).

    Returns ``(new_history, new_scale, found_inf i32)`` where
    found_inf flags ANY non-finite amax in the bucket.
    """
    if not op_enabled("multi_tensor"):
        return flat_amax_scale_update_ref(
            buf, seg_ids, num_segments, amax_history, scale,
            fp8_max=fp8_max, margin=margin,
            backoff_factor=backoff_factor, max_scale=max_scale,
            min_scale=min_scale, update=update)
    amax = flat_segment_absmax(buf, seg_ids, num_segments)
    return _amax_scale_math(amax, amax_history, scale, fp8_max, margin,
                            backoff_factor, max_scale, min_scale,
                            update)


def flat_amax_scale_update_ref(buf, seg_ids, num_segments: int,
                               amax_history, scale, *, fp8_max,
                               margin: float = 0.0,
                               backoff_factor: float = 0.5,
                               max_scale: float = 2.0 ** 24,
                               min_scale: float = 2.0 ** -24,
                               update=True):
    """Oracle: per-segment amax via scatter-max instead of the sorted
    segment reduce; identical update math (bit-exact by test)."""
    amax = jnp.zeros((num_segments,), jnp.float32).at[seg_ids].max(
        jnp.abs(_f32(buf)))
    return _amax_scale_math(amax, amax_history, scale, fp8_max, margin,
                            backoff_factor, max_scale, min_scale,
                            update)


def _amax_scale_math(amax, amax_history, scale, fp8_max, margin,
                     backoff_factor, max_scale, min_scale, update):
    """The ONE delayed-scaling transition (kernel and ref paths, and
    the per-leaf oracle in amp.fp8, all funnel here so the
    bookkeeping cannot drift between layouts).

    ``update`` gates the CLEAN transition (history roll + scale
    recompute: the interval cadence, external skips).  An overflowed
    segment is handled like the loss scaler handles overflow — the
    backoff applies EVEN on a gated step (overflow response must not
    wait for the cadence), while its history always holds (inf must
    never poison the window)."""
    fmax = jnp.asarray(fp8_max, jnp.float32)
    bad_seg = jnp.logical_not(jnp.abs(amax) < jnp.float32(jnp.inf))
    found_inf = jnp.any(bad_seg).astype(jnp.int32)
    safe_amax = jnp.where(bad_seg, jnp.float32(0.0), amax)
    rolled = jnp.concatenate(
        [safe_amax[:, None], amax_history[:, :-1]], axis=1)
    amax_max = jnp.max(rolled, axis=1)
    recomputed = jnp.where(
        amax_max > 0,
        jnp.clip(fmax / (jnp.float32(2.0) ** jnp.asarray(
            margin, jnp.float32) * amax_max),
            jnp.asarray(min_scale, jnp.float32),
            jnp.asarray(max_scale, jnp.float32)),
        scale)
    upd = jnp.asarray(update, jnp.bool_)
    hold = jnp.logical_or(bad_seg, jnp.logical_not(upd))
    new_hist = jnp.where(hold[:, None], amax_history, rolled)
    new_scale = jnp.where(upd, recomputed, scale)
    new_scale = jnp.where(
        bad_seg,
        jnp.maximum(scale * jnp.asarray(backoff_factor, jnp.float32),
                    jnp.asarray(min_scale, jnp.float32)),
        new_scale)
    return new_hist, new_scale, found_inf


# ---------------------------------------------------------------------------
# NovoGrad step (segmented)   [reference: multi_tensor_novograd.cu]
# ---------------------------------------------------------------------------

def _novograd_apply_kernel(grad_averaging, reg_inside_moment,
                           s_ref, p_ref, g_ref, m_ref, d_ref,
                           po_ref, mo_ref):
    lr, b1, wd, inv_scale, first = (
        s_ref[0], s_ref[1], s_ref[2], s_ref[3], s_ref[4])
    p = _f32(p_ref[...])
    gn = _f32(g_ref[...]) * inv_scale * d_ref[...]
    if reg_inside_moment:
        gn = gn + wd * p
    coeff = (1.0 - b1) if grad_averaging else 1.0
    m = jnp.where(first > 0, gn, b1 * m_ref[...] + coeff * gn)
    mo_ref[...] = m
    update = m if reg_inside_moment else m + wd * p
    po_ref[...] = (p - lr * update).astype(po_ref.dtype)


def flat_novograd(p, g, m, v_seg, seg_ids, *, lr, beta1, beta2, eps,
                  weight_decay=0.0, first_run=False, grad_averaging=True,
                  init_zero=False, reg_inside_moment=False, grad_scale=1.0):
    """One fused NovoGrad step over a flat bucket; returns (p, m, v_seg).

    ``v_seg`` is the per-TENSOR second moment, one f32 scalar per bucket
    segment (shape ``(num_segments,)``); ``seg_ids`` maps each element of
    the flat buffer to its segment (sorted, from the bucket plan).  The
    per-segment gradient norms are one sorted-segment reduce; the
    normalizer reaches the elementwise Pallas kernel as a gathered
    per-element buffer, so the heavy math is still one grid launch.
    ``first_run`` may be a Python bool or a traced bool scalar.
    """
    if not op_enabled("multi_tensor"):
        return flat_novograd_ref(
            p, g, m, v_seg, seg_ids, lr=lr, beta1=beta1, beta2=beta2,
            eps=eps, weight_decay=weight_decay, first_run=first_run,
            grad_averaging=grad_averaging, init_zero=init_zero,
            reg_inside_moment=reg_inside_moment, grad_scale=grad_scale)
    num_seg = v_seg.shape[0]
    inv_scale = 1.0 / jnp.asarray(grad_scale, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    first = jnp.asarray(first_run, jnp.bool_)
    g_norm_sq = flat_segment_sumsq(_f32(g) * inv_scale, seg_ids, num_seg)
    if init_zero:
        v_new = jnp.where(first, (1 - b2) * g_norm_sq,
                          b2 * v_seg + (1 - b2) * g_norm_sq)
    else:
        v_new = jnp.where(first, g_norm_sq,
                          b2 * v_seg + (1 - b2) * g_norm_sq)
    inv_denom = 1.0 / (jnp.sqrt(v_new) + jnp.asarray(eps, jnp.float32))
    d_elem = inv_denom[seg_ids]              # one gather, not per leaf
    s = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32), inv_scale,
        jnp.asarray(first, jnp.float32),
    ])
    p2d, n = _as_tiles(p)
    g2d, _ = _as_tiles(g)
    m2d, _ = _as_tiles(m)
    d2d, _ = _as_tiles(d_elem)
    kernel = functools.partial(_novograd_apply_kernel,
                               bool(grad_averaging),
                               bool(reg_inside_moment))
    po, mo = pl.pallas_call(
        kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_smem_spec()] + [_vec_spec()] * 4,
        out_specs=[_vec_spec()] * 2,
        out_shape=[
            jax.ShapeDtypeStruct(p2d.shape, p.dtype),
            jax.ShapeDtypeStruct(m2d.shape, jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret_mode(),
        name="apex_multi_tensor_novograd",
    )(s, p2d, g2d, m2d, d2d)
    return _from_tiles(po, n), _from_tiles(mo, n), v_new


def flat_novograd_ref(p, g, m, v_seg, seg_ids, *, lr, beta1, beta2, eps,
                      weight_decay=0.0, first_run=False,
                      grad_averaging=True, init_zero=False,
                      reg_inside_moment=False, grad_scale=1.0):
    num_seg = v_seg.shape[0]
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    first = jnp.asarray(first_run, jnp.bool_)
    g_norm_sq = flat_segment_sumsq(gf, seg_ids, num_seg)
    if init_zero:
        v_new = jnp.where(first, (1 - b2) * g_norm_sq,
                          b2 * v_seg + (1 - b2) * g_norm_sq)
    else:
        v_new = jnp.where(first, g_norm_sq,
                          b2 * v_seg + (1 - b2) * g_norm_sq)
    denom = jnp.sqrt(v_new) + jnp.asarray(eps, jnp.float32)
    gn = gf / denom[seg_ids]
    if reg_inside_moment:
        gn = gn + wd * pf
    coeff = (1 - b1) if grad_averaging else jnp.float32(1.0)
    m = jnp.where(first, gn, b1 * m + coeff * gn)
    update = m if reg_inside_moment else m + wd * pf
    return ((pf - jnp.asarray(lr, jnp.float32) * update).astype(p.dtype),
            m, v_new)


# ---------------------------------------------------------------------------
# LAMB step (segmented)   [reference: multi_tensor_lamb.cu stage1+stage2]
# ---------------------------------------------------------------------------

def _lamb_moment_kernel(s_ref, p_ref, g_ref, m_ref, v_ref,
                        mo_ref, vo_ref, uo_ref):
    b1, b2, eps, wd, c1r, c2r, gmul = (
        s_ref[0], s_ref[1], s_ref[2], s_ref[3],
        s_ref[4], s_ref[5], s_ref[6])
    p = _f32(p_ref[...])
    g = _f32(g_ref[...]) * gmul
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    mo_ref[...] = m
    vo_ref[...] = v
    uo_ref[...] = (m * c1r) / (jnp.sqrt(v * c2r) + eps) + wd * p


def _apply_update_kernel(p_ref, u_ref, f_ref, po_ref):
    po_ref[...] = (_f32(p_ref[...])
                   - f_ref[...] * u_ref[...]).astype(po_ref.dtype)


def flat_lamb(p, g, m, v, seg_ids, num_segments: int, *, lr, beta1, beta2,
              eps, weight_decay=0.0, step=1, bias_correction=True,
              grad_scale=1.0, clip_coeff=1.0, use_nvlamb=False):
    """One fused LAMB step over a flat bucket; returns (p, m, v).

    Two grid launches per bucket (the reference's stage1+stage2 shape):
    moments + unscaled update, then the trust-ratio-scaled apply.  The
    per-TENSOR trust ratio ||p||/||update|| is computed from bucket
    ``seg_ids`` with one sorted-segment reduce per norm — per-tensor
    semantics preserved without per-tensor kernels.  ``clip_coeff`` is
    the precomputed global-grad-norm clip factor (stage-1 side input).
    """
    step = jnp.asarray(step, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    if bias_correction:
        c1r = 1.0 / (1.0 - b1 ** step)
        c2r = 1.0 / (1.0 - b2 ** step)
    else:
        c1r = c2r = jnp.float32(1.0)
    gmul = (jnp.asarray(clip_coeff, jnp.float32)
            / jnp.asarray(grad_scale, jnp.float32))
    if not op_enabled("multi_tensor"):
        return flat_lamb_ref(
            p, g, m, v, seg_ids, num_segments, lr=lr, beta1=beta1,
            beta2=beta2, eps=eps, weight_decay=weight_decay, step=step,
            bias_correction=bias_correction, grad_scale=grad_scale,
            clip_coeff=clip_coeff, use_nvlamb=use_nvlamb)
    s = jnp.stack([b1, b2, jnp.asarray(eps, jnp.float32), wd,
                   c1r, c2r, gmul])
    p2d, n = _as_tiles(p)
    g2d, _ = _as_tiles(g)
    m2d, _ = _as_tiles(m)
    v2d, _ = _as_tiles(v)
    mo, vo, update2d = pl.pallas_call(
        _lamb_moment_kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_smem_spec()] + [_vec_spec()] * 4,
        out_specs=[_vec_spec()] * 3,
        out_shape=[
            jax.ShapeDtypeStruct(m2d.shape, jnp.float32),
            jax.ShapeDtypeStruct(v2d.shape, jnp.float32),
            jax.ShapeDtypeStruct(p2d.shape, jnp.float32),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret_mode(),
        name="apex_multi_tensor_lamb_moments",
    )(s, p2d, g2d, m2d, v2d)
    update = _from_tiles(update2d, n)
    factor_elem = _lamb_trust_factor(p, update, seg_ids, num_segments,
                                     lr, wd, use_nvlamb)
    f2d, _ = _as_tiles(factor_elem)
    u2d, _ = _as_tiles(update)
    po = pl.pallas_call(
        _apply_update_kernel,
        grid=(_grid(p2d.shape[0]),),
        in_specs=[_vec_spec()] * 3,
        out_specs=_vec_spec(),
        out_shape=jax.ShapeDtypeStruct(p2d.shape, p.dtype),
        input_output_aliases={0: 0},
        interpret=interpret_mode(),
        name="apex_multi_tensor_lamb_apply",
    )(p2d, u2d, f2d)
    return _from_tiles(po, n), _from_tiles(mo, n), _from_tiles(vo, n)


def _lamb_trust_factor(p, update, seg_ids, num_segments, lr, wd,
                       use_nvlamb):
    """Per-element lr*trust buffer from per-segment norms (one gather)."""
    p_norm = jnp.sqrt(flat_segment_sumsq(p, seg_ids, num_segments))
    u_norm_sq = flat_segment_sumsq(update, seg_ids, num_segments)
    u_norm = jnp.sqrt(u_norm_sq)
    trust = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0)
    if not use_nvlamb:
        # standard LAMB exempts decay-free tensors from layer adaptation;
        # NVLAMB applies the trust ratio to every layer
        trust = jnp.where(wd == 0.0, jnp.float32(1.0), trust)
    # telemetry from the reductions that already exist (both the kernel
    # and ref paths come through here) — per-bucket emissions combine
    # across buckets: max for the trust ratio, root-sum-square for the
    # update norm.  No extra HBM sweep: u_norm_sq is (num_segments,).
    _tape.emit("optim/max_trust_ratio", jnp.max(trust), reduce="max")
    _tape.emit("optim/update_norm", jnp.sqrt(jnp.sum(u_norm_sq)),
               reduce="rss")
    return (jnp.asarray(lr, jnp.float32) * trust)[seg_ids]


def flat_lamb_ref(p, g, m, v, seg_ids, num_segments: int, *, lr, beta1,
                  beta2, eps, weight_decay=0.0, step=1,
                  bias_correction=True, grad_scale=1.0, clip_coeff=1.0,
                  use_nvlamb=False):
    step = jnp.asarray(step, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    pf = _f32(p)
    gf = _f32(g) * (jnp.asarray(clip_coeff, jnp.float32)
                    / jnp.asarray(grad_scale, jnp.float32))
    m = b1 * m + (1 - b1) * gf
    v = b2 * v + (1 - b2) * gf * gf
    if bias_correction:
        c1r = 1.0 / (1.0 - b1 ** step)
        c2r = 1.0 / (1.0 - b2 ** step)
    else:
        c1r = c2r = jnp.float32(1.0)
    update = (m * c1r) / (jnp.sqrt(v * c2r)
                          + jnp.asarray(eps, jnp.float32)) + wd * pf
    factor = _lamb_trust_factor(pf, update, seg_ids, num_segments,
                                lr, wd, use_nvlamb)
    return (pf - factor * update).astype(p.dtype), m, v
