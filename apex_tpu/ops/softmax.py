"""Scaled (masked) softmax kernels (reference: csrc/megatron/
scaled_masked_softmax*.cu, scaled_upper_triang_masked_softmax*.cu,
generic_scaled_masked_softmax*, SURVEY.md §2.4).

Attention-shaped row softmax with scale and masking fused in: one VMEM
pass computes max/shift/exp/sum/normalize per row; the causal variant
builds its triangular mask from iota inside the kernel (no mask tensor in
HBM at all — the reference materializes none either).  Fully-masked rows
output ZEROS, as the reference kernel does.  Backward is the standard
softmax VJP fused the same way, consuming the saved output (zero rows
propagate zero grads automatically).

The (b, 1, sq, sk) attention mask is NOT broadcast across heads in HBM:
the kernel's BlockSpec index map routes each (head, query-block) to the
matching mask block, so the mask is read np-times from the same memory
instead of copied np-fold.

Layouts match the reference:
  scaled_masked_softmax:             x (b, np, sq, sk), mask (b, 1, sq, sk)
  scaled_upper_triang_masked_softmax: x (attn_batches, sq, sq)

Fallback to pure XLA for shapes outside the kernel's tiling envelope.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import interpret_mode, op_enabled

LANE = 128
_MAX_SK = 4096          # sk*4B*block_rows must fit VMEM comfortably
_NEG = -10000.0         # reference mask fill value


def _block_rows_cap(sk: int) -> int:
    rows = max(8, min(256, (512 * 1024) // (sk * 4)))
    return rows - rows % 8


def _divisor_block(sq: int, cap: int) -> int:
    """Largest multiple of 8 that divides sq, at most cap (0 if none)."""
    br = min(cap, sq)
    br -= br % 8
    while br >= 8:
        if sq % br == 0:
            return br
        br -= 8
    return 0


def _use_pallas(sk: int) -> bool:
    return op_enabled("softmax") and sk % LANE == 0 and sk <= _MAX_SK


def _finish_rows(x):
    """Row softmax in f32 with fully-masked rows forced to zero."""
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=1, keepdims=True)
    alive = m > (_NEG / 2)
    return jnp.where(alive, e / s, 0.0)


def _masked_fwd_kernel(scale, x_ref, m_ref, y_ref):
    x = x_ref[...].astype(jnp.float32) * scale
    x = jnp.where(m_ref[...] != 0, _NEG, x)
    y_ref[...] = _finish_rows(x).astype(y_ref.dtype)


def _plain_fwd_kernel(scale, causal, sq, x_ref, y_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32) * scale
    br, sk = x.shape
    if causal:
        row_ids = (i * br + jax.lax.broadcasted_iota(
            jnp.int32, (br, sk), 0)) % sq
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (br, sk), 1)
        x = jnp.where(col_ids > row_ids, _NEG, x)
    y_ref[...] = _finish_rows(x).astype(y_ref.dtype)


def _softmax_bwd_kernel(scale, y_ref, dy_ref, dx_ref):
    y = y_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    inner = jnp.sum(y * dy, axis=1, keepdims=True)
    dx_ref[...] = ((dy - inner) * y * scale).astype(dx_ref.dtype)


def _rows_call(kernel, out_dtype, x2d, br):
    """Grid over row blocks of a (rows, sk) array, no extra operands."""
    rows, sk = x2d.shape
    padded = (rows + br - 1) // br * br
    xp = jnp.pad(x2d, ((0, padded - rows), (0, 0)))
    out = pl.pallas_call(
        kernel,
        grid=(padded // br,),
        in_specs=[pl.BlockSpec((br, sk), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, sk), out_dtype),
        interpret=interpret_mode(),
        name="apex_scaled_softmax",
    )(xp)
    return out[:rows]


# ---------------------------------------------------------------------------
# public ops with custom_vjp
# ---------------------------------------------------------------------------

def _check_static_scale(scale):
    """scale is a compile-time constant (custom_vjp nondiff arg, like the
    reference's Python-float attribute); jitting the raw op with scale as
    a traced argument would die deep in custom_vjp with an opaque
    UnexpectedTracerError — fail early with the fix instead."""
    if isinstance(scale, jax.core.Tracer):
        raise TypeError(
            "scale must be a static Python number (it is non-"
            "differentiable); when jitting this op directly, mark it "
            "static: jax.jit(fn, static_argnums=(<scale position>,))")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _scaled_masked_softmax_p(x, mask, scale):
    return _sms_fwd(x, mask, scale)[0]


def scaled_masked_softmax(x, mask, scale):
    """softmax(x*scale masked_fill(mask, -10000)) over the last dim.

    x: (b, np, sq, sk); mask: (b, 1, sq, sk) with nonzero = masked, or
    None.  scale: static Python number.  Reference:
    scaled_masked_softmax_cuda.forward.
    """
    _check_static_scale(scale)
    return _scaled_masked_softmax_p(x, mask, scale)


def _sms_fwd(x, mask, scale):
    b, np_, sq, sk = x.shape
    if not _use_pallas(sk):
        y = scaled_masked_softmax_ref(x, mask, scale)
        return y, y
    if mask is None:
        kern = functools.partial(_plain_fwd_kernel, scale, False, sq)
        y = _rows_call(kern, x.dtype, x.reshape(-1, sk),
                       _block_rows_cap(sk)).reshape(x.shape)
        return y, y
    br = _divisor_block(sq, _block_rows_cap(sk))
    if br == 0:
        y = scaled_masked_softmax_ref(x, mask, scale)
        return y, y
    # mask stays (b*sq, sk); each (head, q-block) indexes its mask block
    blocks_per_head = sq // br
    m2d = mask.reshape(b * sq, sk).astype(jnp.int32)

    def mask_index(i):
        head = i // blocks_per_head        # in [0, b*np)
        b_idx = head // np_
        return (b_idx * blocks_per_head + i % blocks_per_head, 0)

    y2d = pl.pallas_call(
        functools.partial(_masked_fwd_kernel, scale),
        grid=(b * np_ * blocks_per_head,),
        in_specs=[pl.BlockSpec((br, sk), lambda i: (i, 0)),
                  pl.BlockSpec((br, sk), mask_index)],
        out_specs=pl.BlockSpec((br, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * np_ * sq, sk), x.dtype),
        interpret=interpret_mode(),
        name="apex_scaled_masked_softmax",
    )(x.reshape(-1, sk), m2d)
    y = y2d.reshape(x.shape)
    return y, y


def _sms_bwd(scale, y, dy):
    return _softmax_vjp(y, dy, scale), None


def _softmax_vjp(y, dy, scale):
    sk = y.shape[-1]
    if not _use_pallas(sk):
        yf = y.astype(jnp.float32)
        dyf = dy.astype(jnp.float32)
        inner = jnp.sum(yf * dyf, axis=-1, keepdims=True)
        return ((dyf - inner) * yf * scale).astype(y.dtype)
    br = _block_rows_cap(sk)
    rows = y.size // sk
    padded = (rows + br - 1) // br * br
    y2 = jnp.pad(y.reshape(-1, sk), ((0, padded - rows), (0, 0)))
    dy2 = jnp.pad(dy.reshape(-1, sk), ((0, padded - rows), (0, 0)))
    dx = pl.pallas_call(
        functools.partial(_softmax_bwd_kernel, scale),
        grid=(padded // br,),
        in_specs=[pl.BlockSpec((br, sk), lambda i: (i, 0))] * 2,
        out_specs=pl.BlockSpec((br, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, sk), y.dtype),
        interpret=interpret_mode(),
        name="apex_scaled_softmax_bwd",
    )(y2, dy2)
    return dx[:rows].reshape(y.shape)


_scaled_masked_softmax_p.defvjp(_sms_fwd, _sms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scaled_upper_triang_masked_softmax_p(x, scale):
    return _suts_fwd(x, scale)[0]


def scaled_upper_triang_masked_softmax(x, scale):
    """Causal softmax(x*scale) for (attn_batches, sq, sq) inputs.
    scale: static Python number.  Reference:
    scaled_upper_triang_masked_softmax_cuda.forward."""
    _check_static_scale(scale)
    return _scaled_upper_triang_masked_softmax_p(x, scale)


def _suts_fwd(x, scale):
    ab, sq, sk = x.shape
    assert sq == sk, "upper-triang variant requires square attention"
    br = _divisor_block(sq, _block_rows_cap(sk))
    if _use_pallas(sk) and br:
        kern = functools.partial(_plain_fwd_kernel, scale, True, sq)
        y = _rows_call(kern, x.dtype, x.reshape(-1, sk), br
                       ).reshape(x.shape)
    else:
        y = scaled_upper_triang_masked_softmax_ref(x, scale)
    return y, y


def _suts_bwd(scale, y, dy):
    # masked entries have y == 0, so dx is already zero there
    return (_softmax_vjp(y, dy, scale),)


_scaled_upper_triang_masked_softmax_p.defvjp(_suts_fwd, _suts_bwd)


# ---------------------------------------------------------------------------
# XLA oracles / fallbacks (same fully-masked-row semantics)
# ---------------------------------------------------------------------------

def _finish_rows_ref(xf):
    m = jnp.max(xf, axis=-1, keepdims=True)
    e = jnp.exp(xf - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    return jnp.where(m > (_NEG / 2), e / s, 0.0)


def scaled_masked_softmax_ref(x, mask, scale):
    xf = x.astype(jnp.float32) * scale
    if mask is not None:
        xf = jnp.where(mask != 0, _NEG, xf)
    return _finish_rows_ref(xf).astype(x.dtype)


def scaled_upper_triang_masked_softmax_ref(x, scale):
    sq = x.shape[-1]
    causal = jnp.tril(jnp.ones((sq, sq), bool))
    xf = jnp.where(causal, x.astype(jnp.float32) * scale, _NEG)
    return _finish_rows_ref(xf).astype(x.dtype)


def generic_scaled_masked_softmax(x, mask, scale):
    """Reference generic variant (any sk): the XLA path IS the generic
    kernel here."""
    return scaled_masked_softmax(x, mask, scale)
