"""Kernel dispatch policy: real Mosaic on TPU, interpreter elsewhere.

Plays the role of the reference's dtype-dispatch/build-flag glue
(csrc/type_shim.h, setup.py extension gating): decide at trace time whether
a Pallas kernel compiles for hardware or runs interpreted (CPU CI), and
whether to prefer the plain-XLA path where fusion already wins.
"""

from __future__ import annotations

import os

import jax

_FORCE_INTERPRET = os.environ.get("APEX_TPU_PALLAS_INTERPRET", "") == "1"
_DISABLE_PALLAS = os.environ.get("APEX_TPU_DISABLE_PALLAS", "") == "1"


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def interpret_mode() -> bool:
    """True when pallas_call must run interpreted (non-TPU backends)."""
    if os.environ.get("APEX_TPU_FORCE_MOSAIC", "") == "1":
        # AOT TPU lowering on a CPU host: Mosaic kernel serialization and
        # its verifier run at lowering time, no device needed
        # (tests/test_tpu_lowering.py) — checked per call so tests can
        # flip it with monkeypatch
        return False
    if _FORCE_INTERPRET:
        return True
    return not on_tpu()


def pallas_enabled() -> bool:
    """Global escape hatch: fall back to pure-XLA reference paths."""
    return not _DISABLE_PALLAS
