"""Fused attention kernels (reference: apex/contrib/csrc/multihead_attn/*
~8k LoC of per-variant CUDA, apex/contrib/csrc/fmha/ — SURVEY.md §2.4).

One Pallas kernel family with flags replaces the reference's eight
hand-specialized attention extensions: the whole
scores->mask->softmax->context chain runs in VMEM per (batch*head,
q-block) grid cell, so the (Sq, Sk) score matrix never touches HBM (the
reference's kernels fuse the same chain; fmha additionally tiles — here
Mosaic does the tiling).  bf16 inputs accumulate in f32 on the MXU.

Backward: custom_vjp recomputes scores blockwise with XLA math
(flash-style recomputation — no saved probabilities, matching the
memory-efficient behavior the reference gets from its fused bwd kernels).

Long-context path: ``ring_attention`` shards the KV sequence over the
"ctx" mesh axis and rotates KV blocks with lax.ppermute, merging partial
softmax statistics online — apex has NO equivalent (SURVEY.md §2.5 marks
context parallelism out of reference scope); this is the TPU-native
extension that makes long sequences first-class.

Shapes: (B, H, S, D) throughout ("bhsd").
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu import comm
from apex_tpu.ops._dispatch import interpret_mode, pallas_enabled

_NEG = -1e30


def _default_scale(d: int) -> float:
    return 1.0 / math.sqrt(d)


# ---------------------------------------------------------------------------
# Pallas forward kernel: grid (B*H, Sq/BQ); K/V resident per grid cell
# ---------------------------------------------------------------------------

def _attn_fwd_kernel(scale, causal, q_ref, k_ref, v_ref, o_ref):
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)          # (BQ, D)
    k = k_ref[0].astype(jnp.float32)          # (Sk, D)
    v = v_ref[0].astype(jnp.float32)
    bq = q.shape[0]
    sk = k.shape[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        row = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 1)
        s = jnp.where(col > row, _NEG, s)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) / l
    o_ref[0] = o.astype(o_ref.dtype)


def _lane_pad(d: int) -> int:
    """Head dim rounded up to the 128-lane width of the VPU/MXU."""
    return -(-d // 128) * 128


def _fwd_pallas(q, k, v, scale, causal):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    # pad head dim to the 128-lane tile: real head dims (64, 80, 96...)
    # would otherwise never reach the kernel; zero columns change nothing
    # (scores gain 0-products, V gains zero output columns we slice off)
    dp = _lane_pad(d)
    if dp != d:
        pad = ((0, 0), (0, 0), (0, 0), (0, dp - d))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    bq = max(8, min(256, sq))
    while sq % bq:
        bq //= 2
    bq = max(bq, 1)
    q3 = q.reshape(b * h, sq, dp)
    k3 = k.reshape(b * h, sk, dp)
    v3 = v.reshape(b * h, sk, dp)
    out = pl.pallas_call(
        functools.partial(_attn_fwd_kernel, scale, causal),
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, dp), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, dp), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, dp), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dp), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, dp), q.dtype),
        interpret=interpret_mode(),
        name="apex_flash_attention_fwd",
    )(q3, k3, v3)
    return out.reshape(b, h, sq, dp)[..., :d]


def _kernel_ok(q, k) -> bool:
    b, h, sq, d = q.shape
    sk = k.shape[2]
    dp = _lane_pad(d)
    # K/V resident per grid cell: keep them within a few MiB of VMEM
    return (pallas_enabled() and sk % 8 == 0
            and sq % 8 == 0 and sk * dp * 4 * 2 <= 6 * 1024 * 1024)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    """Fused scaled-dot-product attention, (B, H, S, D) layout.

    Replaces the reference's fast_multihead_attn softmax-chain kernels
    and fmhalib (SURVEY.md §2.3): same math, one kernel, no HBM score
    materialization.
    """
    return _fa_fwd(q, k, v, causal, scale)[0]


def _fa_fwd(q, k, v, causal, scale):
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    if _kernel_ok(q, k):
        o = _fwd_pallas(q, k, v, sc, causal)
    else:
        o = attention_ref(q, k, v, causal=causal, scale=sc)
    return o, (q, k, v)


def _fa_bwd(causal, scale, res, do):
    """Memory-efficient backward: scan over q-chunks, recompute scores.

    Peak live memory is O(chunk * Sk) per (B, H) — the full (Sq, Sk)
    probability matrix is never materialized, matching the behavior the
    reference gets from its fused in-place bwd kernels.  Standard flash
    identities: dp = do @ V^T, D = rowsum(p * dp) (= rowsum(do * o)),
    ds = p * (dp - D) * scale.
    """
    q, k, v = res
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    b, h, sq, d = q.shape
    sk = k.shape[2]
    ch = max(8, min(256, sq))
    while sq % ch:
        ch //= 2
    ch = max(ch, 1)
    n = sq // ch
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # (n, b, h, ch, d) chunk-major for scan
    qc = jnp.moveaxis(q.astype(jnp.float32).reshape(b, h, n, ch, d), 2, 0)
    doc = jnp.moveaxis(do.astype(jnp.float32).reshape(b, h, n, ch, d), 2, 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (ch, sk), 1)

    def step(carry, inp):
        dk, dv = carry
        qi, doi, idx = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qi, kf) * sc
        if causal:
            row = (idx * ch
                   + jax.lax.broadcasted_iota(jnp.int32, (ch, sk), 0))
            s = jnp.where(col > row, _NEG, s)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        dp = jnp.einsum("bhqd,bhkd->bhqk", doi, vf)
        dval = jnp.sum(p * dp, axis=-1, keepdims=True)
        ds = p * (dp - dval) * sc
        dqi = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, qi)
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, doi)
        return (dk, dv), dqi

    (dk, dv), dq = jax.lax.scan(
        step, (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        (qc, doc, jnp.arange(n)))
    dq = jnp.moveaxis(dq, 0, 2).reshape(b, h, sq, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def attention_ref(q, k, v, causal=False, scale=None,
                  mask: Optional[jax.Array] = None):
    """XLA oracle/fallback; mask: additive (B,1|H,Sq,Sk) or None."""
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sc
    if mask is not None:
        s = s + mask
    if causal:
        sq, sk = s.shape[-2:]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col > row, _NEG, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# blockwise partial attention with stats (building block of the ring)
# ---------------------------------------------------------------------------

def _partial_attention(q, k, v, scale, mask_val):
    """Unnormalized attention of q against ONE kv block.

    Returns (o_un (B,H,Sq,D), m (B,H,Sq), l (B,H,Sq)): o_un = exp(s-m)@v,
    l = rowsum(exp(s-m)).  mask_val: additive (Sq, Sk) or None.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask_val is not None:
        s = s + mask_val
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(q, k, v, causal=False, scale=None,
                   axis: str = comm.AXIS_CTX):
    """Context-parallel attention: sequences sharded over ``axis``.

    q/k/v: (B, H, S/cp, D) per shard.  KV blocks rotate around the ring
    with ppermute; partial softmax stats merge online, so the full
    (S, S) score matrix never exists anywhere.  Per-step traffic is the
    KV block on ICI neighbors, overlapped by XLA with the block compute.
    Differentiable (scan + ppermute transpose).
    """
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    cp = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    b, h, s_loc, d = q.shape
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    row = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)

    def step(carry, r):
        o, m, l, k_r, v_r = carry
        # k_r currently holds the block owned by rank (rank - r) mod cp
        kv_owner = (rank - r) % cp
        if causal:
            # global positions: q row i -> rank*s_loc + i; kv col j ->
            # kv_owner*s_loc + j
            qpos = rank * s_loc + row
            kpos = kv_owner * s_loc + col
            mask_val = jnp.where(kpos > qpos, _NEG, 0.0)
        else:
            mask_val = None
        o_i, m_i, l_i = _partial_attention(q, k_r, v_r, sc, mask_val)
        m_new = jnp.maximum(m, m_i)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(m_i - m_new)
        o = o * c_old[..., None] + o_i * c_new[..., None]
        l = l * c_old + l_i * c_new
        k_r = jax.lax.ppermute(k_r, axis, perm)
        v_r = jax.lax.ppermute(v_r, axis, perm)
        return (o, m_new, l, k_r, v_r), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    # K/V rotate in their INPUT dtype: bf16 halves the per-step ppermute
    # bytes on ICI; _partial_attention upcasts to f32 for the math anyway
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(cp))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
