"""Fused attention kernels (reference: apex/contrib/csrc/multihead_attn/*
~8k LoC of per-variant CUDA, apex/contrib/csrc/fmha/ — SURVEY.md §2.4).

One Pallas flash-attention kernel family with flags replaces the
reference's eight hand-specialized attention extensions.  The kernel is
K-tiled with online softmax (flash-2 style: unnormalized accumulator,
one divide at the last KV block), so sequence length is bounded by HBM,
not VMEM — the (Sq, Sk) score matrix never exists, at any length.
bf16 inputs hit the MXU in bf16 and accumulate in f32.

Backward is two Pallas kernels (dq over the KV grid; dk/dv over the Q
grid) recomputing probabilities from the forward's saved logsumexp —
no probability tensor is ever stored, matching the memory behavior the
reference gets from its fused in-place bwd kernels.

Variant flags: ``causal`` prunes the iteration space (fully-masked
blocks are skipped and their DMAs clamped away); ``segment_ids``
(q-ids, kv-ids) masks cross-segment pairs, which is how contrib.fmha's
packed variable-length batches route through this one kernel.

Long-context path: ``ring_attention`` shards the KV sequence over the
"ctx" mesh axis and rotates KV blocks with lax.ppermute, merging partial
softmax statistics online — apex has NO equivalent (SURVEY.md §2.5 marks
context parallelism out of reference scope); this is the TPU-native
extension that makes long sequences first-class.

Shapes: (B, H, S, D) throughout ("bhsd").
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu import comm
from apex_tpu.ops import _dispatch
from apex_tpu.ops._dispatch import interpret_mode, op_enabled

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; support
# both so the kernels trace on either side of the rename (the old name
# is what CPU CI ships; BENCH_r05 caught the new-name-only spelling
# crashing every flash bench leg on the 0.4.x interpreter path)
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG = -1e30
_LANES = 128


def _default_scale(d: int) -> float:
    return 1.0 / math.sqrt(d)


def matmul_precision(dtype):
    """The precision contract (docs/kernels.md): f32 operands dot at
    HIGHEST (true-f32 MXU passes — default would round through bf16);
    bf16 operands keep the full-rate default.  Shared by the kernels
    and every oracle/fallback path so comparisons are apples-to-apples."""
    return (jax.lax.Precision.HIGHEST
            if jnp.dtype(dtype) == jnp.float32 else None)


def _dot(a, b, dims):
    """Kernel dot under the precision contract, f32 accumulation."""
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=matmul_precision(a.dtype))


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _block(s: int, cap: int, explicit: bool = False) -> int:
    """Block size for a sequence dim: 128-multiple, <= cap, dividing the
    padded length.  The cap is clamped to the padded length (a short
    sequence runs as one block rather than falling to 128); a
    non-dividing cap falls back to 128 — loudly when it was an explicit
    APEX_TPU_ATTN_BLOCK_CAP, since silently tiling at 128 would be a
    perf regression the operator asked against."""
    sp = _round_up(s, _LANES)
    if sp:                 # sp==0 (degenerate dim): keep old behavior
        cap = min(cap, sp)
    if sp % cap == 0:
        return cap
    if explicit:
        import warnings
        warnings.warn(
            f"APEX_TPU_ATTN_BLOCK_CAP={cap} does not divide the padded "
            f"sequence length {sp}; falling back to 128-blocks for "
            f"this shape")
    return _LANES


def _attn_family(dtype) -> str:
    """Dispatch family for the flash kernel, split by precision class:
    f32 operands dot at Precision.HIGHEST (multi-pass MXU), a very
    different cost model from native-rate bf16 — so a hardware
    measurement that flips one class to the XLA path must not take the
    other down with it (kernel_bench rows map f32 shapes to
    'attention_f32')."""
    return ("attention_f32"
            if jnp.dtype(dtype) == jnp.dtype(jnp.float32) else
            "attention")


def _block_cap(dp: int):
    """(cap, explicit): tunable via APEX_TPU_ATTN_BLOCK_CAP (a
    128-multiple; tools/kernel_bench.py --sweep-attn sweeps it on
    hardware), else the measured-best cap the sweep recorded in
    dispatch_prefs.json for this padded head dim, else a VMEM-safe
    static default.  The env var is read and interpreted HERE only;
    ``explicit`` tells _block to complain loudly when the requested cap
    can't be honored (the measured table is advisory — a non-dividing
    measured cap quietly falls back to 128-blocks for that shape)."""
    env = os.environ.get("APEX_TPU_ATTN_BLOCK_CAP")
    if env:
        try:
            cap = int(env)
        except ValueError:
            cap = -1
        if cap <= 0 or cap % _LANES:
            raise ValueError(
                f"APEX_TPU_ATTN_BLOCK_CAP must be a positive multiple "
                f"of {_LANES}, got {env!r}")
        return cap, True
    measured = _dispatch.attn_block_cap(dp)
    if measured is not None:
        # VMEM-feasibility ceiling: the measured table is advisory and
        # sweep-written (tools/kernel_bench.py only records caps that
        # compiled and won), but a hand-edited value must not push the
        # double-buffered blocks + f32 score tile past ~16 MiB VMEM —
        # clamp to the largest cap the sweep grid explores for this dp.
        return min(measured, _sweep_cap_ceiling(dp)), False
    return (512 if dp <= 128 else (256 if dp <= 256 else 128)), False


def _sweep_cap_ceiling(dp: int) -> int:
    """Largest sequence-block cap the hardware sweep explores (and thus
    the largest a measured table entry can honestly contain) for a
    padded head dim — the VMEM working set grows with cap*dp."""
    return 1024 if dp <= 128 else (512 if dp <= 256 else 256)


def _geom(q, k):
    """Shared fwd/bwd tiling geometry — the saved lse layout depends on
    it, so both passes MUST derive it from this one place.

    The sequence-block cap shrinks as the padded head dim grows so the
    working set (q/k/v/do blocks, double-buffered, plus the f32 score
    tile and accumulators) stays well inside the ~16 MiB VMEM at any d.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    dp = _round_up(d, _LANES)
    cap, explicit = _block_cap(dp)
    bq = _block(sq, cap, explicit)
    bk = _block(sk, cap, explicit)
    sqp, skp = _round_up(sq, bq), _round_up(sk, bk)
    return b, h, sq, sk, d, dp, bq, bk, sqp, skp


def _pad_seq(x, sp):
    s = x.shape[2]
    if s == sp:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, sp - s), (0, 0)))


def _pad_head(x, dp):
    d = x.shape[3]
    if d == dp:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, dp - d)))


def _seg_inputs(segment_ids, b, sqp, skp):
    """Lane/sublane-broadcast segment ids so the kernel never transposes:
    q ids ride the sublanes as (B, SQP, 128); kv ids ride the lanes as
    (B, 8, SKP)."""
    q_ids, kv_ids = segment_ids
    q_ids = jnp.pad(q_ids.astype(jnp.int32),
                    ((0, 0), (0, sqp - q_ids.shape[1])),
                    constant_values=-1)
    kv_ids = jnp.pad(kv_ids.astype(jnp.int32),
                     ((0, 0), (0, skp - kv_ids.shape[1])),
                     constant_values=-2)
    qs = jnp.broadcast_to(q_ids[:, :, None], (b, sqp, _LANES))
    ks = jnp.broadcast_to(kv_ids[:, None, :], (b, 8, skp))
    return qs, ks


def _mask_for_block(j, kk, bq, bk, sq, sk, sqp, skp, causal,
                    qs_tile, ks_row, *, mask_rows):
    """Validity mask (BQ, BK) for one score block, or None if nothing
    masks.  qs_tile: (BQ, 128) or None; ks_row: (1, BK) or None."""
    ok = None

    def _and(a, b):
        return b if a is None else a & b

    row_g = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col_g = kk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if skp != sk:
        ok = _and(ok, col_g < sk)
    if mask_rows and sqp != sq:
        ok = _and(ok, row_g < sq)
    if causal:
        ok = _and(ok, col_g <= row_g)
    if qs_tile is not None:
        reps = bk // _LANES
        qseg = jnp.tile(qs_tile, (1, reps)) if reps > 1 else qs_tile
        ok = _and(ok, qseg[:, :bk] == ks_row)
    return ok


# ---------------------------------------------------------------------------
# forward kernel: grid (B*H, NQ, NK), KV innermost, flash-2 online softmax
# ---------------------------------------------------------------------------

def _fwd_kernel(scale, causal, seg, need_lse, rate, sq, sk, sqp, skp,
                bq, bk, nk, *refs):
    q_ref, k_ref, v_ref = refs[:3]
    refs = refs[3:]
    if rate > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    qs_ref, ks_ref = (refs[:2] if seg else (None, None))
    rest = refs[2:] if seg else refs
    if need_lse:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        lse_ref = None
    i = pl.program_id(0)
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: last KV block this Q block attends to (diagonal block)
    kk_last = jnp.minimum(nk - 1, ((j + 1) * bq - 1) // bk) if causal \
        else nk - 1

    @pl.when(kk <= kk_last)
    def _body():
        # native-dtype operands on the MXU (bf16 runs at full rate),
        # f32 accumulation via preferred_element_type
        s = _dot(q_ref[0], k_ref[0], ((1,), (1,))) * scale
        ok = _mask_for_block(
            j, kk, bq, bk, sq, sk, sqp, skp, causal,
            qs_ref[0] if seg else None,
            ks_ref[0, :1, :] if seg else None, mask_rows=False)
        if ok is not None:
            s = jnp.where(ok, s, _NEG)
        m_prev = m_scr[:, :1]
        m_curr = jnp.max(s, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        if ok is not None:
            p = jnp.where(ok, p, 0.0)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        if rate > 0.0:
            # dropout on softmax PROBS: the denominator l uses the
            # undropped p (softmax normalizes first); only the V
            # accumulation sees the mask, scaled by 1/keep
            keep = _dropout_keep_block(seed_ref[0], i, j, kk, bq, bk,
                                       rate)
            p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
        pv = _dot(p.astype(v_ref.dtype), v_ref[0], ((1,), (0,)))
        acc_scr[...] = acc_scr[...] * alpha + pv

    @pl.when(kk == kk_last)
    def _finish():
        l = l_scr[:, :1]
        linv = jnp.where(l > 0.0, 1.0 / l, 0.0)
        o_ref[0] = (acc_scr[...] * linv).astype(o_ref.dtype)
        if need_lse:   # inference skips the 128-lane lse write entirely
            lse_ref[0] = jnp.where(l_scr[...] > 0.0,
                                   m_scr[...] + jnp.log(l_scr[...]),
                                   _NEG)


def _fwd_kernel_1kv(scale, causal, seg, need_lse, rate, sq, sk, sqp,
                    skp, bq, bk, *refs):
    """Forward body for the nk == 1 geometry (the whole padded KV range
    fits one block, i.e. sk <= the sequence-block cap — the common
    short-sequence regime, s<=512 at d<=128 by default).

    Online softmax exists to merge partial KV blocks; with a single
    block it degenerates to dead work the generic kernel still pays:
    three VMEM scratch accumulators, three @pl.when phases per grid
    step, an alpha-rescale of the (BQ, DP) accumulator and the (BQ,
    LANES) broadcast m/l writes.  This body is the plain fused-softmax
    attention computed in registers — measured motivation: round-4's
    bf16 flash FORWARD lost to the unfused oracle at s=512 (VERDICT r4
    weak #4) while the backward won."""
    q_ref, k_ref, v_ref = refs[:3]
    refs = refs[3:]
    if rate > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    qs_ref, ks_ref = (refs[:2] if seg else (None, None))
    rest = refs[2:] if seg else refs
    if need_lse:
        o_ref, lse_ref = rest
    else:
        (o_ref,) = rest
        lse_ref = None
    i = pl.program_id(0)
    j = pl.program_id(1)

    s = _dot(q_ref[0], k_ref[0], ((1,), (1,))) * scale
    ok = _mask_for_block(
        j, 0, bq, bk, sq, sk, sqp, skp, causal,
        qs_ref[0] if seg else None,
        ks_ref[0, :1, :] if seg else None, mask_rows=False)
    if ok is not None:
        s = jnp.where(ok, s, _NEG)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    if ok is not None:
        p = jnp.where(ok, p, 0.0)       # fully-masked rows: m=_NEG, p=1
    l = jnp.sum(p, axis=1, keepdims=True)
    if rate > 0.0:
        keep = _dropout_keep_block(seed_ref[0], i, j, 0, bq, bk, rate)
        p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    pv = _dot(p.astype(v_ref.dtype), v_ref[0], ((1,), (0,)))
    linv = jnp.where(l > 0.0, 1.0 / l, 0.0)
    o_ref[0] = (pv * linv).astype(o_ref.dtype)
    if need_lse:   # same layout as the generic kernel: bwd shares it
        lse_ref[0] = jnp.broadcast_to(
            jnp.where(l > 0.0, m + jnp.log(l), _NEG),
            lse_ref.shape[1:])


def _kv_row(i, h, hk):
    """Flat KV row for flat q row ``i`` under grouped-query attention:
    q head y attends kv head y // (h // hk).  Identity when hk == h."""
    return (i // h) * hk + (i % h) // (h // hk)


# ---------------------------------------------------------------------------
# fused attention dropout: counter-based hash mask
# ---------------------------------------------------------------------------
#
# The reference fuses probability dropout into its attention kernels
# (apex/contrib/csrc/multihead_attn, fmha).  The TPU-native analog is a
# COUNTER-BASED mask: murmur3's fmix32 avalanche on the global
# (batch*head, row, col) coordinates, pure int32 vector ops.  The same
# jnp code runs inside the Pallas kernels (interpret AND Mosaic), in
# the XLA fallback path, and in the test oracle, so every path drops
# the exact same elements — and the three backward/forward kernels
# reconstruct the mask from coordinates instead of storing an
# (Sq, Sk) mask tensor anywhere.

def _fmix32(h):
    """murmur3 finalizer on int32 (wraparound semantics everywhere)."""
    h = jnp.asarray(h, jnp.int32)
    h = h ^ jax.lax.shift_right_logical(h, 16)
    h = h * jnp.int32(-2048144789)          # 0x85EBCA6B
    h = h ^ jax.lax.shift_right_logical(h, 13)
    h = h * jnp.int32(-1028477387)          # 0xC2B2AE35
    h = h ^ jax.lax.shift_right_logical(h, 16)
    return h


def _keep_mask(seed, i_flat, rows, cols, rate):
    """Boolean keep-mask for attention-prob dropout.

    seed: traced int32 scalar; i_flat: flat batch*head row (scalar);
    rows/cols: int32 arrays of GLOBAL q/k positions (any shape);
    rate: static python float in [0, 1).  keep prob = 1 - rate,
    decided by an unsigned compare of the hashed coordinates."""
    h0 = _fmix32(jnp.asarray(seed, jnp.int32)
                 + jnp.asarray(i_flat, jnp.int32) * jnp.int32(-1640531527))
    h = _fmix32(h0
                + rows.astype(jnp.int32) * jnp.int32(-1654467297)
                + cols.astype(jnp.int32) * jnp.int32(2024237689))
    # unsigned compare in int32: flip the sign bit of both sides
    # host math on the STATIC rate (per contract above), not a traced
    # concretization
    # apexlint: disable-next=APX101
    thresh = min(int((1.0 - rate) * 4294967296.0), 4294967295)
    tu = thresh ^ 0x80000000
    t = jnp.int32(tu - (1 << 32) if tu >= (1 << 31) else tu)
    return (h ^ jnp.int32(-2147483648)) < t


def _dropout_keep_block(seed, i_flat, j, kk, bq, bk, rate):
    """Keep-mask for one (BQ, BK) score block at q-block ``j`` /
    kv-block ``kk`` of flat row ``i_flat`` — the same global
    coordinates in every kernel (fwd, dq, dkv), so all three
    reconstruct the identical mask from position alone."""
    row_g = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col_g = kk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return _keep_mask(seed, i_flat, row_g, col_g, rate)


def _fwd_pallas(q, k, v, scale, causal, segment_ids, need_lse=True,
                rate=0.0, seed=None):
    b, h, sq, sk, d, dp, bq, bk, sqp, skp = _geom(q, k)
    nq, nk = sqp // bq, skp // bk
    hk = k.shape[1]

    q3 = _pad_head(_pad_seq(q, sqp), dp).reshape(b * h, sqp, dp)
    k3 = _pad_head(_pad_seq(k, skp), dp).reshape(b * hk, skp, dp)
    v3 = _pad_head(_pad_seq(v, skp), dp).reshape(b * hk, skp, dp)

    if causal:
        # clamp the KV index for blocks above the diagonal: the skipped
        # iterations re-reference the diagonal block, so no DMA is issued
        def _kv_idx(i, j, kk, bq=bq, bk=bk, nk=nk):
            return (_kv_row(i, h, hk), jnp.minimum(kk, jnp.minimum(
                nk - 1, ((j + 1) * bq - 1) // bk)), 0)
    else:
        _kv_idx = lambda i, j, kk: (_kv_row(i, h, hk), kk, 0)
    in_specs = [
        pl.BlockSpec((1, bq, dp), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bk, dp), _kv_idx),
        pl.BlockSpec((1, bk, dp), _kv_idx),
    ]
    args = [q3, k3, v3]
    if rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(jnp.asarray(seed, jnp.int32).reshape(1))
    seg = segment_ids is not None
    if seg:
        qs, ks = _seg_inputs(segment_ids, b, sqp, skp)
        in_specs += [
            pl.BlockSpec((1, bq, _LANES), lambda i, j, kk: (i // h, j, 0)),
            pl.BlockSpec((1, 8, bk),
                         lambda i, j, kk: (i // h, 0,
                                           _kv_idx(i, j, kk)[1])),
        ]
        args += [qs, ks]

    out_specs = [pl.BlockSpec((1, bq, dp), lambda i, j, kk: (i, j, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * h, sqp, dp), q.dtype)]
    if need_lse:
        out_specs.append(
            pl.BlockSpec((1, bq, _LANES), lambda i, j, kk: (i, j, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, sqp, _LANES), jnp.float32))
    if nk == 1:
        kernel = functools.partial(_fwd_kernel_1kv, scale, causal, seg,
                                   need_lse, rate, sq, sk, sqp, skp,
                                   bq, bk)
        scratch = []
    else:
        kernel = functools.partial(_fwd_kernel, scale, causal, seg,
                                   need_lse, rate, sq, sk, sqp, skp,
                                   bq, bk, nk)
        scratch = [
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, dp), jnp.float32),
        ]
    outs = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
        name="apex_flash_attention_fwd",
    )(*args)
    o = outs[0].reshape(b, h, sqp, dp)[:, :, :sq, :d]
    return o, (outs[1] if need_lse else None)


# ---------------------------------------------------------------------------
# backward kernels: dq over the KV grid, dk/dv over the Q grid
# ---------------------------------------------------------------------------

def _recompute_p(scale, causal, seg, sq, sk, sqp, skp, bq, bk, j, kk,
                 q_ref, k_ref, qs_ref, ks_ref, lse_ref):
    s = _dot(q_ref[0], k_ref[0], ((1,), (1,))) * scale
    p = jnp.exp(s - lse_ref[0, :, :1])
    ok = _mask_for_block(
        j, kk, bq, bk, sq, sk, sqp, skp, causal,
        qs_ref[0] if seg else None,
        ks_ref[0, :1, :] if seg else None, mask_rows=True)
    if ok is not None:
        p = jnp.where(ok, p, 0.0)
    return p


def _dq_kernel(scale, causal, seg, rate, sq, sk, sqp, skp, bq, bk, nk,
               *refs):
    if rate > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    if seg:
        q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, qs_ref, ks_ref, \
            dq_ref, dq_scr = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref, dq_scr = refs
        qs_ref = ks_ref = None
    i = pl.program_id(0)
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    kk_last = jnp.minimum(nk - 1, ((j + 1) * bq - 1) // bk) if causal \
        else nk - 1

    @pl.when(kk <= kk_last)
    def _body():
        p = _recompute_p(scale, causal, seg, sq, sk, sqp, skp, bq, bk,
                         j, kk, q_ref, k_ref, qs_ref, ks_ref, lse_ref)
        dp = _dot(do_ref[0], v_ref[0], ((1,), (1,)))
        if rate > 0.0:
            # dP = mask . (dO V^T)/keep; the rowsum correction stays di
            # (see _flash docstring: rowsum(dP.P) == rowsum(dO.O))
            keep = _dropout_keep_block(seed_ref[0], i, j, kk, bq, bk,
                                       rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - rate)), 0.0)
        ds = p * (dp - di_ref[0, :, :1]) * scale
        dq_scr[...] += _dot(ds.astype(k_ref.dtype), k_ref[0],
                            ((1,), (0,)))

    @pl.when(kk == kk_last)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(scale, causal, seg, rate, h, hk, sq, sk, sqp, skp, bq,
                bk, nq, g, *refs):
    """dk/dv accumulation.  The sequential axis ``t`` covers the whole
    q-head GROUP sharing this kv head times the q blocks (t = qh*NQ+j,
    grouped-query attention): every q head's contribution lands in the
    same scratch accumulator, race-free because the axis is
    'arbitrary' (sequential).  g == 1 recovers plain MHA exactly."""
    if rate > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    if seg:
        q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, qs_ref, ks_ref, \
            dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, \
            dk_ref, dv_ref, dk_scr, dv_scr = refs
        qs_ref = ks_ref = None
    i = pl.program_id(0)
    kk = pl.program_id(1)
    t = pl.program_id(2)
    j = t % nq if g > 1 else t

    # causal: first Q block whose rows reach this KV block (same for
    # every q head in the group, so init fires on the group's first
    # executed tick: qh == 0, j == j_first)
    j_first = jnp.minimum(nq - 1, (kk * bk) // bq) if causal else 0

    @pl.when(t == j_first)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(j >= j_first)
    def _body():
        p = _recompute_p(scale, causal, seg, sq, sk, sqp, skp, bq, bk,
                         j, kk, q_ref, k_ref, qs_ref, ks_ref, lse_ref)
        if rate > 0.0:
            # the mask was drawn per FLAT Q row in fwd/dq; this grid
            # runs over kv heads, so recover that row from (i, t)
            i_flatq = (i // hk) * h + (i % hk) * g + t // nq
            keep = _dropout_keep_block(seed_ref[0], i_flatq, j, kk,
                                       bq, bk, rate)
            inv = 1.0 / (1.0 - rate)
            p_d = jnp.where(keep, p * inv, 0.0)
        else:
            p_d = p
        # dv += (dropped p)^T @ do   (contract the q dim)
        dv_scr[...] += _dot(p_d.astype(do_ref.dtype), do_ref[0],
                            ((0,), (0,)))
        dp = _dot(do_ref[0], v_ref[0], ((1,), (1,)))
        if rate > 0.0:
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - di_ref[0, :, :1]) * scale
        dk_scr[...] += _dot(ds.astype(q_ref.dtype), q_ref[0],
                            ((0,), (0,)))

    @pl.when(t == g * nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, scale, causal, segment_ids,
                rate=0.0, seed=None):
    b, h, sq, sk, d, dp, bq, bk, sqp, skp = _geom(q, k)
    nq, nk = sqp // bq, skp // bk
    hk = k.shape[1]
    g = h // hk
    seed_specs, seed_args = [], []
    if rate > 0.0:
        seed_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        seed_args = [jnp.asarray(seed, jnp.int32).reshape(1)]

    q3 = _pad_head(_pad_seq(q, sqp), dp).reshape(b * h, sqp, dp)
    k3 = _pad_head(_pad_seq(k, skp), dp).reshape(b * hk, skp, dp)
    v3 = _pad_head(_pad_seq(v, skp), dp).reshape(b * hk, skp, dp)
    do3 = _pad_head(_pad_seq(do, sqp), dp).reshape(b * h, sqp, dp)

    # di = rowsum(do * o): plain-XLA elementwise; both di and the saved
    # one-lane lse are broadcast to the kernel's 128-lane layout so
    # neither bwd kernel ever transposes
    di = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    di = jnp.pad(di.reshape(b * h, sq), ((0, 0), (0, sqp - sq)))
    di = jnp.broadcast_to(di[:, :, None], (b * h, sqp, _LANES))
    if lse.shape[1] != sqp:     # callers may pass unpadded (b*h, Sq)
        lse = jnp.pad(lse, ((0, 0), (0, sqp - lse.shape[1])))
    lse = jnp.broadcast_to(lse[:, :, None], (b * h, sqp, _LANES))

    seg = segment_ids is not None

    # dkv grid rows run over KV heads (b*hk); its sequential axis t
    # covers the q-head group x q blocks.  These maps recover the flat
    # q row and the (causal-clamped) q block from (i, kk, t).
    def _q_row_kv(i, t):
        return (i // hk) * h + (i % hk) * g + t // nq

    if causal:
        def _kv_idx(i, j, kk, bq=bq, bk=bk, nk=nk):
            return (_kv_row(i, h, hk), jnp.minimum(kk, jnp.minimum(
                nk - 1, ((j + 1) * bq - 1) // bk)), 0)

        def _q_idx_kv(i, kk, t, bq=bq, bk=bk, nq=nq):
            return (_q_row_kv(i, t), jnp.maximum(t % nq, jnp.minimum(
                nq - 1, (kk * bk) // bq)), 0)
    else:
        _kv_idx = lambda i, j, kk: (_kv_row(i, h, hk), kk, 0)
        _q_idx_kv = lambda i, kk, t: (_q_row_kv(i, t), t % nq, 0)
    base_specs = [
        pl.BlockSpec((1, bq, dp), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bk, dp), _kv_idx),
        pl.BlockSpec((1, bk, dp), _kv_idx),
        pl.BlockSpec((1, bq, dp), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bq, _LANES), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bq, _LANES), lambda i, j, kk: (i, j, 0)),
    ]
    args = [q3, k3, v3, do3, lse, di]
    seg_specs = []
    if seg:
        qs, ks = _seg_inputs(segment_ids, b, sqp, skp)
        seg_specs = [
            pl.BlockSpec((1, bq, _LANES), lambda i, j, kk: (i // h, j, 0)),
            pl.BlockSpec((1, 8, bk),
                         lambda i, j, kk: (i // h, 0,
                                           _kv_idx(i, j, kk)[1])),
        ]
        args += [qs, ks]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale, causal, seg, rate, sq, sk,
                          sqp, skp, bq, bk, nk),
        grid=(b * h, nq, nk),
        in_specs=seed_specs + base_specs + seg_specs,
        out_specs=[pl.BlockSpec((1, bq, dp), lambda i, j, kk: (i, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, sqp, dp), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, dp), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
        name="apex_flash_attention_dq",
    )(*(seed_args + args))[0]

    # dk/dv grid: (BH, NK, NQ) — q innermost; index maps swap j/kk roles;
    # for causal, Q-side blocks below the first contributing one are
    # clamped so skipped iterations issue no DMA
    kv_specs = [
        pl.BlockSpec((1, bq, dp), _q_idx_kv),
        pl.BlockSpec((1, bk, dp), lambda i, kk, j: (i, kk, 0)),
        pl.BlockSpec((1, bk, dp), lambda i, kk, j: (i, kk, 0)),
        pl.BlockSpec((1, bq, dp), _q_idx_kv),
        pl.BlockSpec((1, bq, _LANES), _q_idx_kv),
        pl.BlockSpec((1, bq, _LANES), _q_idx_kv),
    ]
    if seg:
        kv_specs += [
            pl.BlockSpec((1, bq, _LANES),
                         lambda i, kk, t: (i // hk,
                                           _q_idx_kv(i, kk, t)[1], 0)),
            pl.BlockSpec((1, 8, bk), lambda i, kk, t: (i // hk, 0, kk)),
        ]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale, causal, seg, rate, h, hk,
                          sq, sk, sqp, skp, bq, bk, nq, g),
        grid=(b * hk, nk, g * nq),
        in_specs=seed_specs + kv_specs,
        out_specs=[
            pl.BlockSpec((1, bk, dp), lambda i, kk, t: (i, kk, 0)),
            pl.BlockSpec((1, bk, dp), lambda i, kk, t: (i, kk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hk, skp, dp), k.dtype),
            jax.ShapeDtypeStruct((b * hk, skp, dp), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dp), jnp.float32),
            pltpu.VMEM((bk, dp), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
        name="apex_flash_attention_dkv",
    )(*(seed_args + args))

    dq = dq.reshape(b, h, sqp, dp)[:, :, :sq, :d]
    dk = dk.reshape(b, hk, skp, dp)[:, :, :sk, :d]
    dv = dv.reshape(b, hk, skp, dp)[:, :, :sk, :d]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, segment_ids, seed, causal, scale, rate):
    # primal (non-differentiated) path: no lse output at all
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    o, _ = _fwd_pallas(q, k, v, sc, causal, segment_ids,
                       need_lse=False, rate=rate, seed=seed)
    return o


def _flash_fwd(q, k, v, segment_ids, seed, causal, scale, rate):
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    o, lse = _fwd_pallas(q, k, v, sc, causal, segment_ids,
                         rate=rate, seed=seed)
    # keep ONE lane of the kernel's 128-lane lse layout as the residual
    # (they're identical); _bwd_pallas re-broadcasts.  The dropout mask
    # is NOT a residual: every backward kernel reconstructs it from the
    # (seed, coordinates) hash.
    return o, (q, k, v, segment_ids, seed, o, lse[:, :, 0])


def _flash_bwd(causal, scale, rate, res, do):
    q, k, v, segment_ids, seed, o, lse = res
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    dq, dk, dv = _bwd_pallas(q, k, v, o, lse, do, sc, causal,
                             segment_ids, rate=rate, seed=seed)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def dropout_seed_from_key(key):
    """Fold a jax PRNG key down to the int32 seed the fused hash-mask
    dropout consumes (deterministic per key, traced).  THE one
    canonical fold: every frontend (contrib.multihead_attn,
    contrib.fmha, user code) must derive seeds this way so the same
    key always drops the same elements."""
    return jax.random.randint(key, (), 0, 2147483647, dtype=jnp.int32)


def dropout_keep_ref(seed, b, h, sq, sk, rate):
    """(B, H, Sq, Sk) keep-mask EXACTLY matching the kernels' hash
    (same _keep_mask on global coordinates).  Used by the XLA fallback
    path and the test oracle, so dropout semantics are dispatch-stable:
    the kernel and the escape hatch drop the same elements."""
    i = jnp.arange(b * h, dtype=jnp.int32)[:, None, None]
    rows = jnp.arange(sq, dtype=jnp.int32)[None, :, None]
    cols = jnp.arange(sk, dtype=jnp.int32)[None, None, :]
    keep = _keep_mask(jnp.asarray(seed, jnp.int32).reshape(()),
                      i, rows, cols, rate)
    return keep.reshape(b, h, sq, sk)


def _dense_fallback_fits(q_shape, k_shape) -> bool:
    """Memory gate on the unfused escape hatch: the dense path
    materializes the (B, H, Sq, Sk) f32 score tensor (several live
    copies under remat — the round-4 window hit a 48G HBM request at
    s=8192 on a 16G chip when measured prefs routed attention to XLA).
    The measured preference table only speaks for the shapes the bench
    ran (Sq·Sk <= 2048²); past this element budget the flash kernel is
    the only memory-safe implementation and the preference is ignored.
    Operator overrides (APEX_TPU_DISABLE_PALLAS, APEX_TPU_PREFER_XLA)
    are NOT subject to this gate — see _dispatch.prefs_disabled.
    """
    b, h, sq = q_shape[0], q_shape[1], q_shape[2]
    sk = k_shape[2]
    env = os.environ.get("APEX_TPU_ATTN_DENSE_MAX_SCORES")
    budget = 2 ** 27
    if env:
        try:
            iv = int(env)
        except ValueError:
            iv = -1
        if iv > 0:
            budget = iv
        else:
            import warnings
            warnings.warn(
                f"APEX_TPU_ATTN_DENSE_MAX_SCORES={env!r} is not a "
                f"positive integer; using the default budget {budget}")
    return b * h * sq * sk <= budget


def packed_segment_ids(segment_ids, xp=jnp):
    """(q_ids, kv_ids) for a packed batch's base segment array
    ((B, S); 1.. per sequence, 0 on padding — the
    apex_tpu.data.pack_sequences form).  Padding gets DISJOINT ids per
    side (-1 on q, -2 on kv, the contrib.fmha convention) so pad rows
    attend nowhere and output exact zeros.  The single home of that
    convention — data.pack_sequences (xp=numpy, host side) and the
    GPT packed path (traced) both derive from here."""
    return (xp.where(segment_ids > 0, segment_ids, -1),
            xp.where(segment_ids > 0, segment_ids, -2))


def flash_attention(q, k, v, causal=False, scale=None,
                    segment_ids: Optional[Tuple[jax.Array,
                                                jax.Array]] = None,
                    dropout_rate: float = 0.0, dropout_seed=None):
    """Fused scaled-dot-product attention, (B, H, S, D) layout.

    Replaces the reference's fast_multihead_attn softmax-chain kernels
    and fmhalib (SURVEY.md §2.3): same math, one K-tiled online-softmax
    kernel, no HBM score materialization at any sequence length.

    segment_ids: optional (q_ids (B, Sq), kv_ids (B, Sk)) int arrays;
    attention is masked where ids differ (packed variable-length
    batches — the fmha contract).

    Grouped-query / multi-query attention (beyond-reference TPU
    extension): k/v may carry FEWER heads than q — (B, HK, Sk, D) with
    H % HK == 0; q head y attends kv head y // (H // HK).  The kernels
    read the small K/V straight from HBM (the bandwidth point of GQA)
    instead of materializing repeated heads.

    dropout_rate/dropout_seed: fused probability dropout (the
    reference fuses it in multihead_attn/fmha kernels).  rate is a
    STATIC float in [0, 1); seed is a traced int32 scalar (vary it per
    step).  The mask is a counter-based hash of (seed, head, row, col)
    recomputed inside every kernel — no mask tensor is ever stored —
    and the backward drops the same elements.  Callers own the
    train/eval switch: pass rate 0 (or no seed) when not training.
    """
    h, hk = q.shape[1], k.shape[1]
    if h % hk or v.shape[1] != hk:
        raise ValueError(
            f"flash_attention: q heads ({h}) must be a multiple of kv "
            f"heads ({hk}, v: {v.shape[1]})")
    rate = float(dropout_rate)
    if not 0.0 <= rate < 1.0:
        raise ValueError(
            f"flash_attention: dropout_rate must be in [0, 1), got "
            f"{dropout_rate!r}")
    if rate > 0.0 and dropout_seed is None:
        raise ValueError(
            "flash_attention: dropout_rate > 0 requires dropout_seed "
            "(a traced int32 scalar; vary it per training step)")
    seed = (None if rate == 0.0
            else jnp.asarray(dropout_seed, jnp.int32).reshape(()))
    # the kernels dot native-dtype operands (full-rate MXU): normalize
    # mixed q/k/v dtypes once here so kernel and fallback paths agree
    if not (q.dtype == k.dtype == v.dtype):
        dt = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype),
                               v.dtype)
        q, k, v = q.astype(dt), k.astype(dt), v.astype(dt)
    fam = _attn_family(q.dtype)
    if not op_enabled(fam) and not (
            _dispatch.prefs_disabled(fam)
            and not _dense_fallback_fits(q.shape, k.shape)):
        sc = scale if scale is not None else _default_scale(q.shape[-1])
        # jax.checkpoint: don't hold the (Sq, Sk) probability residual
        # between fwd and bwd on the escape-hatch path
        ref = jax.checkpoint(functools.partial(
            attention_ref, causal=causal, scale=sc,
            dropout_rate=rate, dropout_seed=seed))
        if segment_ids is not None:
            q_ids, kv_ids = segment_ids
            same = q_ids[:, None, :, None] == kv_ids[:, None, None, :]
            o = ref(q, k, v, mask=jnp.where(same, 0.0, _NEG))
            # kernel contract: fully-masked q rows give exact zeros (the
            # oracle's softmax over an all--1e30 row gives mean-of-V);
            # under causal, positions above the diagonal don't count as
            # visible either
            visible = same
            if causal:
                sq, sk = q.shape[2], k.shape[2]
                col_ok = (jnp.arange(sk)[None, :]
                          <= jnp.arange(sq)[:, None])   # (Sq, Sk)
                visible = visible & col_ok[None, None]
            any_kv = jnp.any(visible, axis=-1)          # (B, 1, Sq)
            return jnp.where(any_kv[..., None], o, 0.0).astype(q.dtype)
        return ref(q, k, v)
    return _flash(q, k, v, segment_ids, seed, causal, scale, rate)


def attention_ref(q, k, v, causal=False, scale=None,
                  mask: Optional[jax.Array] = None,
                  dropout_rate: float = 0.0, dropout_seed=None):
    """XLA oracle/fallback; mask: additive (B,1|H,Sq,Sk) or None.

    f32 inputs get HIGHEST matmul precision (true f32 on the MXU, same
    contract as the kernel's _dot); bf16 inputs keep the fast default.
    Grouped-query shapes (kv heads < q heads) are handled by repeating
    kv — the oracle states the semantics; the kernel avoids the copy.
    Dropout uses the SAME counter-based hash as the kernels
    (dropout_keep_ref), so kernel and oracle drop identical elements.
    """
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    prec = matmul_precision(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=prec) * sc
    if mask is not None:
        s = s + mask
    if causal:
        sq, sk = s.shape[-2:]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col > row, _NEG, s)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError(
                "attention_ref: dropout_rate > 0 requires dropout_seed "
                "(a traced int32 scalar; vary it per training step)")
        b, h, sq, sk = p.shape
        keep = dropout_keep_ref(dropout_seed, b, h, sq, sk,
                                dropout_rate)
        p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                      precision=prec).astype(q.dtype)


# ---------------------------------------------------------------------------
# blockwise partial attention with stats (building block of the ring)
# ---------------------------------------------------------------------------

def _partial_attention(q, k, v, scale, mask_val):
    """Unnormalized attention of q against ONE kv block.

    Returns (o_un (B,H,Sq,D), m (B,H,Sq), l (B,H,Sq)): o_un = exp(s-m)@v,
    l = rowsum(exp(s-m)).  mask_val: additive (Sq, Sk) or None.
    """
    prec = matmul_precision(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=prec) * scale
    if mask_val is not None:
        s = s + mask_val
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                   precision=prec)
    return o, m, l


def _block_modes(causal, kv_owner, rank):
    """0 = attend fully, 1 = diagonal (causal mask), 2 = skip."""
    if not causal:
        return jnp.int32(0)
    return jnp.where(kv_owner < rank, 0,
                     jnp.where(kv_owner == rank, 1, 2)).astype(jnp.int32)


def _ring_block_fwd(q, k_r, v_r, sc, mode):
    """One ring step through the flash kernel: normalized block output
    + lse, switched over the causal block mode."""
    b, h, s_loc, d = q.shape

    def _run(causal_flag):
        def f(_):
            o, lse = _fwd_pallas(q, k_r, v_r, sc, causal_flag, None,
                                 need_lse=True)
            lse = lse[:, :s_loc, 0].reshape(b, h, s_loc)
            return o.astype(jnp.float32), lse
        return f

    def _skip(_):
        return (jnp.zeros((b, h, s_loc, d), jnp.float32),
                jnp.full((b, h, s_loc), _NEG, jnp.float32))

    return jax.lax.switch(mode, [_run(False), _run(True), _skip], None)


def _ring_block_bwd(q, k_r, v_r, o, lse1, do, sc, mode):
    """One backward ring step: per-block (dq, dk, dv) from the Pallas
    backward kernels evaluated against the GLOBAL lse (probabilities
    come out globally normalized, so the partials sum exactly)."""
    b, h, s_loc, d = q.shape

    def _run(causal_flag):
        def f(_):
            return _bwd_pallas(q, k_r, v_r, o, lse1, do, sc,
                               causal_flag, None)
        return f

    def _skip(_):
        return (jnp.zeros_like(q), jnp.zeros_like(k_r),
                jnp.zeros_like(v_r))

    return jax.lax.switch(mode, [_run(False), _run(True), _skip], None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, causal, scale, axis):
    o, _ = _ring_fwd_impl(q, k, v, causal, scale, axis)
    return o


def _ring_fwd_impl(q, k, v, causal, scale, axis):
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    cp = comm.bound_axis_size(axis)
    # only the causal mask consumes the rank; a dead axis_index would
    # leave an unused partition-id instruction the CPU SPMD partitioner
    # rejects outright (it only rewrites the patterns it recognizes)
    rank = jax.lax.axis_index(axis) if causal else jnp.int32(0)
    b, h, s_loc, d = q.shape
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, r):
        o, lse, k_r, v_r = carry
        kv_owner = (rank - r) % cp
        mode = _block_modes(causal, kv_owner, rank)
        o_i, lse_i = _ring_block_fwd(q, k_r, v_r, sc, mode)
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new)
        w_new = jnp.exp(lse_i - lse_new)
        o = o * w_old[..., None] + o_i * w_new[..., None]
        k_r = jax.lax.ppermute(k_r, axis, perm)
        v_r = jax.lax.ppermute(v_r, axis, perm)
        return (o, lse_new, k_r, v_r), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    (o, lse, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v),
                                     jnp.arange(cp))
    return o.astype(q.dtype), lse


def _ring_vjp_fwd(q, k, v, causal, scale, axis):
    o, lse = _ring_fwd_impl(q, k, v, causal, scale, axis)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(causal, scale, axis, res, do):
    q, k, v, o, lse = res
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    cp = comm.bound_axis_size(axis)
    rank = jax.lax.axis_index(axis) if causal else jnp.int32(0)
    b, h, s_loc, d = q.shape
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    lse1 = lse.reshape(b * h, s_loc)

    # second ring: dk/dv accumulators travel WITH their kv block, so
    # after the full cycle every block is back home carrying the sum of
    # all ranks' contributions
    def step(carry, r):
        dq, k_r, v_r, dk_r, dv_r = carry
        kv_owner = (rank - r) % cp
        mode = _block_modes(causal, kv_owner, rank)
        dq_i, dk_i, dv_i = _ring_block_bwd(q, k_r, v_r, o, lse1, do,
                                           sc, mode)
        dq = dq + dq_i.astype(jnp.float32)
        dk_r = dk_r + dk_i.astype(jnp.float32)
        dv_r = dv_r + dv_i.astype(jnp.float32)
        k_r = jax.lax.ppermute(k_r, axis, perm)
        v_r = jax.lax.ppermute(v_r, axis, perm)
        dk_r = jax.lax.ppermute(dk_r, axis, perm)
        dv_r = jax.lax.ppermute(dv_r, axis, perm)
        return (dq, k_r, v_r, dk_r, dv_r), None

    zeros = jnp.zeros((b, h, s_loc, d), jnp.float32)
    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (zeros, k, v, zeros, zeros), jnp.arange(cp))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, causal=False, scale=None,
                   axis: str = comm.AXIS_CTX):
    """Context-parallel attention: sequences sharded over ``axis``.

    q/k/v: (B, H, S/cp, D) per shard.  KV blocks rotate around the ring
    with ppermute; per-block flash-kernel calls merge via logsumexp, so
    the full (S, S) score matrix never exists anywhere and each block
    runs at kernel speed.  Backward is a second ring whose dk/dv
    accumulators travel with their KV block (each block arrives home
    after the full cycle carrying every rank's contribution).  Per-step
    traffic is the KV block (+cotangets in backward) on ICI neighbors.

    Reverse-mode only (custom_vjp): for jvp/forward-mode use
    ``ring_attention_ref`` (plain scan + ppermute, fully transposable)
    or set APEX_TPU_DISABLE_PALLAS=1.
    """
    if k.shape[1] != q.shape[1]:
        # the ring's blockwise math and its traveling dk/dv accumulators
        # are head-aligned with q; GQA shapes would half-work (forward
        # only) — refuse clearly instead.  GQA composes with
        # ulysses_attention (hk % cp == 0) or plain flash_attention.
        raise ValueError(
            f"ring_attention requires equal q/kv head counts, got "
            f"q={q.shape[1]} kv={k.shape[1]}; repeat kv heads first or "
            "use ulysses_attention / flash_attention for grouped-query "
            "shapes")
    # normalize mixed dtypes BEFORE picking the dispatch family, so
    # this entry point and flash_attention consult the same precision
    # class for identical inputs
    if not (q.dtype == k.dtype == v.dtype):
        dt = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype),
                               v.dtype)
        q, k, v = q.astype(dt), k.astype(dt), v.astype(dt)
    if op_enabled(_attn_family(q.dtype)):
        return _ring(q, k, v, causal, scale, axis)
    return ring_attention_ref(q, k, v, causal=causal, scale=scale,
                              axis=axis)


def ring_attention_ref(q, k, v, causal=False, scale=None,
                       axis: str = comm.AXIS_CTX):
    """jnp blockwise ring (oracle/escape hatch): same math, plain XLA
    per-block attention with online stat merging.
    """
    sc = scale if scale is not None else _default_scale(q.shape[-1])
    cp = comm.bound_axis_size(axis)
    rank = jax.lax.axis_index(axis)
    b, h, s_loc, d = q.shape
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    row = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)

    # jax.checkpoint: without it the scan saves every step's (s_loc,
    # s_loc) probability block as a backward residual — O(cp * s^2)
    # memory, exactly what ring attention exists to avoid.  Remat
    # recomputes each block's scores during backward instead.
    @functools.partial(jax.checkpoint, prevent_cse=False)
    def step_math(o, m, l, k_r, v_r, r):
        # k_r currently holds the block owned by rank (rank - r) mod cp
        kv_owner = (rank - r) % cp
        if causal:
            # global positions: q row i -> rank*s_loc + i; kv col j ->
            # kv_owner*s_loc + j
            qpos = rank * s_loc + row
            kpos = kv_owner * s_loc + col
            mask_val = jnp.where(kpos > qpos, _NEG, 0.0)
        else:
            mask_val = None
        o_i, m_i, l_i = _partial_attention(q, k_r, v_r, sc, mask_val)
        m_new = jnp.maximum(m, m_i)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(m_i - m_new)
        o = o * c_old[..., None] + o_i * c_new[..., None]
        l = l * c_old + l_i * c_new
        return o, m_new, l

    def step(carry, r):
        o, m, l, k_r, v_r = carry
        o, m, l = step_math(o, m, l, k_r, v_r, r)
        k_r = jax.lax.ppermute(k_r, axis, perm)
        v_r = jax.lax.ppermute(v_r, axis, perm)
        return (o, m, l, k_r, v_r), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    # K/V rotate in their INPUT dtype: bf16 halves the per-step ppermute
    # bytes on ICI; _partial_attention upcasts to f32 for the math anyway
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(cp))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# all-to-all sequence parallelism (Ulysses-style) — the second
# long-context strategy next to the ppermute ring
# ---------------------------------------------------------------------------

def ulysses_attention(q, k, v, causal=False, scale=None,
                      axis: str = comm.AXIS_CTX):
    """All-to-all sequence parallelism over ``axis`` (Ulysses style).

    q/k/v: (B, H, S/cp, D) per shard, with H divisible by cp.  One
    ``all_to_all`` reshards sequence→heads — every device ends up with
    the FULL sequence for H/cp heads — the flash kernel runs ordinary
    full-sequence attention locally (causal masking is exact, positions
    are global), and a second ``all_to_all`` restores (B, H, S/cp, D).

    vs ``ring_attention``: two all_to_all collectives total (each moving
    the activations once) instead of cp ppermute rounds of KV blocks —
    cheaper when cp is large and ICI all_to_all bandwidth is good, but
    requires H % cp == 0 while the ring has no head constraint.  Both
    are beyond-reference extensions: apex's only sequence-length scaling
    is Megatron SP (SURVEY.md §2.5).

    Differentiable end to end (all_to_all transposes to all_to_all; the
    kernel brings its custom_vjp).
    """
    cp = comm.bound_axis_size(axis)
    if cp == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale)
    h, hk = q.shape[1], k.shape[1]
    if h % cp or hk % cp:
        # GQA composes with Ulysses when BOTH head counts split over the
        # axis (each device then holds H/cp q heads + HK/cp kv heads of
        # the full sequence); checking only q would let hk % cp != 0
        # die inside all_to_all with an opaque shape error
        raise ValueError(
            f"ulysses_attention: q heads ({h}) and kv heads ({hk}) "
            f"must be divisible by the '{axis}' axis size ({cp}); use "
            "ring_attention for head-count-agnostic context "
            "parallelism")

    def seq_to_heads(x):   # (B, H, S/cp, D) -> (B, H/cp, S, D)
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(x):   # (B, H/cp, S, D) -> (B, H, S/cp, D)
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    o = flash_attention(seq_to_heads(q), seq_to_heads(k),
                        seq_to_heads(v), causal=causal, scale=scale)
    return heads_to_seq(o)
