"""Int8 inference quantization (beyond-reference TPU extension).

The reference's perf toolbox ends at fp16 + 2:4 sparsity (ASP); TPUs
have a different fast path: the MXU runs int8×int8→int32 at twice the
bf16 rate (v5e: ~394 TOPS vs ~197 TFLOPS), and int8 weights halve HBM
traffic for bandwidth-bound inference.  Two modes, composable per layer:

- **weight-only** (``int8_matmul(..., dynamic=False)``): weights stored
  int8 + per-channel f32 scales, dequantized into the matmul operand —
  XLA fuses the dequant into the dot's operand read, so the win is
  weight memory/bandwidth (activation precision untouched).
- **dynamic full-int8** (``dynamic=True``): activations are quantized
  per-row at runtime (dynamic symmetric), the dot runs int8×int8 on the
  MXU with i32 accumulation, and the output is rescaled by
  (row_scale × channel_scale).

``quantize_model`` walks a params pytree and replaces selected float
matrices with ``QTensor``s; ``QuantDense`` mirrors
apex_tpu.fused_dense.FusedDense's contract for drop-in inference.
Training stays in bf16/f32 — this is an inference tier, like the
reference's ASP is a post-training tier.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

Pytree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QTensor:
    """Symmetric per-channel int8 weight: ``w ≈ q * scale``.

    q: int8, same shape as the original weight; scale: f32, shape 1 on
    ``axis`` (the contraction dim keeps full length).
    """
    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):   # the "logical" dtype callers see
        return self.scale.dtype


def _symmetric_int8(x: jax.Array, axis: int):
    """The one symmetric-int8 formula (weights AND activations):
    per-slice amax → scale, round, clip."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    return q, scale


def quantize_int8(w: jax.Array, axis: int = 0) -> QTensor:
    """Symmetric per-channel int8 quantization.

    axis: the CONTRACTION axis (reduced in the matmul) — scales are
    per-output-channel, i.e. per element of the other axes.
    """
    q, scale = _symmetric_int8(w, axis)
    return QTensor(q=q, scale=scale)


def dequantize(t: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (t.q.astype(jnp.float32) * t.scale).astype(dtype)


def _dynamic_quant_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 for activations (x: (..., K))."""
    return _symmetric_int8(x, axis=-1)


def quantize_kv_int8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-vector symmetric int8 for the serving KV arena: one scale
    per trailing ``head_dim`` vector (the same ``_symmetric_int8``
    formula as weights and activations — ONE quantization discipline
    in the codebase).  Returns ``(q int8, scale f32)`` with the
    trailing axis dropped from ``scale`` so it stores densely in the
    arena's page-parallel scale planes.  A token's quantization
    depends only on its OWN K/V vector, which is what keeps the
    engine's batch-composition-independence invariant intact under
    ``kv_dtype="int8"``."""
    q, scale = _symmetric_int8(x, axis=-1)
    return q, jnp.squeeze(scale, axis=-1)


def dequantize_kv(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_kv_int8`: f32 values from int8 pages
    plus the per-vector scale plane (broadcast over ``head_dim``)."""
    return q.astype(jnp.float32) * scale[..., None]


def int8_matmul(x: jax.Array, w: QTensor, *,
                dynamic: Optional[bool] = False) -> jax.Array:
    """``x @ dequant(w)`` with int8 weights; w quantized on axis 0
    (shape (K, N), scale (1, N)).

    dynamic=False: weight-only — dequant folds into the dot operand.
    dynamic=True: per-row activation quant + int8×int8 MXU dot with i32
    accumulation, rescaled to x's dtype.
    dynamic=None ("auto"): the measured per-topology preference
    (``ops._dispatch.quantization_pref("int8_dynamic")``, written by
    the autotuner's quantization sweep) decides; absent entry =
    weight-only, the design default.  An explicit bool always wins —
    the table steers only callers that asked it to.
    """
    if dynamic is None:
        from apex_tpu.ops._dispatch import quantization_pref
        # host-side dispatch-table read at TRACE time, never a traced
        # value (serving reaches here jit-side with an explicit bool)
        dynamic = bool(quantization_pref(   # apexlint: disable=APX101
            "int8_dynamic", False))
    if not dynamic:
        return jax.lax.dot_general(
            x, dequantize(w, x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
    qx, sx = _dynamic_quant_rows(x)
    acc = jax.lax.dot_general(
        qx, w.q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    # squeeze the (1, N) channel scale so a 1-D x keeps rank 1 (matches
    # the weight-only path's (..., In) -> (..., Out) contract)
    return (acc.astype(jnp.float32) * sx
            * jnp.squeeze(w.scale, axis=0)).astype(x.dtype)


def quantize_model(params: Pytree,
                   predicate: Optional[Callable[[tuple, jax.Array],
                                                bool]] = None,
                   axis: int = 0) -> Pytree:
    """Replace selected float matrices in a params pytree with QTensors.

    predicate(path, leaf) -> bool decides per leaf; default: every
    floating 2D+ array (weights), leaving 1D (biases/norm params) alone.
    The result is still a pytree — checkpoints, tree_map, and jit all
    work on it unchanged.
    """
    if predicate is None:
        def predicate(path, leaf):
            return (hasattr(leaf, "ndim") and leaf.ndim >= 2
                    and jnp.issubdtype(leaf.dtype, jnp.floating))

    def visit(path, leaf):
        if predicate(path, leaf):
            return quantize_int8(leaf, axis=axis)
        return leaf

    return jax.tree_util.tree_map_with_path(
        visit, params, is_leaf=lambda x: isinstance(x, QTensor))


class QuantDense:
    """Inference drop-in for fused_dense.FusedDense over int8 weights.

    >>> qd = QuantDense.from_weights(weight, bias, dynamic=True)
    >>> y = qd(x)        # x (..., In) -> (..., Out)

    weight follows the reference layout (Out, In) — quantization is
    per-Out-channel over the In contraction.
    """

    def __init__(self, qweight: QTensor, bias: Optional[jax.Array] = None,
                 dynamic: Optional[bool] = False):
        # dynamic=None defers to the measured per-topology routing at
        # each call (int8_matmul's "auto" contract)
        self.qweight = qweight    # stored (In, Out), scale (1, Out)
        self.bias = bias
        self.dynamic = dynamic

    @classmethod
    def from_weights(cls, weight: jax.Array,
                     bias: Optional[jax.Array] = None,
                     dynamic: Optional[bool] = False) -> "QuantDense":
        # (Out, In) -> transpose once at quantization time so the hot
        # matmul is a plain (…, In) @ (In, Out)
        return cls(quantize_int8(jnp.transpose(weight), axis=0),
                   bias=bias, dynamic=dynamic)

    def __call__(self, x: jax.Array) -> jax.Array:
        y = int8_matmul(x, self.qweight, dynamic=self.dynamic)
        if self.bias is not None:
            y = y + self.bias.astype(y.dtype)
        return y
