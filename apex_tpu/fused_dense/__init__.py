from apex_tpu.fused_dense.fused_dense import (FusedDense,
                                              FusedDenseGeluDense,
                                              fp8_matmul,
                                              fused_dense_function,
                                              fused_dense_gelu_dense_function)

__all__ = ["FusedDense", "FusedDenseGeluDense", "fp8_matmul",
           "fused_dense_function", "fused_dense_gelu_dense_function"]
