from apex_tpu.fused_dense.fused_dense import (FusedDense,
                                              FusedDenseGeluDense,
                                              fused_dense_function,
                                              fused_dense_gelu_dense_function)

__all__ = ["FusedDense", "FusedDenseGeluDense", "fused_dense_function",
           "fused_dense_gelu_dense_function"]
