"""Epilogue-fused dense layers (reference: apex/fused_dense/fused_dense.py
+ csrc/fused_dense.cpp using cuBLASLt epilogues).

GEMM+bias and GEMM+bias+GELU+GEMM+bias: on TPU these epilogues are
exactly what XLA fuses into the matmul, so the module keeps the
reference's API while a single jit region delivers the fusion
(SURVEY.md §2.4).  f32 accumulation via preferred_element_type.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def fused_dense_function(x, weight, bias=None):
    """y = x @ W^T + b (torch Linear weight layout: (out, in))."""
    y = jnp.dot(x, weight.T, preferred_element_type=jnp.float32
                ).astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def fused_dense_gelu_dense_function(x, w1, b1, w2, b2):
    h = fused_dense_function(x, w1, b1)
    h = jax.nn.gelu(h, approximate=True)
    return fused_dense_function(h, w2, b2)


class FusedDense(nn.Module):
    """Reference-shaped: FusedDense(in_features, out_features, bias)."""
    in_features: int
    out_features: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # torch Linear weight layout is (out, in): fan-in is the LAST axis
        w = self.param("weight",
                       nn.initializers.lecun_normal(in_axis=-1, out_axis=-2),
                       (self.out_features, self.in_features),
                       self.param_dtype)
        b = (self.param("bias", nn.initializers.zeros,
                        (self.out_features,), self.param_dtype)
             if self.bias else None)
        return fused_dense_function(x, w, b)


class FusedDenseGeluDense(nn.Module):
    """Reference-shaped: Linear+GELU+Linear in one fused region."""
    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.lecun_normal(in_axis=-1, out_axis=-2)
        w1 = self.param("weight1", init,
                        (self.intermediate_features, self.in_features),
                        self.param_dtype)
        b1 = (self.param("bias1", nn.initializers.zeros,
                         (self.intermediate_features,), self.param_dtype)
              if self.bias else None)
        w2 = self.param("weight2", init,
                        (self.out_features, self.intermediate_features),
                        self.param_dtype)
        b2 = (self.param("bias2", nn.initializers.zeros,
                         (self.out_features,), self.param_dtype)
              if self.bias else None)
        return fused_dense_gelu_dense_function(x, w1, b1, w2, b2)
