"""Epilogue-fused dense layers (reference: apex/fused_dense/fused_dense.py
+ csrc/fused_dense.cpp using cuBLASLt epilogues).

GEMM+bias and GEMM+bias+GELU+GEMM+bias: on TPU these epilogues are
exactly what XLA fuses into the matmul, so the module keeps the
reference's API while a single jit region delivers the fusion
(SURVEY.md §2.4).  f32 accumulation via preferred_element_type.

fp8 path (``fp8_matmul`` / ``fp8=Fp8Policy(...)`` on the modules and
functions): operands quantize to e4m3 in the forward and the incoming
cotangent to e5m2 in the backward — fp8-capable MXUs run these dots at
~2x the bf16 rate.  Scales follow the delayed-scaling discipline of
``apex_tpu.amp.fp8``: pass ``w_scale=`` (and ``x_scale=``/``g_scale=``)
from the packed per-bucket state for delayed scaling, or omit them for
just-in-time (current) scaling.  Exactly ONE quantize convert per
operand and ONE per cotangent — the e5m2 cotangent is shared by dx and
dw — pinned program-wide by the apexverify spec ``amp.fp8_step``.
Where the backend cannot compile fp8 dots the quantization still runs
and the dot upcasts to bf16 (the bit-identical-bookkeeping fallback;
docs/amp.md "fp8 training" fallback matrix).
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.amp.fp8 import Fp8Policy, dynamic_scale, quantize


def _fp8_operand(q, policy: Fp8Policy):
    """The dot operand for a quantized array: fp8 straight to the MXU
    where the backend compiles it, else the bf16-compute oracle
    (upcast AFTER quantization — the rounding, saturation and scale
    bookkeeping are identical on both paths)."""
    if policy.uses_fp8_compute():
        return q
    return q.astype(jnp.bfloat16)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fp8_matmul(policy: Fp8Policy, x, w, x_scale, w_scale, g_scale):
    out, _ = _fp8_matmul_fwd(policy, x, w, x_scale, w_scale, g_scale)
    return out


def _fp8_matmul_fwd(policy, x, w, x_scale, w_scale, g_scale):
    qx = quantize(x, x_scale, policy.fwd_dtype() or policy.fwd_format)
    qw = quantize(w, w_scale, policy.fwd_dtype() or policy.fwd_format)
    acc = jax.lax.dot_general(
        _fp8_operand(qx, policy), _fp8_operand(qw, policy),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = (acc / (jnp.asarray(x_scale, jnp.float32)
                  * jnp.asarray(w_scale, jnp.float32))).astype(x.dtype)
    # zero-size dtype carriers: residual leaves must be arrays, and the
    # backward needs the PRIMAL dtypes for its cotangent casts
    return out, (qx, qw, x_scale, w_scale, g_scale,
                 jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype))


def _fp8_matmul_bwd(policy, res, g):
    qx, qw, sx, sw, sg, x_like, w_like = res
    sg_primal_none = sg is None
    if sg_primal_none:
        sg = dynamic_scale(g, policy.bwd_max())
    # ONE e5m2 quantize of the cotangent, shared by dx and dw — casts
    # must never silently multiply (spec amp.fp8_step pins the count)
    qg = quantize(g, sg, policy.bwd_dtype() or policy.bwd_format)
    og, ow, ox = (_fp8_operand(qg, policy), _fp8_operand(qw, policy),
                  _fp8_operand(qx, policy))
    sx = jnp.asarray(sx, jnp.float32)
    sw = jnp.asarray(sw, jnp.float32)
    sg = jnp.asarray(sg, jnp.float32)
    # dx = g @ w.T: contract the output dim
    dx = jax.lax.dot_general(
        og, ow, (((og.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) / (sg * sw)
    # dw = x.T @ g over all leading dims
    k = ox.shape[-1]
    n = og.shape[-1]
    dw = jax.lax.dot_general(
        ox.reshape(-1, k), og.reshape(-1, n),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / (sx * sg)
    # scales are non-differentiable data: symbolic-zero cotangents
    return (dx.astype(x_like.dtype), dw.astype(w_like.dtype),
            jnp.zeros_like(sx), jnp.zeros_like(sw),
            None if sg_primal_none else jnp.zeros_like(sg))


_fp8_matmul.defvjp(_fp8_matmul_fwd, _fp8_matmul_bwd)


def fp8_matmul(x, w, *, policy: Optional[Fp8Policy] = None,
               x_scale=None, w_scale=None, g_scale=None):
    """``(..., K) @ (K, N)`` through the fp8 path.

    Forward: x and w quantize to the policy's forward format (e4m3)
    with ``x_scale``/``w_scale`` — the DELAYED per-tensor scales from
    the packed state (``FusedOptimizerBase.fp8_scales()`` /
    ``amp.fp8.scales_tree``), or just-in-time amax scaling when
    omitted.  Backward: the cotangent quantizes ONCE to the backward
    format (e5m2) with ``g_scale`` (delayed) or current scaling, and
    feeds both dx and dw.  f32 accumulation throughout; output in
    ``x.dtype``.
    """
    if policy is None:
        policy = Fp8Policy()
    if x_scale is None:
        x_scale = dynamic_scale(x, policy.fwd_max())
    if w_scale is None:
        w_scale = dynamic_scale(w, policy.fwd_max())
    return _fp8_matmul(policy, x, w, x_scale, w_scale, g_scale)


def fused_dense_function(x, weight, bias=None, fp8=None, w_scale=None):
    """y = x @ W^T + b (torch Linear weight layout: (out, in)).

    ``fp8``: an :class:`~apex_tpu.amp.fp8.Fp8Policy` routes the matmul
    through :func:`fp8_matmul` (``w_scale``: the weight's delayed
    per-tensor scale; omitted = just-in-time scaling)."""
    if fp8 is not None:
        y = fp8_matmul(x, weight.T, policy=fp8, w_scale=w_scale)
    else:
        y = jnp.dot(x, weight.T, preferred_element_type=jnp.float32
                    ).astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def fused_dense_gelu_dense_function(x, w1, b1, w2, b2, fp8=None,
                                    w_scales=None):
    s1, s2 = w_scales if w_scales is not None else (None, None)
    h = fused_dense_function(x, w1, b1, fp8=fp8, w_scale=s1)
    h = jax.nn.gelu(h, approximate=True)
    return fused_dense_function(h, w2, b2, fp8=fp8, w_scale=s2)


class FusedDense(nn.Module):
    """Reference-shaped: FusedDense(in_features, out_features, bias).

    ``fp8``: an :class:`~apex_tpu.amp.fp8.Fp8Policy` routes the matmul
    through the e4m3/e5m2 path (just-in-time scaling at the module
    level; thread delayed per-tensor scales through
    ``fused_dense_function(w_scale=...)`` for the packed-state
    discipline)."""
    in_features: int
    out_features: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32
    fp8: Optional[Fp8Policy] = None

    @nn.compact
    def __call__(self, x):
        # torch Linear weight layout is (out, in): fan-in is the LAST axis
        w = self.param("weight",
                       nn.initializers.lecun_normal(in_axis=-1, out_axis=-2),
                       (self.out_features, self.in_features),
                       self.param_dtype)
        b = (self.param("bias", nn.initializers.zeros,
                        (self.out_features,), self.param_dtype)
             if self.bias else None)
        return fused_dense_function(x, w, b, fp8=self.fp8)


class FusedDenseGeluDense(nn.Module):
    """Reference-shaped: Linear+GELU+Linear in one fused region."""
    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32
    fp8: Optional[Fp8Policy] = None

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.lecun_normal(in_axis=-1, out_axis=-2)
        w1 = self.param("weight1", init,
                        (self.intermediate_features, self.in_features),
                        self.param_dtype)
        b1 = (self.param("bias1", nn.initializers.zeros,
                         (self.intermediate_features,), self.param_dtype)
              if self.bias else None)
        w2 = self.param("weight2", init,
                        (self.out_features, self.intermediate_features),
                        self.param_dtype)
        b2 = (self.param("bias2", nn.initializers.zeros,
                         (self.out_features,), self.param_dtype)
              if self.bias else None)
        return fused_dense_gelu_dense_function(x, w1, b1, w2, b2,
                                               fp8=self.fp8)
