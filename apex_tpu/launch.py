"""Multi-process launcher (reference: ``python -m
torch.distributed.launch --nproc_per_node=N train.py`` and the
examples/simple/distributed run.sh flows, SURVEY.md §2.6).

    python -m apex_tpu.launch --nproc 4 train.py --lr 0.1

Spawns ``nproc`` worker processes with the launcher env contract set —
``WORLD_SIZE``, ``RANK``, ``LOCAL_RANK``, ``JAX_COORDINATOR_ADDRESS``
— which is exactly what ``comm.initialize_distributed()`` (the
``init_process_group`` analog) consumes inside each worker.  Multi-node
use passes ``--nnodes``/``--node-rank``/``--coordinator`` so every node
agrees on the rendezvous (rank = node_rank * nproc + local_rank).

On TPU pods this launcher is usually unnecessary — the pod runtime
announces itself and ``initialize_distributed()`` autodetects — but
CPU/GPU-style multi-process development, CI, and the reference's
launch idiom port 1:1 through it.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["main"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.launch",
        description="spawn N processes with the distributed env "
                    "contract (reference: torch.distributed.launch)")
    ap.add_argument("--nproc", "--nproc-per-node", type=int, default=1,
                    dest="nproc", help="processes on this node")
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node-rank", type=int, default=0)
    ap.add_argument("--coordinator", default=None,
                    help="host:port every node can reach; default: a "
                         "free local port (single-node)")
    ap.add_argument("--module", "-m", action="store_true",
                    help="run script as a module (python -m)")
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    if args.nproc < 1 or args.nnodes < 1:
        ap.error(f"--nproc/--nnodes must be >= 1 (got {args.nproc}/"
                 f"{args.nnodes}); a zero-worker launch exiting 0 "
                 "would report success with no training run")
    if not 0 <= args.node_rank < args.nnodes:
        ap.error(f"--node-rank {args.node_rank} outside "
                 f"[0, {args.nnodes})")
    if args.nnodes > 1 and not args.coordinator:
        ap.error("--coordinator host:port is required with --nnodes>1 "
                 "(every node must name the same rendezvous)")
    coordinator = args.coordinator or f"127.0.0.1:{_free_port()}"
    world = args.nnodes * args.nproc

    procs = []
    try:
        for local_rank in range(args.nproc):
            env = dict(os.environ)
            env["JAX_COORDINATOR_ADDRESS"] = coordinator
            env["WORLD_SIZE"] = str(world)
            env["RANK"] = str(args.node_rank * args.nproc + local_rank)
            env["LOCAL_RANK"] = str(local_rank)
            cmd = [sys.executable]
            if args.module:
                cmd += ["-m", args.script]
            else:
                cmd += [args.script]
            cmd += args.script_args
            procs.append(subprocess.Popen(cmd, env=env))
        # first nonzero exit wins and tears the rest down (the finally
        # below) — a crashed rank must not leave siblings hanging in
        # collectives forever (torchrun semantics)
        rc = 0
        alive = list(procs)
        while alive and rc == 0:
            for p in list(alive):
                r = p.poll()
                if r is not None:
                    alive.remove(p)
                    rc = rc or r
            if alive and rc == 0:
                time.sleep(0.2)
        return rc
    finally:
        # one worker failing (or ^C) must not leave siblings running:
        # the reference launcher's kill-the-group semantics
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


if __name__ == "__main__":
    sys.exit(main())
