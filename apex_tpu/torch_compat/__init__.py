"""torch-facing compatibility frontend (SURVEY.md §0's public contract).

The reference is a torch extension: ``from apex import amp;
model, opt = amp.initialize(model, opt, opt_level="O2")`` — keep your
torch training loop.  On this stack the TPU is reachable only through
JAX (no torch_xla exists here), so the TPU compute path is the
JAX-native core package; THIS subpackage reproduces the reference's
torch API for torch-on-CPU — the reference's own "Python-only install"
degradation (no CUDA extensions → pure-Python amp), and BASELINE.md
config 1 (ResNet-18 amp O0/O1, one process, CPU).

    from apex_tpu.torch_compat import amp
    model, optimizer = amp.initialize(model, optimizer, opt_level="O1")
    with amp.scale_loss(loss, optimizer) as scaled_loss:
        scaled_loss.backward()
    optimizer.step()

docs/porting.md maps each reference surface to its JAX-native
equivalent for the TPU path.
"""

from . import amp

__all__ = ["amp"]
