"""apex.amp for torch models on CPU — the reference's pure-Python amp.

Reference surfaces reproduced (upstream-expected paths, SURVEY.md §2.1;
the reference mount was empty, so no line numbers):

- ``apex/amp/frontend.py`` — ``initialize`` + the O0–O3 ``Properties``
  tables with per-kwarg override, ``state_dict``/``load_state_dict``.
- ``apex/amp/wrap.py`` + ``lists/`` — O1 monkey-patching of torch
  functions per FP16/FP32 lists (GEMM/conv → half; softmax/log/exp/
  norm/loss → fp32).
- ``apex/amp/_initialize.py``/``_process_optimizer.py`` — O2 model
  cast with batchnorm exemption, input casting on ``forward``, fp32
  master params, patched ``optimizer.step`` (skip on overflow, master
  step + copy-back).
- ``apex/amp/scaler.py``/``handle.py`` — dynamic loss scaling
  (grow ×2 per 2000 clean steps, backoff ×0.5 on inf/nan) and the
  ``scale_loss`` context manager.

Deliberate deviations, documented in PARITY.md: CPU torch only (the
TPU path is the JAX-native core; no torch_xla exists on this stack);
``cast_model_type`` defaults to ``torch.bfloat16`` — the CPU-native
half type — instead of fp16 (override with
``cast_model_type=torch.float16`` for reference-exact dtypes).
"""

from __future__ import annotations

import contextlib
import copy
import functools
import warnings

import torch
import torch.nn.functional as F

__all__ = ["initialize", "scale_loss", "state_dict", "load_state_dict",
           "master_params", "deinitialize", "Properties", "LossScaler"]

_CPU_HALF = torch.bfloat16     # fp16 matmuls exist on CPU but crawl


class Properties:
    """Resolved option bundle for one ``initialize`` call (reference:
    frontend.py's Properties; attributes, not a dict, so user code that
    reads ``amp._amp_state.opt_properties.loss_scale`` ports over)."""

    def __init__(self, **kw):
        self.opt_level = kw["opt_level"]
        self.cast_model_type = kw["cast_model_type"]
        self.patch_torch_functions = kw["patch_torch_functions"]
        self.keep_batchnorm_fp32 = kw["keep_batchnorm_fp32"]
        self.master_weights = kw["master_weights"]
        self.loss_scale = kw["loss_scale"]


_OPT_LEVELS = {
    "O0": dict(cast_model_type=None, patch_torch_functions=False,
               keep_batchnorm_fp32=None, master_weights=False,
               loss_scale=1.0),
    "O1": dict(cast_model_type=None, patch_torch_functions=True,
               keep_batchnorm_fp32=None, master_weights=False,
               loss_scale="dynamic"),
    "O2": dict(cast_model_type=_CPU_HALF, patch_torch_functions=False,
               keep_batchnorm_fp32=True, master_weights=True,
               loss_scale="dynamic"),
    "O3": dict(cast_model_type=_CPU_HALF, patch_torch_functions=False,
               keep_batchnorm_fp32=False, master_weights=False,
               loss_scale=1.0),
}


class LossScaler:
    """Dynamic loss scaling (reference: apex/amp/scaler.py): backoff
    ×0.5 on overflow, grow ×2 after 2000 consecutive clean steps,
    clamped to [min_loss_scale, max_loss_scale]."""

    def __init__(self, loss_scale="dynamic", init_scale=2.0 ** 16,
                 scale_factor=2.0, scale_window=2000,
                 min_loss_scale=None, max_loss_scale=2.0 ** 24):
        self.dynamic = loss_scale == "dynamic"
        self._scale = float(init_scale if self.dynamic else loss_scale)
        self._factor = scale_factor
        self._window = scale_window
        self._min = (1.0 if min_loss_scale is None
                     else float(min_loss_scale))
        self._max = float(max_loss_scale)
        self._unskipped = 0

    def loss_scale(self):
        return self._scale

    def update_scale(self, overflow: bool):
        if not self.dynamic:
            return
        if overflow:
            self._scale = max(self._scale / self._factor, self._min)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self._scale = min(self._scale * self._factor,
                                  self._max)
                self._unskipped = 0


class _AmpState:
    def __init__(self):
        self.initialized = False
        self.enabled = True
        self.opt_properties = None
        self.loss_scalers = []
        self.optimizers = []
        self._patches = []       # (owner, name, original)
        self._forward_patched = []  # (model, original_forward)
        self._cast_models = []   # (model, {name: fp32 tensor})
        self._orig_fp32 = {}     # id(cast param) -> original fp32


_amp_state = _AmpState()


# ---------------------------------------------------------------------------
# O1: monkey-patched op lists (reference: apex/amp/lists/*.py)
# ---------------------------------------------------------------------------

# GEMM/conv-class ops run in half precision...
_FP16_FUNCS = [
    (torch, "mm"), (torch, "matmul"), (torch, "bmm"), (torch, "addmm"),
    (torch, "addbmm"), (torch, "baddbmm"), (torch, "conv1d"),
    (torch, "conv2d"), (torch, "conv3d"),
    (F, "linear"), (F, "conv1d"), (F, "conv2d"), (F, "conv3d"),
]
# ...reductions/exponentials/losses in fp32
_FP32_FUNCS = [
    (torch, "exp"), (torch, "log"), (torch, "pow"), (torch, "softmax"),
    (torch, "log_softmax"),
    (F, "softmax"), (F, "log_softmax"), (F, "cross_entropy"),
    (F, "nll_loss"), (F, "mse_loss"), (F, "l1_loss"),
    (F, "layer_norm"), (F, "group_norm"), (F, "cosine_similarity"),
]


def _cast_tree(x, dtype):
    if isinstance(x, torch.Tensor) and x.is_floating_point() \
            and x.dtype != dtype:
        return x.to(dtype)
    if isinstance(x, tuple) and hasattr(x, "_fields"):   # namedtuple
        return type(x)(*(_cast_tree(v, dtype) for v in x))
    if isinstance(x, (list, tuple)):
        return type(x)(_cast_tree(v, dtype) for v in x)
    if isinstance(x, dict):      # dict batches (the collate pattern);
        out = copy.copy(x)       # copy preserves subclass state
        for k, v in x.items():   # (defaultdict factory, OrderedDict)
            out[k] = _cast_tree(v, dtype)
        return out
    return x


def _wrap_cast(fn, dtype):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if "out" in kwargs:
            # out= is a destination, not an operand: casting it would
            # leave the caller's buffer unwritten, and NOT casting it
            # trips torch's dtype check against the cast operands —
            # the reference bans out= on patched ops the same way
            raise NotImplementedError(
                f"amp O1: out= is not supported on the patched op "
                f"{getattr(fn, '__name__', fn)!s} — drop out= (use "
                f"the return value) or initialize without "
                f"patch_torch_functions")
        return fn(*_cast_tree(list(args), dtype),
                  **{k: _cast_tree(v, dtype)
                     for k, v in kwargs.items()})
    wrapper._amp_original = fn
    return wrapper


def _patch_torch_functions(half_dtype):
    for owner, name in _FP16_FUNCS:
        fn = getattr(owner, name, None)
        if fn is None or hasattr(fn, "_amp_original"):
            continue
        _amp_state._patches.append((owner, name, fn))
        setattr(owner, name, _wrap_cast(fn, half_dtype))
    for owner, name in _FP32_FUNCS:
        fn = getattr(owner, name, None)
        if fn is None or hasattr(fn, "_amp_original"):
            continue
        _amp_state._patches.append((owner, name, fn))
        setattr(owner, name, _wrap_cast(fn, torch.float32))


# ---------------------------------------------------------------------------
# O2: model cast + master weights (reference: _initialize.py,
# _process_optimizer.py)
# ---------------------------------------------------------------------------

def _cast_model(model, dtype, keep_batchnorm_fp32,
                cast_model_outputs=None):
    # snapshot EVERY float tensor before the cast: (a) BN restoration
    # below must be exact, not a half round-trip; (b) O2 masters copy
    # from these originals instead of re-upcasting rounded half params
    # (same fidelity rule as the JAX amp path); (c) deinitialize puts
    # the fp32 model back so the module is usable after un-patching
    saved_model = {
        name: t.detach().clone()
        for name, t in (list(model.named_parameters())
                        + list(model.named_buffers()))
        if t.is_floating_point() and t.dtype != dtype}
    _amp_state._cast_models.append((model, saved_model))
    param_names = {name: p for name, p in model.named_parameters()}
    bn_saved = []
    if keep_batchnorm_fp32:
        for m in model.modules():
            if isinstance(m, torch.nn.modules.batchnorm._BatchNorm):
                saved = {k: v.clone() for k, v in
                         list(m.named_parameters(recurse=False))
                         + list(m.named_buffers(recurse=False))}
                bn_saved.append((m, saved))
    model.to(dtype)
    for name, p in param_names.items():   # param objects survive .to()
        if name in saved_model:
            _amp_state._orig_fp32[id(p)] = saved_model[name]
    for m, saved in bn_saved:
        for k, v in saved.items():
            getattr(m, k).data = v
        # half activations meet fp32 BN params: run the BN itself in
        # fp32 and hand back the half dtype (reference semantics of
        # keep_batchnorm_fp32; CPU batch_norm rejects mixed dtypes)
        if not hasattr(m.forward, "_amp_original"):
            m.forward = _wrap_bn_fp32(m, m.forward, dtype)
    orig_forward = model.forward

    @functools.wraps(orig_forward)
    def forward(*args, **kwargs):
        out = orig_forward(*_cast_tree(list(args), dtype),
                           **{k: _cast_tree(v, dtype)
                              for k, v in kwargs.items()})
        if cast_model_outputs is not None:
            out = _cast_tree(out, cast_model_outputs)
        return out

    forward._amp_original = orig_forward
    model.forward = forward
    _amp_state._forward_patched.append((model, orig_forward))


def _wrap_bn_fp32(module, orig, half_dtype):
    @functools.wraps(orig)
    def forward(x, *args, **kwargs):
        return orig(x.float(), *args, **kwargs).to(half_dtype)

    forward._amp_original = orig
    _amp_state._forward_patched.append((module, orig))
    return forward


def _process_optimizer(optimizer, props):
    """Patch ``step`` (and wire master weights under O2): unscaling and
    the overflow verdict happen in ``scale_loss.__exit__``; the patched
    step consumes the verdict — skip entirely on overflow, otherwise
    step (the fp32 masters, if any) and copy back down."""
    optimizer._amp_overflow = False
    optimizer._amp_masters = []       # [(master_param, model_param)]

    if props.master_weights:
        for group in optimizer.param_groups:
            new_params = []
            for p in group["params"]:
                if p.requires_grad and p.is_floating_point() \
                        and p.dtype != torch.float32:
                    # prefer the pre-cast fp32 original captured by
                    # _cast_model over re-upcasting the rounded half
                    orig = _amp_state._orig_fp32.get(id(p))
                    master = (orig.detach().clone() if orig is not None
                              else p.detach().clone().float())
                    master.requires_grad_(True)
                    optimizer._amp_masters.append((master, p))
                    new_params.append(master)
                else:
                    new_params.append(p)
            group["params"] = new_params

    orig_step = optimizer.step

    @functools.wraps(orig_step)
    def step(closure=None):
        if optimizer._amp_overflow:
            optimizer._amp_overflow = False
            return None   # reference behavior: skipped step, no update
        out = orig_step(closure) if closure is not None else orig_step()
        with torch.no_grad():
            for master, model_p in optimizer._amp_masters:
                model_p.copy_(master.to(model_p.dtype))
        return out

    step._amp_original = orig_step
    optimizer.step = step

    if optimizer._amp_masters:
        # the param groups now hold the fp32 masters, so the stock
        # zero_grad no longer reaches the MODEL params backward
        # actually writes to — without this, model grads accumulate
        # across steps (reference: _process_optimizer patches
        # zero_grad for exactly this)
        orig_zero = optimizer.zero_grad

        @functools.wraps(orig_zero)
        def zero_grad(set_to_none: bool = True):
            orig_zero(set_to_none=set_to_none)
            for _, model_p in optimizer._amp_masters:
                model_p.grad = None

        zero_grad._amp_original = orig_zero
        optimizer.zero_grad = zero_grad


def _grads_for(optimizer):
    """(grad, param) pairs the unscale/overflow pass walks: the MODEL
    grads (where backward deposited them), plus the master mirror."""
    pairs = []
    seen_masters = {id(m) for m, _ in optimizer._amp_masters}
    for group in optimizer.param_groups:
        for p in group["params"]:
            if id(p) in seen_masters:
                continue            # masters get grads via the copy below
            if p.grad is not None:
                pairs.append((p.grad, p))
    for master, model_p in optimizer._amp_masters:
        if model_p.grad is not None:
            pairs.append((model_p.grad, model_p))
    return pairs


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def initialize(models, optimizers=None, opt_level="O1", **overrides):
    """Reference: apex.amp.initialize.  Accepts one model/optimizer or
    lists of either; returns the same shape it was given."""
    if opt_level not in _OPT_LEVELS:
        raise ValueError(
            f"opt_level must be one of {sorted(_OPT_LEVELS)}, got "
            f"{opt_level!r}")
    if _amp_state.initialized:
        # a second pass over an already-processed optimizer would
        # orphan its masters (param_groups hold fp32 copies the model
        # grads no longer reach) — undo everything first so re-init
        # behaves like a fresh init
        warnings.warn("amp.initialize called twice; undoing previous "
                      "patches and reinitializing")
        deinitialize()
    patch_dtype = overrides.pop("patch_dtype", _CPU_HALF)
    num_losses = overrides.pop("num_losses", None)
    # reference-surface kwargs (apex/amp/frontend.py): verbosity is
    # accepted and ignored (we don't print banners); enabled=False
    # makes the whole frontend a no-op passthrough; the scale bounds
    # feed the LossScaler; cast_model_outputs casts what the patched
    # forward RETURNS
    overrides.pop("verbosity", None)
    enabled = overrides.pop("enabled", True)
    min_loss_scale = overrides.pop("min_loss_scale", None)
    max_loss_scale = overrides.pop("max_loss_scale", 2.0 ** 24)
    cast_model_outputs = overrides.pop("cast_model_outputs", None)
    opts = dict(_OPT_LEVELS[opt_level])
    for k, v in overrides.items():
        if v is None:
            continue
        if k not in opts:
            raise TypeError(f"unknown amp.initialize option {k!r}")
        opts[k] = v
    props = Properties(opt_level=opt_level, **opts)

    models_list = models if isinstance(models, (list, tuple)) \
        else [models]
    opt_list = ([] if optimizers is None
                else optimizers if isinstance(optimizers, (list, tuple))
                else [optimizers])

    _amp_state.enabled = bool(enabled)
    if not _amp_state.enabled:
        # reference: enabled=False leaves models/optimizers untouched;
        # scale_loss degrades to a passthrough
        _amp_state.opt_properties = props
        _amp_state.initialized = True
        return models if optimizers is None else (models, optimizers)

    if props.cast_model_type is not None:
        for m in models_list:
            _cast_model(m, props.cast_model_type,
                        props.keep_batchnorm_fp32,
                        cast_model_outputs)
    if props.patch_torch_functions:
        _patch_torch_functions(patch_dtype)

    _amp_state.opt_properties = props
    _amp_state.optimizers = list(opt_list)
    # reference: num_losses > 1 gives each loss its own scaler (the
    # scale_loss(loss_id=...) companion); default one per optimizer
    _amp_state.loss_scalers = [
        LossScaler(props.loss_scale, min_loss_scale=min_loss_scale,
                   max_loss_scale=max_loss_scale)
        for _ in range(num_losses or max(1, len(opt_list)))]
    for opt in opt_list:
        _process_optimizer(opt, props)
    # the snapshot's job for master-paired params is done (masters
    # seeded from it; deinitialize restores those from the TRAINED
    # masters) — drop the redundant fp32 copies so O2 doesn't hold a
    # third full-model buffer for the life of the process
    mastered = {id(mp) for opt in opt_list
                for _, mp in getattr(opt, "_amp_masters", [])}
    for m, saved in _amp_state._cast_models:
        for name, p in m.named_parameters():
            if id(p) in mastered:
                saved.pop(name, None)
    _amp_state._orig_fp32.clear()      # only needed to seed masters
    _amp_state.initialized = True

    if optimizers is None:
        return models
    return models, optimizers


@contextlib.contextmanager
def scale_loss(loss, optimizer, loss_id=0, delay_unscale=False):
    """Reference: apex.amp.handle.scale_loss.  Multiplies the loss by
    the current scale for backward; on exit unscales the grads in
    place, detects inf/nan, posts the skip verdict to the patched
    ``optimizer.step``, and updates the dynamic scale.

    delay_unscale=True (reference escape hatch for gradient
    accumulation): the exit does NOTHING — grads stay scaled and keep
    accumulating; only the final micro-batch's scale_loss (with the
    default delay_unscale=False) unscales the sum and renders the
    overflow verdict.  Without it, each exit would divide the
    accumulated sum by the scale again, destroying every earlier
    micro-batch's contribution."""
    if not _amp_state.initialized:
        raise RuntimeError("amp.scale_loss used before amp.initialize")
    if not _amp_state.enabled:
        yield loss                  # enabled=False: pure passthrough
        return
    if not hasattr(optimizer, "_amp_masters"):
        raise RuntimeError(
            "this optimizer was not prepared by amp.initialize — pass "
            "it to amp.initialize(models, optimizers, ...) first")
    scaler = _amp_state.loss_scalers[loss_id]
    scale = scaler.loss_scale()
    yield loss.float() * scale
    if delay_unscale:
        return

    overflow = False
    with torch.no_grad():
        for grad, _ in _grads_for(optimizer):
            if not torch.isfinite(grad).all():
                overflow = True
                break
        if not overflow and scale != 1.0:
            for grad, _ in _grads_for(optimizer):
                grad.mul_(1.0 / scale)
        if not overflow:
            for master, model_p in optimizer._amp_masters:
                if model_p.grad is not None:
                    master.grad = model_p.grad.float()
    optimizer._amp_overflow = overflow
    scaler.update_scale(overflow)


def master_params(optimizer):
    """Reference: apex.amp.master_params — iterate the fp32 params the
    optimizer actually steps."""
    for group in optimizer.param_groups:
        yield from group["params"]


def state_dict():
    """Reference: amp.state_dict — loss-scaler state for checkpoints."""
    return {f"loss_scaler{i}": {"loss_scale": s.loss_scale(),
                                "unskipped": s._unskipped}
            for i, s in enumerate(_amp_state.loss_scalers)}


def load_state_dict(sd):
    for i, s in enumerate(_amp_state.loss_scalers):
        entry = sd.get(f"loss_scaler{i}")
        if entry:
            # checkpoint dict values are already host floats — no
            # device value is pulled here, per-scaler loop or not
            s._scale = float(entry["loss_scale"])   # apexlint: disable=APX102
            s._unskipped = int(entry["unskipped"])


def deinitialize():
    """Undo every monkey-patch AND restore cast models to their exact
    pre-cast fp32 tensors (not in the reference, which patches for the
    life of the process; here so test suites and notebooks can restore
    a clean torch — a model left in half with its input-cast wrapper
    removed would be unusable)."""
    for owner, name, fn in reversed(_amp_state._patches):
        setattr(owner, name, fn)
    for model, fwd in reversed(_amp_state._forward_patched):
        model.forward = fwd
    for model, saved in reversed(_amp_state._cast_models):
        tensors = dict(model.named_parameters())
        tensors.update(model.named_buffers())
        for name, orig in saved.items():
            t = tensors.get(name)
            # only un-cast tensors that are STILL cast: an fp32-exempt
            # tensor (keep_batchnorm_fp32 params, running stats) has
            # been training in place — overwriting it with the
            # pre-cast snapshot would roll its training back
            if t is not None and t.dtype != orig.dtype:
                t.data = orig
    for opt in _amp_state.optimizers:
        if hasattr(opt.step, "_amp_original"):
            opt.step = opt.step._amp_original
        if hasattr(opt.zero_grad, "_amp_original"):
            opt.zero_grad = opt.zero_grad._amp_original
        if getattr(opt, "_amp_masters", None):
            # put the MODEL params back in the groups so the optimizer
            # (and any later re-initialize) sees the real parameters —
            # carrying the TRAINED fp32 values from the masters (this
            # runs after the pre-cast snapshot restore above, so where
            # a master exists the trained value wins; without masters,
            # O3-style, deinitialize rolls back to the pre-cast
            # weights)
            swap = {id(m): mp for m, mp in opt._amp_masters}
            for master, model_p in opt._amp_masters:
                model_p.data = master.detach().clone()
            for group in opt.param_groups:
                group["params"] = [swap.get(id(p), p)
                                   for p in group["params"]]
            opt._amp_masters = []
    _amp_state.__init__()
