from apex_tpu.reparameterization.weight_norm import (  # noqa: F401
    WeightNorm,
    apply_weight_norm,
    remove_weight_norm,
    reparametrize,
)

__all__ = ["WeightNorm", "apply_weight_norm", "remove_weight_norm",
           "reparametrize"]
