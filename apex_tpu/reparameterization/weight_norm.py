"""Weight normalization (reference: apex/reparameterization/ —
`WeightNorm`/`Reparameterization` splitting w into direction v and
magnitude g, w = g * v / ||v||, SURVEY.md §2.1).

The reference hooks torch Parameters; functionally in JAX the split IS
the parameter tree: `apply_weight_norm` rewrites matching kernel leaves
into {v, g} subtrees, `reparametrize` reconstitutes w inside the forward
pass (differentiable — grads flow to v and g exactly as the reference's
autograd does), `remove_weight_norm` folds back to plain weights.
"""

from __future__ import annotations

import re
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

def _norm(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes,
                            keepdims=True))


_G_RE = re.compile(r"^g(\d+)$")


def _g_key(node):
    for k in node:
        m = _G_RE.match(k)
        if m:
            return k, int(m.group(1))
    return None, None


def _is_wn_node(node) -> bool:
    if not (isinstance(node, dict) and len(node) == 2 and "v" in node
            and isinstance(node["v"], jnp.ndarray)):
        return False
    return _g_key(node)[0] is not None


def apply_weight_norm(params: Any, name: str = "kernel", dim: int = -1):
    """Split every leaf whose key == `name` into a {v, g<dim>} subtree.
    The norm axis is encoded in the g key (structural metadata), so the
    tree contains only float leaves and stays jax.grad-able; size-1 axes
    are unambiguous."""
    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if k == name and isinstance(v, jnp.ndarray):
                d = dim % v.ndim
                out[k] = {"v": v, f"g{d}": _norm(v, d).astype(v.dtype)}
            else:
                out[k] = walk(v)
        return out
    return walk(jax.tree_util.tree_map(lambda x: x, params))


def reparametrize(params: Any):
    """Reconstitute w = g * v / ||v|| for every weight-normed leaf; call
    on the tree before module.apply."""
    def walk(node):
        if _is_wn_node(node):
            gk, d = _g_key(node)
            v, g = node["v"], node[gk]
            w = g.astype(jnp.float32) * v.astype(jnp.float32) / _norm(v, d)
            return w.astype(v.dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node
    return walk(params)


def remove_weight_norm(params: Any):
    """Fold {v, g} back into plain weights (reference remove_weight_norm)."""
    return reparametrize(params)


class WeightNorm(nn.Module):
    """Module wrapper parity: WeightNorm(module)(x) runs the wrapped
    module with weight-normed kernels, learning v and g."""

    module: nn.Module
    name: str = "kernel"
    dim: int = -1

    @nn.compact
    def __call__(self, *args, **kwargs):
        def init_fn(rng):
            vars_ = self.module.init(rng, *args, **kwargs)
            return apply_weight_norm(vars_["params"], self.name, self.dim)
        wn_params = self.param("wn", lambda rng: init_fn(rng))
        return self.module.apply({"params": reparametrize(wn_params)},
                                 *args, **kwargs)
