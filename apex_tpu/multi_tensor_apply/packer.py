"""One-time bucket packing for the fused optimizers.

The reference's ``multi_tensor_apply`` re-chunks the tensor lists on
every step (cheap on CUDA — it's host pointer math).  On TPU the analog
must not re-trace or re-concatenate per step, so the plan is computed
ONCE at optimizer init: dtype-homogeneous parameter leaves are assigned
to buckets, each bucket a single flat HBM buffer with static
shape+offset metadata.  The jitted optimizer step then runs one flat
Pallas kernel per bucket (see apex_tpu.ops.multi_tensor), and the
packed buffers are the persistent representation — params, masters and
optimizer state stay packed BETWEEN steps.  Unpacking (static
``lax.slice`` + reshape per leaf, offsets are Python ints) happens only
on the rare host-facing paths: ``state_dict()``, ``load_state_dict()``
and the ``params`` property.

Per-tensor semantics (LAMB trust ratios, NovoGrad per-tensor second
moments) survive packing through each bucket's ``segment_ids``: a
sorted i32 element->leaf map the segmented kernels reduce over.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


class LeafSpec(NamedTuple):
    index: int            # position in tree_leaves order
    shape: Tuple[int, ...]
    size: int             # element count
    offset: int           # element offset inside the bucket buffer


class Bucket(NamedTuple):
    dtype: Any            # work (stepped) dtype of every leaf in here
    model_dtype: Any      # model-param dtype (== dtype without masters)
    leaves: Tuple[LeafSpec, ...]
    size: int             # total element count (exact, unpadded)


def _leaf_arrays(tree) -> List[jax.Array]:
    return jax.tree_util.tree_leaves(tree)


class BucketPlan:
    """Static packing plan for one params pytree.

    Built from the WORK tree (masters when mixed-precision, else the
    params themselves) plus, when masters exist, the model params tree
    — buckets are keyed on (work dtype, model dtype) so the
    master->model writeback stays a single-dtype cast per bucket.
    """

    def __init__(self, treedef, buckets: Sequence[Bucket],
                 max_bucket_bytes: Optional[int] = None):
        self.treedef = treedef
        self.buckets = tuple(buckets)
        self.n_leaves = sum(len(b.leaves) for b in self.buckets)
        # the chunking cap this plan was built with (None = monolithic
        # per dtype group) — consumers that were ASKED for a specific
        # cap can detect a mismatching supplied plan (FlatGradPipeline)
        self.max_bucket_bytes = max_bucket_bytes
        self._seg_ids = None

    # ---- construction ----------------------------------------------------
    @classmethod
    def from_tree(cls, work: Pytree, model: Optional[Pytree] = None,
                  max_bucket_bytes: Optional[int] = None
                  ) -> Optional["BucketPlan"]:
        """Build a plan, or None when packing is unsupported: empty
        trees, non-floating leaves (nothing for an optimizer kernel to
        do with them), or multi-device leaves (concatenation would
        destroy their sharding — the per-leaf path preserves it).

        ``max_bucket_bytes``: optional chunking cap.  By default every
        dtype group packs into ONE bucket — maximal kernel fusion, but
        the data-parallel all-reduce then depends on the ENTIRE
        backward (one trailing collective).  With a cap, a dtype group
        splits into multiple buckets of at most that many bytes (leaf
        order preserved, every bucket holds >= 1 leaf), so each
        bucket's collective depends only on its own leaves' cotangents
        and the scheduler can overlap bucket k's psum with bucket
        k-1's backward compute (docs/perf.md "Overlap schedule")."""
        work_leaves, treedef = jax.tree_util.tree_flatten(work)
        if not work_leaves:
            return None
        model_leaves = (jax.tree_util.tree_leaves(model)
                        if model is not None else work_leaves)
        if len(model_leaves) != len(work_leaves):
            return None
        groups = {}
        for i, (w, p) in enumerate(zip(work_leaves, model_leaves)):
            if not (hasattr(w, "dtype") and hasattr(w, "shape")):
                return None
            if not jnp.issubdtype(w.dtype, jnp.floating):
                return None
            if isinstance(w, jax.Array):
                try:
                    multi = len(w.sharding.device_set) > 1
                except AttributeError:
                    # tracer (cached_plan inside a jit trace): sharding
                    # unknown — the caller owns that placement decision
                    multi = False
                if multi:
                    return None
            key = (jnp.dtype(w.dtype), jnp.dtype(p.dtype))
            groups.setdefault(key, []).append((i, w))
        buckets = []
        for (wdt, mdt), entries in groups.items():
            cap_elems = None
            if max_bucket_bytes is not None:
                cap_elems = max(1, int(max_bucket_bytes)
                                // jnp.dtype(wdt).itemsize)
            specs, offset = [], 0
            for i, w in entries:
                size = int(np.prod(w.shape)) if w.shape else 1
                if cap_elems is not None and specs \
                        and offset + size > cap_elems:
                    # start a fresh bucket: the cap is a soft split
                    # point, never a reason to split one leaf
                    buckets.append(Bucket(wdt, mdt, tuple(specs), offset))
                    specs, offset = [], 0
                specs.append(LeafSpec(i, tuple(w.shape), size, offset))
                offset += size
            buckets.append(Bucket(wdt, mdt, tuple(specs), offset))
        return cls(treedef, buckets, max_bucket_bytes=max_bucket_bytes)

    # ---- packing ---------------------------------------------------------
    def pack(self, tree: Pytree, dtypes=None) -> List[jax.Array]:
        """Pytree -> one flat buffer per bucket.  Trace-safe (the
        jitted step packs the incoming grads this way: one concatenate
        per bucket, not per leaf).  ``dtypes``: per-bucket target dtype
        (defaults to whatever concatenation yields — homogeneous
        inputs keep their dtype)."""
        leaves = _leaf_arrays(tree)
        out = []
        for bi, b in enumerate(self.buckets):
            parts = [jnp.ravel(leaves[s.index]) for s in b.leaves]
            buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            want = dtypes[bi] if dtypes is not None else None
            if want is not None and buf.dtype != want:
                buf = buf.astype(want)
            out.append(buf)
        return out

    def pack_work(self, tree: Pytree) -> List[jax.Array]:
        return self.pack(tree, dtypes=[b.dtype for b in self.buckets])

    def pack_model(self, tree: Pytree) -> List[jax.Array]:
        return self.pack(tree, dtypes=[b.model_dtype for b in self.buckets])

    def pack_grads(self, tree: Pytree) -> List[jax.Array]:
        """THE gradient pack: one concatenate per bucket, grads keep
        their own (model) dtype — the flat AMP pipeline's single pack
        point.  Everything downstream (bucketed all-reduce, fused
        unscale+norm, the flat optimizer kernels) consumes these
        buffers; nothing re-walks the pytree."""
        return self.pack(tree)

    def is_packed(self, obj) -> bool:
        """True iff ``obj`` is a per-bucket flat-buffer list matching
        this plan: one 1-D buffer per bucket, each exactly bucket-sized.
        Shape-only (works on tracers); used by step()/clip_grad to
        accept already-packed gradients without re-packing."""
        if not isinstance(obj, (list, tuple)) \
                or len(obj) != len(self.buckets):
            return False
        return all(
            getattr(buf, "ndim", None) == 1
            and tuple(buf.shape) == (b.size,)
            for buf, b in zip(obj, self.buckets))

    # ---- unpacking -------------------------------------------------------
    def _unpack_leaves(self, bufs: Sequence[jax.Array],
                       dtypes=None) -> List[jax.Array]:
        leaves: List[Optional[jax.Array]] = [None] * self.n_leaves
        for bi, b in enumerate(self.buckets):
            buf = bufs[bi]
            want = dtypes[bi] if dtypes is not None else None
            for s in b.leaves:
                # static offsets -> lax.slice: XLA sees fixed layout
                leaf = jax.lax.slice(buf, (s.offset,),
                                     (s.offset + s.size,)).reshape(s.shape)
                if want is not None and leaf.dtype != want:
                    leaf = leaf.astype(want)
                leaves[s.index] = leaf
        return leaves

    def unpack(self, bufs: Sequence[jax.Array]) -> Pytree:
        """Per-bucket flat buffers -> pytree in the WORK dtypes."""
        return jax.tree_util.tree_unflatten(
            self.treedef,
            self._unpack_leaves(bufs, [b.dtype for b in self.buckets]))

    def unpack_grads(self, bufs: Sequence[jax.Array]) -> Pytree:
        """Per-bucket flat buffers -> pytree, each leaf keeping its
        buffer's dtype (the inverse of ``pack_grads``; rare host-facing
        path — the hot loop never unpacks gradients)."""
        return jax.tree_util.tree_unflatten(
            self.treedef, self._unpack_leaves(bufs, dtypes=None))

    def unpack_model(self, bufs: Sequence[jax.Array]) -> Pytree:
        """Per-bucket flat buffers -> pytree in the MODEL dtypes."""
        return jax.tree_util.tree_unflatten(
            self.treedef,
            self._unpack_leaves(bufs,
                                [b.model_dtype for b in self.buckets]))

    # ---- optimizer-state packing ----------------------------------------
    # Generic rule covering every fused optimizer's state layout:
    #   * a state field whose leaves mirror the param shapes packs into
    #     per-bucket flat buffers (exp_avg, exp_avg_sq, momentum, sum);
    #   * a state field whose leaves are all scalars packs into one
    #     (num_segments,) vector per bucket (NovoGrad's per-tensor
    #     second moment), indexed by the bucket-local leaf ordinal;
    #   * a state field whose leaves are all the SAME small (H,) vector
    #     (and do NOT mirror the param shapes) stacks into one
    #     (num_segments, H) matrix per bucket, row per leaf — the fp8
    #     per-tensor amax-history slot.
    def _field_is_leaf_vectors(self, leaves) -> bool:
        """True for the row-stacked layout: every leaf a same-length
        1-D vector that is NOT this plan's own leaf shape (a params
        tree of uniform (H,) vectors keeps the flat pack — the two
        layouts would otherwise be write-ambiguous)."""
        shapes = {tuple(getattr(l, "shape", ())) for l in leaves}
        if len(shapes) != 1:
            return False
        (shape,) = shapes
        if len(shape) != 1:
            return False
        return any(s.shape != shape
                   for b in self.buckets for s in b.leaves)

    def pack_state_field(self, field: Pytree) -> List[jax.Array]:
        leaves = _leaf_arrays(field)
        if len(leaves) != self.n_leaves:
            raise ValueError("state field does not mirror the plan's tree")
        if all(getattr(l, "shape", ()) == () for l in leaves):
            return [jnp.stack([jnp.asarray(leaves[s.index], jnp.float32)
                               for s in b.leaves])
                    for b in self.buckets]
        if self._field_is_leaf_vectors(leaves):
            return [jnp.stack([jnp.asarray(leaves[s.index], jnp.float32)
                               for s in b.leaves])
                    for b in self.buckets]
        return self.pack(field)

    def unpack_state_field(self, bufs: Sequence[jax.Array]) -> Pytree:
        # Per-leaf-scalar layout iff every bucket's buffer is exactly
        # (num leaves,).  When that coincides with the flat layout
        # (every param leaf itself a scalar) the two agree elementwise,
        # so either unpack is correct.  State dtypes (f32 moments even
        # for bf16 work buffers) are preserved: no work-dtype cast here.
        # A 2-D (num leaves, H) buffer is the row-stacked per-leaf-
        # vector layout (fp8 amax history) — unambiguous: the flat
        # pack always yields 1-D buffers.
        if all(getattr(bufs[bi], "ndim", None) == 2
               and bufs[bi].shape[0] == len(b.leaves)
               for bi, b in enumerate(self.buckets)):
            leaves: List[Optional[jax.Array]] = [None] * self.n_leaves
            for bi, b in enumerate(self.buckets):
                for j, s in enumerate(b.leaves):
                    leaves[s.index] = bufs[bi][j]
            return jax.tree_util.tree_unflatten(self.treedef, leaves)
        scalar = all(tuple(bufs[bi].shape) == (len(b.leaves),)
                     for bi, b in enumerate(self.buckets))
        flat = all(bufs[bi].size == b.size
                   for bi, b in enumerate(self.buckets))
        if scalar and not flat:
            leaves = [None] * self.n_leaves
            for bi, b in enumerate(self.buckets):
                for j, s in enumerate(b.leaves):
                    leaves[s.index] = bufs[bi][j]
            return jax.tree_util.tree_unflatten(self.treedef, leaves)
        return jax.tree_util.tree_unflatten(
            self.treedef, self._unpack_leaves(bufs, dtypes=None))

    # ---- segment metadata ------------------------------------------------
    def segment_ids(self, bucket_index: int) -> jax.Array:
        """Sorted i32 element->bucket-local-leaf map for one bucket
        (computed once, cached; feeds the segmented LAMB/NovoGrad
        kernels)."""
        if self._seg_ids is None:
            self._seg_ids = {}
        ids = self._seg_ids.get(bucket_index)
        if ids is None:
            b = self.buckets[bucket_index]
            ids = jnp.asarray(
                np.repeat(np.arange(len(b.leaves), dtype=np.int32),
                          [s.size for s in b.leaves]))
            self._seg_ids[bucket_index] = ids
        return ids

    def num_segments(self, bucket_index: int) -> int:
        return len(self.buckets[bucket_index].leaves)

    def describe(self) -> List[dict]:
        """Human/bench-facing plan summary."""
        return [{"dtype": str(np.dtype(b.dtype)),
                 "model_dtype": str(np.dtype(b.model_dtype)),
                 "leaves": len(b.leaves), "elements": b.size}
                for b in self.buckets]

    # ---- layout (de)serialization ----------------------------------------
    def leaf_paths(self) -> List[str]:
        """``jax.tree_util.keystr`` path per leaf, in leaf-index order —
        the human-readable identity the checkpoint v2 header records so
        a restore onto a DIFFERENT tree fails with a named leaf, not a
        positional index."""
        dummy = jax.tree_util.tree_unflatten(
            self.treedef, list(range(self.n_leaves)))
        flat, _ = jax.tree_util.tree_flatten_with_path(dummy)
        paths: List[Optional[str]] = [None] * self.n_leaves
        for path, idx in flat:
            paths[idx] = jax.tree_util.keystr(path)
        return paths  # type: ignore[return-value]

    def layout(self) -> dict:
        """JSON-able static layout: leaf paths plus every bucket's
        dtypes, element count and per-leaf shape/offset table.  This is
        the checkpoint v2 header's ``plan`` record: enough to (a) slice
        a flat bucket buffer back into per-leaf arrays on the host with
        no device traffic, and (b) decide whether a restoring
        optimizer's own plan matches bit-for-bit (same doc ==> packed
        buffers can be adopted directly)."""
        return {
            "paths": self.leaf_paths(),
            "buckets": [
                {"dtype": np.dtype(b.dtype).name,
                 "model_dtype": np.dtype(b.model_dtype).name,
                 "size": b.size,
                 "leaves": [{"index": s.index, "shape": list(s.shape),
                             "offset": s.offset} for s in b.leaves]}
                for b in self.buckets],
        }


# ---- cached standalone plans ----------------------------------------------
# The fused optimizers own their plan; everything else on the flat
# gradient pipeline (FlatGradPipeline without an optimizer, the bucketed
# Reducer, packed clip_grad) needs one too — built ONCE per distinct
# tree layout, keyed on (treedef, leaf shape/dtype signature), so
# repeated calls (including from inside a jit trace) reuse the same
# static offsets instead of recomputing the layout.
_PLAN_CACHE: dict = {}
_PLAN_CACHE_MAX = 64


def _leaf_multi_device(l):
    """True/False for concrete arrays, None for tracers (sharding
    unknown at trace time) — part of the cache key so a plan built for
    single-device arrays is never reused for same-shaped multi-device
    ones (from_tree declines those) or vice versa."""
    try:
        return len(l.sharding.device_set) > 1
    except AttributeError:
        return None


def cached_plan(tree: Pytree, model: Optional[Pytree] = None,
                max_bucket_bytes: Optional[int] = None
                ) -> Optional[BucketPlan]:
    """Memoized ``BucketPlan.from_tree`` (grad-only pack entry point).

    Works on concrete arrays and tracers alike.  Returns None exactly
    when ``from_tree`` would (non-float or multi-device leaves); the
    key carries shapes, dtypes, device placement AND the chunking cap,
    so the memo never bypasses from_tree's multi-device guard and a
    chunked plan is never served where a monolithic one was asked."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sig = tuple((tuple(getattr(l, "shape", ())),
                 jnp.dtype(getattr(l, "dtype", jnp.float32)).name,
                 _leaf_multi_device(l))
                for l in leaves if hasattr(l, "dtype"))
    if len(sig) != len(leaves):
        return None
    if model is not None:
        sig += tuple(jnp.dtype(l.dtype).name
                     for l in jax.tree_util.tree_leaves(model))
    key = (treedef, sig, max_bucket_bytes)
    if key not in _PLAN_CACHE:
        if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
            _PLAN_CACHE.clear()
        _PLAN_CACHE[key] = BucketPlan.from_tree(
            tree, model, max_bucket_bytes=max_bucket_bytes)
    return _PLAN_CACHE[key]
