"""Parity package for apex.multi_tensor_apply (SURVEY.md §2.1).

Reference: apex/multi_tensor_apply/multi_tensor_apply.py — a dispatcher
that chunks many CUDA tensors into one kernel launch.  On TPU the analog
is: concatenate leaves (grouped by dtype) into one flat buffer, run one
Pallas grid over it, split back.  XLA's fusion makes the jnp fallback
competitive; the flat path guarantees a single kernel for huge trees.
"""

from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
    MultiTensorApply,
    multi_tensor_applier,
    flatten,
    unflatten,
    flatten_tensors,
    unflatten_tensors,
)
from apex_tpu.multi_tensor_apply.packer import Bucket, BucketPlan, LeafSpec

__all__ = [
    "MultiTensorApply",
    "multi_tensor_applier",
    "flatten",
    "unflatten",
    "flatten_tensors",
    "unflatten_tensors",
    "Bucket",
    "BucketPlan",
    "LeafSpec",
]
