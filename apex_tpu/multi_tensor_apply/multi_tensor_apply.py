"""Flat-buffer dispatch over tensor lists / pytrees.

Replaces both reference pieces:
  - apex/multi_tensor_apply/multi_tensor_apply.py (MultiTensorApply)
  - csrc/flatten_unflatten.cpp (apex_C.flatten / apex_C.unflatten)

JAX arrays are immutable, so unlike the reference (which mutates tensors
in place) every applier RETURNS the updated lists.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp


def flatten(tensors: Sequence[jax.Array]) -> jax.Array:
    """apex_C.flatten parity: concatenate raveled tensors (common dtype)."""
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten(flat: jax.Array, like: Sequence[jax.Array]) -> List[jax.Array]:
    """apex_C.unflatten parity: split a flat buffer back to shapes of `like`.

    Offsets are Python ints, so these are STATIC ``lax.slice``s — a
    dynamic_slice here would hide the fixed layout from XLA and block
    its static-offset folding for no benefit."""
    splits = []
    offset = 0
    for t in like:
        n = int(t.size)
        splits.append(
            jax.lax.slice(flat, (offset,), (offset + n,)).reshape(t.shape))
        offset += n
    return splits


# torch-parity aliases used by apex.parallel.distributed
flatten_tensors = flatten
unflatten_tensors = unflatten


def _group_by_dtype(tensors: Sequence[jax.Array]):
    groups = {}
    for idx, t in enumerate(tensors):
        groups.setdefault(jnp.dtype(t.dtype), []).append(idx)
    return groups


def multi_tensor_applier(op: Callable, noop_flag: Any,
                         tensor_lists: Sequence[Sequence[jax.Array]],
                         *args, **kwargs):
    """API-parity entry point.

    ``op`` is a flat-buffer kernel from apex_tpu.ops.multi_tensor taking
    positional flat buffers (one per tensor list) followed by kwargs.
    ``noop_flag`` is accepted for signature parity with the reference's
    overflow buffer and ignored (non-finite detection is returned
    functionally by the ops that support it).

    Returns whatever ``op`` returns, with flat buffers split back into the
    original tensor shapes.
    """
    del noop_flag
    lists = [list(tl) for tl in tensor_lists]
    n_lists = len(lists)
    if n_lists == 0 or len(lists[0]) == 0:
        return None
    # Group by dtype of the FIRST list (the reference dispatches on the
    # tuple of dtypes; in practice lists are dtype-homogeneous per group).
    groups = _group_by_dtype(lists[0])
    # result slots per original tensor position
    out_lists: List[List[Any]] = None
    extra = None
    for _, idxs in groups.items():
        flats = [flatten([lists[k][i] for i in idxs]) for k in range(n_lists)]
        result = op(*flats, *args, **kwargs)
        if not isinstance(result, tuple):
            result = (result,)
        # split array results that match the flat buffer size back out
        flat_size = flats[0].size
        split_results = []
        extras = []
        for r in result:
            if isinstance(r, jax.Array) and r.ndim == 1 and r.size == flat_size:
                split_results.append(unflatten(r, [lists[0][i] for i in idxs]))
            else:
                extras.append(r)
        if out_lists is None:
            out_lists = [[None] * len(lists[0]) for _ in split_results]
        for j, sr in enumerate(split_results):
            for slot, i in enumerate(idxs):
                out_lists[j][i] = sr[slot]
        if extras:
            extra = extras if extra is None else [
                _combine_extra(a, b) for a, b in zip(extra, extras)]
    outs = tuple(out_lists or ())
    if extra:
        return outs + tuple(extra)
    return outs


def _combine_extra(a, b):
    # non-finite flags combine by max; norms combine by rss
    if a.dtype == jnp.int32:
        return jnp.maximum(a, b)
    return jnp.sqrt(a * a + b * b)


class MultiTensorApply:
    """Reference-shaped callable (apex/multi_tensor_apply).

    The chunk_size ctor arg is kept for parity; Pallas tiling supersedes it.
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag_buffer, tensor_lists, *args, **kwargs):
        return multi_tensor_applier(op, noop_flag_buffer, tensor_lists,
                                    *args, **kwargs)
