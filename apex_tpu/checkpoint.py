"""Checkpoint / resume (SURVEY.md §5: the reference's story is
`{'model': ..., 'optimizer': ..., 'amp': amp.state_dict()}` torch.save
dicts — examples/imagenet/main_amp.py pattern; fused optimizers piggyback
on Optimizer.state_dict).

TPU-native: pytree checkpoints in a single packed file — a JSON header
(treedef, shapes, dtypes) + one contiguous payload assembled by the
native apex_C flatten (apex_tpu._native), so writing a checkpoint is one
sequential IO instead of thousands of small arrays.  Includes a norm
checksum computed by the native threaded l2norm to catch corruption at
load, and restores arrays to device with any requested sharding.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import _native

Pytree = Any

_MAGIC = "APEX_TPU_CKPT_V1"


class TemplateMismatchError(ValueError):
    """The checkpoint is intact but does not fit the caller's template
    (different tree/shape/dtype) — a caller bug, NOT file corruption.
    Recovery flows (resilience.restore_latest) must not treat it as a
    corrupt file to skip."""


def _resolve_dtype(name: str) -> np.dtype:
    """np.dtype('bfloat16') fails in stock numpy; resolve extended types
    through jnp (ml_dtypes)."""
    try:
        return np.dtype(name)
    except TypeError:
        return np.dtype(getattr(jnp, name))


def _flatten_with_paths(tree: Pytree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def save_checkpoint(path: str, tree: Pytree,
                    metadata: Optional[Dict] = None) -> None:
    """Write a pytree of arrays (+ JSON-able metadata) to one file."""
    leaves, treedef = _flatten_with_paths(tree)
    host = [np.asarray(l) for l in leaves]
    payload = _native.host_flatten(host)
    f32_leaves = [h.astype(np.float32).ravel() for h in host
                  if np.issubdtype(h.dtype, np.floating)]
    checksum = _native.host_l2norm(
        np.concatenate(f32_leaves) if f32_leaves
        else np.zeros((0,), np.float32))
    header = {
        "magic": _MAGIC,
        "treedef": str(treedef),
        "shapes": [list(h.shape) for h in host],
        "dtypes": [str(h.dtype) for h in host],   # 'bfloat16' prints fine
        "checksum": checksum,
        # crc over EVERY payload byte — integer leaves are invisible to
        # the float l2 checksum (ADVICE r1); zlib takes the buffer
        # protocol, no copy
        "payload_crc32": int(zlib.crc32(payload)),
        "metadata": metadata or {},
    }
    hbytes = json.dumps(header).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(len(hbytes).to_bytes(8, "little"))
        f.write(hbytes)
        payload.tofile(f)      # streams; tobytes() would copy GBs first
        f.flush()
        os.fsync(f.fileno())   # durable before the atomic publish
    os.replace(tmp, path)
    try:   # persist the rename itself (directory entry)
        dfd = os.open(os.path.dirname(os.path.abspath(path)),
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass   # some filesystems refuse directory fsync; best effort


def load_checkpoint(path: str, like: Pytree,
                    sharding=None) -> tuple:
    """Read back into the structure of `like`.  Returns (tree, metadata).

    `sharding`: optional NamedSharding (or pytree of them) applied on
    device_put — how a multi-host restore lands shards directly.
    """
    with open(path, "rb") as f:
        hlen = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(hlen).decode())
        # fromfile reads straight into one array (read()+frombuffer is
        # equivalent peak memory — frombuffer views the bytes — this
        # just skips the intermediate bytes object); requires a real
        # file, which every caller passes
        payload = np.fromfile(f, np.uint8)
    if header.get("magic") != _MAGIC:
        raise ValueError(f"{path} is not an apex_tpu checkpoint")
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if len(leaves) != len(header["shapes"]):
        raise TemplateMismatchError(
            f"checkpoint has {len(header['shapes'])} leaves, template "
            f"has {len(leaves)}")
    for i, (leaf, s, d) in enumerate(zip(leaves, header["shapes"],
                                         header["dtypes"])):
        if tuple(leaf.shape) != tuple(s) or \
                np.dtype(leaf.dtype) != _resolve_dtype(d):
            raise TemplateMismatchError(
                f"checkpoint does not match template at leaf {i}: "
                f"saved {tuple(s)}/{d}, template "
                f"{tuple(leaf.shape)}/{leaf.dtype}")
    protos = [np.empty(s, _resolve_dtype(d))
              for s, d in zip(header["shapes"], header["dtypes"])]
    # a truncated/oversized payload must fail BEFORE the native memcpy
    # reads out of bounds (ADVICE r1)
    expect = sum(int(np.prod(s)) * _resolve_dtype(d).itemsize
                 for s, d in zip(header["shapes"], header["dtypes"]))
    if payload.nbytes != expect:
        raise ValueError(
            f"checkpoint payload is {payload.nbytes} bytes, header "
            f"declares {expect} (truncated or corrupt file?)")
    if "payload_crc32" in header:
        crc = int(zlib.crc32(payload))
        if crc != header["payload_crc32"]:
            raise ValueError(
                f"checkpoint payload crc mismatch: {crc} != "
                f"{header['payload_crc32']} (corrupt file?)")
    host = _native.host_unflatten(payload, protos)
    f32_leaves = [h.astype(np.float32).ravel() for h in host
                  if np.issubdtype(h.dtype, np.floating)]
    checksum = _native.host_l2norm(
        np.concatenate(f32_leaves) if f32_leaves
        else np.zeros((0,), np.float32))
    if not np.isclose(checksum, header["checksum"], rtol=1e-6):
        raise ValueError(
            f"checkpoint checksum mismatch: {checksum} != "
            f"{header['checksum']} (corrupt file?)")
    if sharding is not None:
        if hasattr(sharding, "spec"):       # single sharding for all
            arrays = [jax.device_put(h, sharding) for h in host]
        else:
            slist = jax.tree_util.tree_leaves(sharding)
            arrays = [jax.device_put(h, s) for h, s in zip(host, slist)]
    else:
        arrays = [jnp.asarray(h) for h in host]
    return jax.tree_util.tree_unflatten(treedef, arrays), \
        header["metadata"]


def _training_state_tree(params, optimizer, amp_state, step, extra):
    """Assemble the {'model','optimizer','amp'} bundle (tree, meta).
    Runs on the CALLER thread so the snapshot is step-consistent even
    when the write is deferred to AsyncCheckpointer's worker."""
    tree = {"params": params}
    if extra is not None:
        tree["extra"] = extra
    meta: Dict[str, Any] = {"step": step}
    if optimizer is not None:
        sd = optimizer.state_dict()
        meta["opt_step"] = sd.pop("step", 0)
        meta["opt_hypers"] = {
            k: v for k, v in sd.pop("hypers", {}).items()
            if isinstance(v, (int, float, bool, str))}
        tree["opt"] = {k: v for k, v in sd.items() if v is not None}
    if amp_state is not None:
        meta["amp"] = amp_state
    return tree, meta


def save_training_state(path: str, params: Pytree, optimizer=None,
                        amp_state=None, step: int = 0,
                        extra: Optional[Pytree] = None) -> None:
    """The reference's {'model', 'optimizer', 'amp'} bundle in one call.

    optimizer: any apex_tpu optimizer facade (state_dict'ed); amp_state:
    amp.state_dict() or a scaler state_dict; extra: any additional array
    pytree (e.g. BN batch_stats)."""
    tree, meta = _training_state_tree(params, optimizer, amp_state,
                                      step, extra)
    save_checkpoint(path, tree, meta)


def load_training_state(path: str, params_like: Pytree, optimizer=None,
                        extra_like: Optional[Pytree] = None):
    """Inverse of save_training_state; restores the optimizer in place.
    Returns (params, amp_state, step) — or (params, amp_state, step,
    extra) when `extra_like` is given."""
    tree_like = {"params": params_like}
    if extra_like is not None:
        tree_like["extra"] = extra_like
    if optimizer is not None:
        sd = optimizer.state_dict()
        tree_like["opt"] = {k: v for k, v in sd.items()
                            if k not in ("step", "hypers") and v is not None}
    tree, meta = load_checkpoint(path, tree_like)
    if optimizer is not None:
        sd = dict(tree["opt"])
        sd["step"] = meta.get("opt_step", 0)
        sd["hypers"] = meta.get("opt_hypers", {})
        if "masters" not in sd:
            sd["masters"] = None
        optimizer.load_state_dict(sd)
        optimizer.params = tree["params"]
    out = (tree["params"], meta.get("amp"), meta.get("step", 0))
    if extra_like is not None:
        return out + (tree["extra"],)
    return out


class AsyncCheckpointer:
    """Non-blocking checkpoint writes on a single worker thread.

    ``save()``/``save_training_state()`` snapshot on the caller thread —
    tree containers and metadata are copied, and jax array leaves get an
    asynchronous DEVICE-SIDE copy (dispatch returns immediately), so the
    capture survives the caller's next step even when that step donates
    the originals (`donate_argnums` deletes donated buffers — a
    by-reference capture would race it).  The device→host transfer and
    the packed-file write happen on the worker.  Pass
    ``copy_leaves=False`` to skip the device copies (saves one transient
    params-sized HBM allocation) IF the training step does not donate
    the checkpointed buffers.  (Raw numpy leaves are by-reference either
    way: don't mutate them in place mid-save.)  At most one save is in
    flight — a new save first waits for the previous one (so checkpoints
    never interleave), and any worker exception is re-raised at the next
    call or at ``wait_until_finished()``.

    The reference blocks training for the full torch.save; here the step
    loop only ever waits when checkpoints are requested faster than the
    disk can take them.
    """

    def __init__(self, copy_leaves: bool = True):
        import concurrent.futures as cf
        self._pool = cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="apex_ckpt")
        self._inflight = None
        self._copy_leaves = copy_leaves

    def _join(self):
        if self._inflight is not None:
            fut, self._inflight = self._inflight, None
            fut.result()   # re-raise worker failures

    def _snapshot(self, tree, metadata):
        """Fresh containers + deep-copied metadata + (by default)
        device-side leaf copies, so caller-side mutation OR buffer
        donation between submit and the worker's serialization can't
        tear or delete the checkpoint's inputs."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if self._copy_leaves:
            leaves = [l.copy() if isinstance(l, jax.Array) else l
                      for l in leaves]
        import copy
        return (jax.tree_util.tree_unflatten(treedef, leaves),
                copy.deepcopy(metadata) if metadata is not None
                else None)

    def save(self, path: str, tree: Pytree,
             metadata: Optional[Dict] = None) -> None:
        self._join()
        tree, metadata = self._snapshot(tree, metadata)
        self._inflight = self._pool.submit(
            save_checkpoint, path, tree, metadata)

    def save_training_state(self, path: str, params: Pytree,
                            optimizer=None, amp_state=None,
                            step: int = 0,
                            extra: Optional[Pytree] = None) -> None:
        self._join()
        # snapshot the optimizer/amp state NOW (caller thread): the
        # facade rebinds attributes each step, so a worker-side
        # state_dict could mix two steps' arrays
        tree, meta = _training_state_tree(params, optimizer, amp_state,
                                          step, extra)
        tree, meta = self._snapshot(tree, meta)
        self._inflight = self._pool.submit(save_checkpoint, path, tree,
                                           meta)

    def wait_until_finished(self) -> None:
        """Block until the in-flight save (if any) is durable on disk."""
        self._join()

    def close(self) -> None:
        try:
            self.wait_until_finished()
        finally:   # never leak the worker, even when the save failed
            self._pool.shutdown(wait=True)

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
