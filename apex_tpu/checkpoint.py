"""Checkpoint / resume (SURVEY.md §5: the reference's story is
`{'model': ..., 'optimizer': ..., 'amp': amp.state_dict()}` torch.save
dicts — examples/imagenet/main_amp.py pattern; fused optimizers piggyback
on Optimizer.state_dict).

TPU-native: pytree checkpoints in a single packed file — a JSON header
(treedef, shapes, dtypes) + one contiguous payload assembled by the
native apex_C flatten (apex_tpu._native), so writing a checkpoint is one
sequential IO instead of thousands of small arrays.  Includes a norm
checksum computed by the native threaded l2norm to catch corruption at
load, and restores arrays to device with any requested sharding.

Two on-disk formats share the container (8-byte header length + JSON
header + payload):

- **v1** (``APEX_TPU_CKPT_V1``): per-leaf — the tree is flattened leaf
  by leaf and each save pays a per-leaf walk (``state_dict()`` lazily
  unpacks every bucket of a bucketed optimizer first).
- **v2** (``APEX_TPU_CKPT_V2``, bucket-native): when the optimizer runs
  bucketed, ``save_training_state`` snapshots the packed ``BucketPlan``
  buffers directly — one async device-side copy (the double-buffer; the
  next step's donation can never race the in-flight transfer) plus one
  contiguous device->host transfer per bucket, ZERO per-leaf unpack.
  The header records the plan layout (leaf paths/shapes/dtypes/offsets,
  ``BucketPlan.layout()``), so restore can (i) adopt the buffers
  directly onto a matching plan, (ii) reconstruct per-leaf trees on the
  host for ``fuse_buckets=False`` optimizers / plain templates, and
  (iii) reshard every leaf onto a different mesh via ``sharding=``.

All filesystem WRITES route through the :class:`CheckpointIO` seam so
``apex_tpu.resilience.faults`` can inject mid-write truncation, fsync
failures, slow disks and crash-before-publish deterministically.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import _native
from apex_tpu.telemetry import hostmetrics as _hostmetrics

Pytree = Any

_MAGIC = "APEX_TPU_CKPT_V1"
_MAGIC_V2 = "APEX_TPU_CKPT_V2"


# ---------------------------------------------------------------------------
# IO seam (fault injection point)
# ---------------------------------------------------------------------------
class CheckpointIO:
    """The filesystem operations a checkpoint write performs, as an
    overridable object: ``resilience.faults.FaultInjector`` subclasses
    this to inject torn writes, fsync errors, slow disks and
    crash-before-publish without touching the writers themselves.
    Reads are NOT hooked — corruption is injected by making the write
    leave bad bytes, the same way real failures do."""

    def open(self, path: str, mode: str = "wb"):
        return open(path, mode)

    def write_array(self, f, arr: np.ndarray) -> None:
        # streams; tobytes() would copy GBs first
        arr.tofile(f)

    def fsync(self, f) -> None:
        f.flush()
        os.fsync(f.fileno())   # durable before the atomic publish

    def replace(self, tmp: str, path: str) -> None:
        os.replace(tmp, path)

    def fsync_dir(self, path: str) -> None:
        try:   # persist the rename itself (directory entry)
            dfd = os.open(os.path.dirname(os.path.abspath(path)),
                          os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass   # some filesystems refuse directory fsync; best effort


_io = CheckpointIO()


def get_io() -> CheckpointIO:
    return _io


def set_io(io: Optional[CheckpointIO]) -> CheckpointIO:
    """Install an IO implementation (None restores the direct one);
    returns the previous one so callers can restore it."""
    global _io
    prev = _io
    # install/uninstall run on the main thread before a run arms its
    # workers (FaultInjector.install precedes run_elastic) or between
    # joined saves; the async writer only ever READS the reference,
    # which is a GIL-atomic load
    _io = io if io is not None else CheckpointIO()   # apexlint: disable=APX1001
    return prev


def _d2h(buf) -> np.ndarray:
    """ONE contiguous device->host transfer for one flat buffer.  The
    bucket-native writer routes every transfer through this seam so
    tests can count transfers structurally (acceptance: exactly one per
    bucket, no per-leaf traffic)."""
    return np.asarray(buf)


class TemplateMismatchError(ValueError):
    """The checkpoint is intact but does not fit the caller's template
    (different tree/shape/dtype) — a caller bug, NOT file corruption.
    Recovery flows (resilience.restore_latest) must not treat it as a
    corrupt file to skip."""


def _resolve_dtype(name: str) -> np.dtype:
    """np.dtype('bfloat16') fails in stock numpy; resolve extended types
    through jnp (ml_dtypes)."""
    try:
        return np.dtype(name)
    except TypeError:
        return np.dtype(getattr(jnp, name))


def _flatten_with_paths(tree: Pytree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def _publish(path: str, header: Dict, payload_bufs: Sequence[np.ndarray]
             ) -> None:
    """Write header + payload buffers to ``path + ".tmp"``, fsync, and
    atomically publish — every filesystem touch through the IO seam.
    Emits ``ckpt/save_ms`` / ``ckpt/bytes_written`` host counters."""
    t0 = time.perf_counter()
    hbytes = json.dumps(header).encode()
    tmp = path + ".tmp"
    io = _io
    f = io.open(tmp, "wb")
    try:
        f.write(len(hbytes).to_bytes(8, "little"))
        f.write(hbytes)
        for buf in payload_bufs:
            io.write_array(f, buf)
        io.fsync(f)
    finally:
        f.close()
    io.replace(tmp, path)
    io.fsync_dir(path)
    _hostmetrics.emit("ckpt/save_ms",
                      (time.perf_counter() - t0) * 1e3)
    _hostmetrics.emit("ckpt/bytes_written",
                      8 + len(hbytes)
                      + sum(int(b.nbytes) for b in payload_bufs))


def save_checkpoint(path: str, tree: Pytree,
                    metadata: Optional[Dict] = None) -> None:
    """Write a pytree of arrays (+ JSON-able metadata) to one file."""
    leaves, treedef = _flatten_with_paths(tree)
    host = [np.asarray(l) for l in leaves]
    payload = _native.host_flatten(host)
    f32_leaves = [h.astype(np.float32).ravel() for h in host
                  if np.issubdtype(h.dtype, np.floating)]
    checksum = _native.host_l2norm(
        np.concatenate(f32_leaves) if f32_leaves
        else np.zeros((0,), np.float32))
    header = {
        "magic": _MAGIC,
        "treedef": str(treedef),
        "shapes": [list(h.shape) for h in host],
        "dtypes": [str(h.dtype) for h in host],   # 'bfloat16' prints fine
        "checksum": checksum,
        # crc over EVERY payload byte — integer leaves are invisible to
        # the float l2 checksum (ADVICE r1); zlib takes the buffer
        # protocol, no copy
        "payload_crc32": int(zlib.crc32(payload)),
        "metadata": metadata or {},
    }
    _publish(path, header, [payload])


def load_checkpoint(path: str, like: Pytree, sharding=None,
                    header: Optional[Dict] = None) -> tuple:
    """Read back into the structure of `like`.  Returns (tree, metadata).

    `sharding`: optional NamedSharding (or pytree of them) applied on
    device_put — how a multi-host restore lands shards directly.
    `header`: the file's already-parsed JSON header (from
    `read_checkpoint_header`) — skips re-reading and re-parsing the
    per-leaf shapes/dtypes tables.
    """
    with open(path, "rb") as f:
        hlen = int.from_bytes(f.read(8), "little")
        if header is None:
            header = json.loads(f.read(hlen).decode())
        else:
            f.seek(hlen, os.SEEK_CUR)
        # fromfile reads straight into one array (read()+frombuffer is
        # equivalent peak memory — frombuffer views the bytes — this
        # just skips the intermediate bytes object); requires a real
        # file, which every caller passes
        payload = np.fromfile(f, np.uint8)
    if header.get("magic") != _MAGIC:
        raise ValueError(f"{path} is not an apex_tpu checkpoint")
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if len(leaves) != len(header["shapes"]):
        raise TemplateMismatchError(
            f"checkpoint has {len(header['shapes'])} leaves, template "
            f"has {len(leaves)}")
    for i, (leaf, s, d) in enumerate(zip(leaves, header["shapes"],
                                         header["dtypes"])):
        if tuple(leaf.shape) != tuple(s) or \
                np.dtype(leaf.dtype) != _resolve_dtype(d):
            raise TemplateMismatchError(
                f"checkpoint does not match template at leaf {i}: "
                f"saved {tuple(s)}/{d}, template "
                f"{tuple(leaf.shape)}/{leaf.dtype}")
    protos = [np.empty(s, _resolve_dtype(d))
              for s, d in zip(header["shapes"], header["dtypes"])]
    # a truncated/oversized payload must fail BEFORE the native memcpy
    # reads out of bounds (ADVICE r1)
    expect = sum(int(np.prod(s)) * _resolve_dtype(d).itemsize
                 for s, d in zip(header["shapes"], header["dtypes"]))
    if payload.nbytes != expect:
        raise ValueError(
            f"checkpoint payload is {payload.nbytes} bytes, header "
            f"declares {expect} (truncated or corrupt file?)")
    if "payload_crc32" in header:
        crc = int(zlib.crc32(payload))
        if crc != header["payload_crc32"]:
            raise ValueError(
                f"checkpoint payload crc mismatch: {crc} != "
                f"{header['payload_crc32']} (corrupt file?)")
    host = _native.host_unflatten(payload, protos)
    f32_leaves = [h.astype(np.float32).ravel() for h in host
                  if np.issubdtype(h.dtype, np.floating)]
    checksum = _native.host_l2norm(
        np.concatenate(f32_leaves) if f32_leaves
        else np.zeros((0,), np.float32))
    if not np.isclose(checksum, header["checksum"], rtol=1e-6):
        raise ValueError(
            f"checkpoint checksum mismatch: {checksum} != "
            f"{header['checksum']} (corrupt file?)")
    return jax.tree_util.tree_unflatten(treedef,
                                        _to_device(host, sharding)), \
        header["metadata"]


# ---------------------------------------------------------------------------
# Format v2: bucket-native packed checkpoints
# ---------------------------------------------------------------------------
def read_checkpoint_header(path: str) -> Dict:
    """The JSON header of either format (cheap: no payload read).
    Raises ValueError on anything that is not an apex_tpu checkpoint —
    including torn files, which is what a mid-write crash leaves."""
    with open(path, "rb") as f:
        head = f.read(8)
        if len(head) < 8:
            raise ValueError(
                f"{path} is not an apex_tpu checkpoint (truncated)")
        hlen = int.from_bytes(head, "little")
        if not 0 < hlen < (1 << 31):
            raise ValueError(f"{path} is not an apex_tpu checkpoint")
        raw = f.read(hlen)
    if len(raw) < hlen:
        raise ValueError(f"{path}: truncated checkpoint header")
    try:
        header = json.loads(raw.decode())
    except (UnicodeDecodeError, ValueError) as e:
        raise ValueError(f"{path}: unreadable checkpoint header: {e}")
    if not isinstance(header, dict):
        raise ValueError(f"{path} is not an apex_tpu checkpoint")
    return header


def _v2_metadata(snap: Dict, amp_state, step: int) -> Dict:
    return {"step": step, "opt_step": snap["step"],
            "opt_hypers": {k: v for k, v in snap["hypers"].items()
                           if isinstance(v, (int, float, bool, str))},
            "amp": amp_state}


def _packed_sections(snap: Dict, extra: Optional[Pytree]
                     ) -> Tuple[List[Dict], List[Any]]:
    """Section docs + the flat list of (device) buffers backing them,
    in payload order.  Bucketed sections carry per-bucket dtype/element
    tables; the optional ``extra`` pytree (e.g. BN batch_stats) rides
    as a per-leaf section — it is not bucket-packed state."""
    docs: List[Dict] = []
    bufs: List[Any] = []

    def add(name, blist):
        docs.append({"name": name,
                     "dtypes": [np.dtype(b.dtype).name for b in blist],
                     "elements": [int(b.size) for b in blist]})
        bufs.extend(blist)

    add("params", snap["param_bufs"])
    if snap["master_bufs"] is not None:
        add("masters", snap["master_bufs"])
    for k in sorted(snap["state"]):
        add("state:" + k, snap["state"][k])
    if extra is not None:
        flat, _ = jax.tree_util.tree_flatten_with_path(extra)
        # normalize python scalars NOW so the header dtype matches the
        # bytes the writer will emit (np.asarray(3.0) is float64 — a
        # float32 default would shift every later extra leaf on read)
        arrs = [l if hasattr(l, "dtype") else np.asarray(l)
                for _, l in flat]
        docs.append({
            "name": "extra",
            "paths": [jax.tree_util.keystr(p) for p, _ in flat],
            "shapes": [list(np.shape(a)) for a in arrs],
            "dtypes": [np.dtype(a.dtype).name for a in arrs]})
        bufs.extend(arrs)
    return docs, bufs


def _write_checkpoint_v2(path: str, plan_doc: Dict, metadata: Dict,
                         section_docs: List[Dict],
                         dev_bufs: List[Any]) -> None:
    """The v2 writer (runs on the AsyncCheckpointer worker for async
    saves): one ``_d2h`` per buffer — for a bucketed optimizer that is
    one contiguous transfer per bucket per field, never a per-leaf
    walk — then one sequential publish."""
    host = [np.ascontiguousarray(_d2h(b)) for b in dev_bufs]
    crc = 0
    for h in host:
        crc = zlib.crc32(h, crc)
    header = {
        "magic": _MAGIC_V2,
        "plan": plan_doc,
        "sections": section_docs,
        "metadata": metadata,
        "payload_bytes": int(sum(h.nbytes for h in host)),
        "payload_crc32": int(crc),
    }
    _publish(path, header, host)


def _packed_v2_args(optimizer, amp_state, step: int,
                    extra: Optional[Pytree]):
    """Assemble the v2 writer's inputs from a bucketed optimizer —
    ONE shared front half for the sync and async save paths, so the
    on-disk structure cannot drift between them."""
    snap = optimizer.packed_snapshot()
    docs, bufs = _packed_sections(snap, extra)
    return (snap["plan"].layout(), _v2_metadata(snap, amp_state, step),
            docs, bufs)


def save_training_state_packed(path: str, optimizer, amp_state=None,
                               step: int = 0,
                               extra: Optional[Pytree] = None) -> None:
    """Bucket-native (v2) training-state save: snapshot the packed
    buffers (one device-side copy per bucket, ``packed_snapshot``) and
    write them with one d2h per bucket.  Requires a bucketed optimizer
    — ``save_training_state(format="auto")`` routes here."""
    plan_doc, meta, docs, bufs = _packed_v2_args(optimizer, amp_state,
                                                 step, extra)
    _write_checkpoint_v2(path, plan_doc, meta, docs, bufs)


def _read_v2(path: str, header: Optional[Dict] = None
             ) -> Tuple[Dict, Dict[str, List[np.ndarray]]]:
    """Read + validate a v2 file; returns (header, {section name ->
    per-bucket (or per-leaf, for "extra") host arrays}).  ``header``:
    the file's already-parsed JSON header — skips re-reading and
    re-parsing it (the v2 plan table is per-leaf, so a large model's
    header is the expensive part after the payload)."""
    with open(path, "rb") as f:
        hlen = int.from_bytes(f.read(8), "little")
        if header is None:
            header = json.loads(f.read(hlen).decode())
        else:
            f.seek(hlen, os.SEEK_CUR)
        payload = np.fromfile(f, np.uint8)
    if header.get("magic") != _MAGIC_V2:
        raise ValueError(f"{path} is not a v2 apex_tpu checkpoint")
    if payload.nbytes != header["payload_bytes"]:
        raise ValueError(
            f"checkpoint payload is {payload.nbytes} bytes, header "
            f"declares {header['payload_bytes']} (truncated or corrupt "
            "file?)")
    crc = int(zlib.crc32(payload))
    if crc != header["payload_crc32"]:
        raise ValueError(
            f"checkpoint payload crc mismatch: {crc} != "
            f"{header['payload_crc32']} (corrupt file?)")
    sections: Dict[str, List[np.ndarray]] = {}
    off = 0
    for doc in header["sections"]:
        if doc["name"] == "extra":
            counts = [int(np.prod(s)) if s else 1 for s in doc["shapes"]]
        else:
            counts = [int(n) for n in doc["elements"]]
        sect = []
        for d, n in zip(doc["dtypes"], counts):
            dt = _resolve_dtype(d)
            nb = n * dt.itemsize
            if off + nb > payload.nbytes:
                raise ValueError(
                    f"checkpoint section {doc['name']} overruns the "
                    "payload (corrupt header?)")
            # copy into a fresh aligned buffer (a .view on an odd slice
            # offset would be unaligned for wide dtypes)
            arr = np.empty(n, dt)
            arr.view(np.uint8)[:] = payload[off:off + nb]
            off += nb
            sect.append(arr)
        sections[doc["name"]] = sect
    return header, sections


def _v2_leaves(plan_doc: Dict, bufs: Sequence[np.ndarray]
               ) -> List[np.ndarray]:
    """Host-side slice of per-bucket flat buffers into per-leaf arrays
    (leaf-index order) using the header's static offsets — the per-leaf
    fallback / reshard path.  Mirrors ``BucketPlan.unpack_state_field``
    's scalar-vector-vs-flat rule for optimizer-state sections."""
    bdocs = plan_doc["buckets"]
    n = len(plan_doc["paths"])
    leaves: List[Optional[np.ndarray]] = [None] * n
    scalar = all(b.size == len(d["leaves"])
                 for b, d in zip(bufs, bdocs))
    flat = all(b.size == int(d["size"]) for b, d in zip(bufs, bdocs))
    # row-stacked per-leaf vectors (the fp8 amax-history slot packs
    # (n_leaves, H) per bucket, stored flattened): every buffer a
    # whole multiple of its leaf count with ONE consistent row width
    widths = {b.size // len(d["leaves"])
              for b, d in zip(bufs, bdocs)
              if len(d["leaves"]) and b.size % len(d["leaves"]) == 0}
    stacked = (not scalar and not flat and len(widths) == 1
               and all(len(d["leaves"])
                       and b.size % len(d["leaves"]) == 0
                       for b, d in zip(bufs, bdocs)))
    for bi, d in enumerate(bdocs):
        buf = bufs[bi]
        if scalar and not flat:
            for j, ld in enumerate(d["leaves"]):
                leaves[ld["index"]] = buf[j]
        elif stacked:
            width = next(iter(widths))
            rows = buf.reshape(len(d["leaves"]), width)
            for j, ld in enumerate(d["leaves"]):
                leaves[ld["index"]] = rows[j]
        else:
            for ld in d["leaves"]:
                shape = tuple(ld["shape"])
                size = int(np.prod(shape)) if shape else 1
                o = int(ld["offset"])
                leaves[ld["index"]] = buf[o:o + size].reshape(shape)
    return leaves  # type: ignore[return-value]


# sentinel sharding leaf: "default placement" inside a per-leaf
# sharding pytree (None can't express it — tree_leaves drops None and
# the zip misaligns every later leaf)
_REPLICATED = object()


def _to_device(leaves: Sequence[np.ndarray], sharding) -> List[jax.Array]:
    """Host leaves -> device, honoring an optional sharding (single
    spec or a pytree of per-leaf shardings; a ``_REPLICATED`` leaf
    means default placement) — the reshard-onto-a-different-mesh
    surface."""
    if sharding is None:
        return [jnp.asarray(l) for l in leaves]
    if hasattr(sharding, "spec"):       # single sharding for all
        return [jax.device_put(l, sharding) for l in leaves]
    slist = jax.tree_util.tree_leaves(
        sharding, is_leaf=lambda x: x is _REPLICATED)
    if len(slist) != len(leaves):
        raise ValueError(
            f"sharding pytree has {len(slist)} leaves, restoring "
            f"{len(leaves)} arrays")
    return [jnp.asarray(l) if s is _REPLICATED else jax.device_put(l, s)
            for l, s in zip(leaves, slist)]


def _bundle_sharding(tree_like: dict, params_like, sharding) -> dict:
    """Expand a PARAMS-shaped pytree of shardings to the v1
    {extra, opt, params} bundle: params and every param-shaped
    optimizer slot get the matching per-param sharding, per-tensor
    SCALAR state (e.g. novograd second-moment norms) and the extra
    section replicate (``_REPLICATED``)."""
    p_leaves = jax.tree_util.tree_leaves(params_like)
    s_leaves = jax.tree_util.tree_leaves(sharding)
    if len(s_leaves) != len(p_leaves):
        raise ValueError(
            f"sharding pytree has {len(s_leaves)} leaves, params "
            f"template has {len(p_leaves)}")

    def aligned(subtree):
        # one params-shaped subtree (a state slot / masters): zip its
        # leaves against the per-param shardings.  None leaves (a
        # per-leaf optimizer keeps masters/state only for some params)
        # stay None — tree_flatten drops them from the bundle and from
        # this sharding pytree identically, so the zip stays aligned
        leaves, td = jax.tree_util.tree_flatten(
            subtree, is_leaf=lambda x: x is None)
        if len(leaves) != len(p_leaves):
            raise ValueError(
                f"optimizer state subtree has {len(leaves)} leaves, "
                f"params template has {len(p_leaves)} — cannot align "
                f"the sharding pytree")
        out = [None if l is None
               else _REPLICATED if (np.ndim(l) == 0 and np.ndim(p) != 0)
               else s
               for l, p, s in zip(leaves, p_leaves, s_leaves)]
        return jax.tree_util.tree_unflatten(td, out)

    sh: dict = {"params": aligned(tree_like["params"])}
    if "extra" in tree_like:
        sh["extra"] = jax.tree_util.tree_map(
            lambda _: _REPLICATED, tree_like["extra"])
    if "opt" in tree_like:
        sh["opt"] = {
            k: ({slot: aligned(sub) for slot, sub in v.items()}
                if k == "state" else aligned(v))
            for k, v in tree_like["opt"].items()}
    return sh


def _load_training_state_v2(path: str, params_like: Pytree,
                            optimizer=None,
                            extra_like: Optional[Pytree] = None,
                            sharding=None,
                            header: Optional[Dict] = None):
    """v2 restore.  Three flows, picked automatically:

    (i)  packed fast path — the optimizer's own ``BucketPlan.layout()``
         equals the header's: adopt the buffers directly (one h2d per
         bucket, zero per-leaf traffic);
    (ii) per-leaf fallback — no optimizer / ``fuse_buckets=False`` /
         layout mismatch by construction order: host-slice the buckets
         back into leaves and ``load_state_dict`` the per-leaf layout;
    (iii) reshard — ``sharding`` given: per-leaf flow with every leaf
         ``device_put`` onto the requested sharding(s).
    """
    header, sects = _read_v2(path, header=header)
    plan_doc = header["plan"]
    meta = header.get("metadata", {})
    paths = plan_doc["paths"]
    like_leaves, like_treedef = jax.tree_util.tree_flatten(params_like)
    if len(like_leaves) != len(paths):
        raise TemplateMismatchError(
            f"checkpoint has {len(paths)} leaves, template has "
            f"{len(like_leaves)}")
    shapes: List = [None] * len(paths)
    mdtypes: List = [None] * len(paths)
    for d in plan_doc["buckets"]:
        for ld in d["leaves"]:
            shapes[ld["index"]] = tuple(ld["shape"])
            mdtypes[ld["index"]] = d["model_dtype"]
    for i, leaf in enumerate(like_leaves):
        if tuple(leaf.shape) != shapes[i] or \
                np.dtype(leaf.dtype) != _resolve_dtype(mdtypes[i]):
            raise TemplateMismatchError(
                f"checkpoint does not match template at leaf "
                f"{paths[i]}: saved {shapes[i]}/{mdtypes[i]}, template "
                f"{tuple(leaf.shape)}/{leaf.dtype}")
    state_fields = sorted(n.split(":", 1)[1] for n in sects
                          if n.startswith("state:"))
    if optimizer is not None and sorted(optimizer.opt_state) != \
            state_fields:
        raise TemplateMismatchError(
            f"checkpoint optimizer state fields {state_fields} do not "
            f"match the restoring optimizer's "
            f"{sorted(optimizer.opt_state)} (different optimizer?)")
    has_masters = "masters" in sects
    plan = getattr(optimizer, "_plan", None) if optimizer is not None \
        else None
    if optimizer is not None:
        # _master_bufs first: the masters PROPERTY of a bucketed
        # optimizer would lazily unpack — per-leaf traffic on the path
        # built to avoid it
        opt_has_masters = (optimizer._master_bufs is not None
                           if plan is not None else
                           getattr(optimizer, "masters", None)
                           is not None)
        if has_masters != opt_has_masters:
            raise TemplateMismatchError(
                f"checkpoint {'has' if has_masters else 'lacks'} "
                f"master weights but the restoring optimizer "
                f"{'lacks' if has_masters else 'keeps'} them "
                "(different master_weights= setting?) — a partial "
                "load would train from freshly-initialized masters")
    if (plan is not None and sharding is None
            and plan.layout() == plan_doc
            and has_masters == (optimizer._master_bufs is not None)):
        optimizer.load_packed_snapshot(
            meta.get("opt_step", 0), meta.get("opt_hypers", {}),
            sects["params"], sects.get("masters"),
            {k: sects["state:" + k] for k in state_fields})
        params = optimizer.params   # ONE compiled unpack, lazy-cached
    else:
        params = jax.tree_util.tree_unflatten(
            like_treedef,
            _to_device(_v2_leaves(plan_doc, sects["params"]), sharding))
        if optimizer is not None:
            masters = None
            if has_masters:
                masters = jax.tree_util.tree_unflatten(
                    like_treedef,
                    _to_device(_v2_leaves(plan_doc, sects["masters"]),
                               sharding))
            def _put_state(sleaves):
                # per-tensor SCALAR state (e.g. novograd second-moment
                # norms) has no axes the param sharding could apply to
                # — replicate those; everything param-shaped reshards
                # alongside params/masters
                if sharding is None or all(
                        np.ndim(l) == 0 and np.ndim(t) != 0
                        for l, t in zip(sleaves, like_leaves)):
                    return [jnp.asarray(l) for l in sleaves]
                return _to_device(sleaves, sharding)

            state_tree = {
                k: jax.tree_util.tree_unflatten(
                    like_treedef,
                    _put_state(_v2_leaves(plan_doc, sects["state:" + k])))
                for k in state_fields}
            optimizer.load_state_dict({
                "step": meta.get("opt_step", 0),
                "hypers": meta.get("opt_hypers", {}),
                "state": state_tree, "masters": masters})
            optimizer.params = params
    out = (params, meta.get("amp"), meta.get("step", 0))
    if extra_like is not None:
        if "extra" not in sects:
            raise TemplateMismatchError(
                "extra_like given but the checkpoint has no extra "
                "section")
        doc = next(d for d in header["sections"]
                   if d["name"] == "extra")
        eleaves, etreedef = jax.tree_util.tree_flatten(extra_like)
        if len(eleaves) != len(sects["extra"]):
            raise TemplateMismatchError(
                f"checkpoint extra has {len(sects['extra'])} leaves, "
                f"template has {len(eleaves)}")
        restored = []
        for i, (el, arr) in enumerate(zip(eleaves, sects["extra"])):
            shape = tuple(doc["shapes"][i])
            # attribute reads, like every other template check here:
            # ShapeDtypeStruct templates are valid (run_elastic builds
            # them) and a device-array template must not pay a d2h
            # just to compare its dtype; python scalars fall back
            eshape = tuple(el.shape) if hasattr(el, "shape") \
                else tuple(np.shape(el))
            edtype = np.dtype(el.dtype) if hasattr(el, "dtype") \
                else np.asarray(el).dtype
            if eshape != shape or \
                    edtype != _resolve_dtype(doc["dtypes"][i]):
                raise TemplateMismatchError(
                    f"checkpoint extra does not match template at "
                    f"{doc['paths'][i]}")
            restored.append(arr.reshape(shape))
        # a params-shaped sharding pytree does not align with the
        # extra tree — only a single (uniform) sharding applies here
        esh = sharding if (sharding is None
                           or hasattr(sharding, "spec")) else None
        out = out + (jax.tree_util.tree_unflatten(
            etreedef, _to_device(restored, esh)),)
    return out


def _training_state_tree(params, optimizer, amp_state, step, extra):
    """Assemble the {'model','optimizer','amp'} bundle (tree, meta).
    Runs on the CALLER thread so the snapshot is step-consistent even
    when the write is deferred to AsyncCheckpointer's worker."""
    tree = {"params": params}
    if extra is not None:
        tree["extra"] = extra
    meta: Dict[str, Any] = {"step": step}
    if optimizer is not None:
        sd = optimizer.state_dict()
        meta["opt_step"] = sd.pop("step", 0)
        meta["opt_hypers"] = {
            k: v for k, v in sd.pop("hypers", {}).items()
            if isinstance(v, (int, float, bool, str))}
        tree["opt"] = {k: v for k, v in sd.items() if v is not None}
    if amp_state is not None:
        meta["amp"] = amp_state
    return tree, meta


def _wants_packed(optimizer, format: str, params=None) -> bool:
    if format == "v1":
        return False
    packed = (optimizer is not None
              and getattr(optimizer, "_plan", None) is not None)
    if format == "v2":
        if not packed:
            raise ValueError(
                "format='v2' requires a bucketed optimizer "
                "(fuse_buckets=True and a tree the packer accepts)")
        if params is not None:
            raise ValueError(
                "format='v2' snapshots the optimizer's own packed "
                "params; an explicit params pytree (e.g. EMA weights) "
                "cannot be written packed — pass params=None, or "
                "format='v1' to save the given tree")
        return True
    # auto: an explicit params pytree (EMA/averaged weights distinct
    # from the training weights) must be honored — per-leaf v1 is the
    # format that can represent it
    return packed and params is None


def save_training_state(path: str, params: Pytree = None, optimizer=None,
                        amp_state=None, step: int = 0,
                        extra: Optional[Pytree] = None,
                        format: str = "auto") -> None:
    """The reference's {'model', 'optimizer', 'amp'} bundle in one call.

    optimizer: any apex_tpu optimizer facade; amp_state:
    amp.state_dict() or a scaler state_dict; extra: any additional array
    pytree (e.g. BN batch_stats).

    ``format``: ``"auto"`` (default) writes the bucket-native v2 format
    when the optimizer runs bucketed AND ``params`` is None — the
    packed buffers snapshot directly with NO per-leaf unpack; an
    explicit ``params`` pytree (EMA weights etc.) is honored via the
    per-leaf v1 format instead.  ``"v1"`` forces per-leaf (interop
    with old readers); ``"v2"`` raises if the optimizer is not
    bucketed or ``params`` is given."""
    if _wants_packed(optimizer, format, params):
        save_training_state_packed(path, optimizer, amp_state=amp_state,
                                   step=step, extra=extra)
        return
    if params is None:
        params = optimizer.params if optimizer is not None else None
    if params is None:
        raise ValueError("params required for a v1 (per-leaf) save")
    tree, meta = _training_state_tree(params, optimizer, amp_state,
                                      step, extra)
    save_checkpoint(path, tree, meta)


def load_training_state(path: str, params_like: Pytree, optimizer=None,
                        extra_like: Optional[Pytree] = None,
                        sharding=None):
    """Inverse of save_training_state; restores the optimizer in place.
    Returns (params, amp_state, step) — or (params, amp_state, step,
    extra) when `extra_like` is given.

    Format-aware: v1 files walk per leaf; v2 (bucket-native) files
    adopt the packed buffers directly when the optimizer's plan matches
    (zero per-leaf traffic) and reconstruct per-leaf otherwise —
    including onto per-leaf (``fuse_buckets=False``) optimizers.
    ``sharding`` (a NamedSharding or pytree of them) reshards every
    restored leaf onto a different mesh at load."""
    header = read_checkpoint_header(path)
    if header.get("magic") == _MAGIC_V2:
        return _load_training_state_v2(path, params_like, optimizer,
                                       extra_like, sharding,
                                       header=header)
    tree_like = {"params": params_like}
    if extra_like is not None:
        tree_like["extra"] = extra_like
    if optimizer is not None:
        sd = optimizer.state_dict()
        tree_like["opt"] = {k: v for k, v in sd.items()
                            if k not in ("step", "hypers") and v is not None}
    # a single sharding applies to every bundle leaf; a PARAMS-shaped
    # pytree of shardings aligns with the params subtree only — so it
    # is expanded to a bundle-shaped pytree BEFORE any leaf lands:
    # param-shaped optimizer slots reshard alongside params (staging
    # the bundle on the default device first would OOM exactly the
    # model that only fits sharded), scalar state and extra replicate
    uniform = sharding is None or hasattr(sharding, "spec")
    if not uniform:
        sharding = _bundle_sharding(tree_like, params_like, sharding)
    tree, meta = load_checkpoint(path, tree_like, sharding=sharding,
                                 header=header)
    if optimizer is not None:
        sd = dict(tree["opt"])
        sd["step"] = meta.get("opt_step", 0)
        sd["hypers"] = meta.get("opt_hypers", {})
        if "masters" not in sd:
            sd["masters"] = None
        optimizer.load_state_dict(sd)
        optimizer.params = tree["params"]
    out = (tree["params"], meta.get("amp"), meta.get("step", 0))
    if extra_like is not None:
        return out + (tree["extra"],)
    return out


class AsyncCheckpointer:
    """Non-blocking checkpoint writes on a single worker thread.

    ``save()``/``save_training_state()`` snapshot on the caller thread —
    tree containers and metadata are copied, and jax array leaves get an
    asynchronous DEVICE-SIDE copy (dispatch returns immediately), so the
    capture survives the caller's next step even when that step donates
    the originals (`donate_argnums` deletes donated buffers — a
    by-reference capture would race it).  The device→host transfer and
    the packed-file write happen on the worker.  Pass
    ``copy_leaves=False`` to skip the device copies (saves one transient
    params-sized HBM allocation) IF the training step does not donate
    the checkpointed buffers.  (Raw numpy leaves are by-reference either
    way: don't mutate them in place mid-save.)  At most one save is in
    flight — a new save first waits for the previous one (so checkpoints
    never interleave), and any worker exception is re-raised at the next
    call or at ``wait_until_finished()``.

    The reference blocks training for the full torch.save; here the step
    loop only ever waits when checkpoints are requested faster than the
    disk can take them.
    """

    def __init__(self, copy_leaves: bool = True):
        import concurrent.futures as cf
        self._pool = cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="apex_ckpt")
        self._inflight = None          # (future, path, step) or None
        self._copy_leaves = copy_leaves

    def _join(self, backpressure: bool = False):
        if self._inflight is None:
            return
        (fut, path, step), self._inflight = self._inflight, None
        blocked = backpressure and not fut.done()
        t0 = time.perf_counter()
        try:
            fut.result()   # re-raise worker failures
        except Exception as e:
            # the failure surfaces at the NEXT call, whose traceback
            # points at the WRONG save — attach the failed write's
            # identity to the exception itself (ISSUE 6 satellite)
            note = (f"[async checkpoint write of {path!r} "
                    f"(step {step}) failed]")
            e.checkpoint_path = path
            e.checkpoint_step = step
            if hasattr(e, "add_note"):        # py3.11+
                e.add_note(note)
            else:
                e.args = e.args + (note,)
            raise
        finally:
            if blocked:
                # time save() spent waiting on the previous in-flight
                # write — the backpressure signal (ckpt/blocked_ms)
                _hostmetrics.emit(
                    "ckpt/blocked_ms",
                    (time.perf_counter() - t0) * 1e3)

    def _snapshot(self, tree, metadata):
        """Fresh containers + deep-copied metadata + (by default)
        device-side leaf copies, so caller-side mutation OR buffer
        donation between submit and the worker's serialization can't
        tear or delete the checkpoint's inputs."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if self._copy_leaves:
            leaves = [l.copy() if isinstance(l, jax.Array) else l
                      for l in leaves]
        import copy
        return (jax.tree_util.tree_unflatten(treedef, leaves),
                copy.deepcopy(metadata) if metadata is not None
                else None)

    def save(self, path: str, tree: Pytree,
             metadata: Optional[Dict] = None) -> None:
        self._join(backpressure=True)
        tree, metadata = self._snapshot(tree, metadata)
        self._inflight = (self._pool.submit(
            save_checkpoint, path, tree, metadata), path,
            (metadata or {}).get("step"))

    def save_training_state(self, path: str, params: Pytree = None,
                            optimizer=None, amp_state=None,
                            step: int = 0,
                            extra: Optional[Pytree] = None,
                            format: str = "auto") -> None:
        self._join(backpressure=True)
        if _wants_packed(optimizer, format, params):
            # bucket-native: packed_snapshot's device-side copies ARE
            # the double buffer (async dispatch, caller thread) — the
            # next step's donation of opt_state can never race the
            # worker's d2h.  Zero per-leaf work on either thread.
            import copy
            plan_doc, meta, docs, bufs = _packed_v2_args(
                optimizer, copy.deepcopy(amp_state), step,
                self._snapshot(extra, None)[0] if extra is not None
                else None)
            self._inflight = (self._pool.submit(
                _write_checkpoint_v2, path, plan_doc, meta, docs,
                bufs), path, step)
            return
        # snapshot the optimizer/amp state NOW (caller thread): the
        # facade rebinds attributes each step, so a worker-side
        # state_dict could mix two steps' arrays
        if params is None and optimizer is not None:
            params = optimizer.params
        if params is None:
            # a {'params': None} bundle would WRITE fine and then fail
            # every restore with a 0-leaf template mismatch
            raise ValueError("params required for a v1 (per-leaf) save")
        tree, meta = _training_state_tree(params, optimizer, amp_state,
                                          step, extra)
        tree, meta = self._snapshot(tree, meta)
        self._inflight = (self._pool.submit(save_checkpoint, path, tree,
                                            meta), path, step)

    def wait_until_finished(self) -> None:
        """Block until the in-flight save (if any) is durable on disk."""
        self._join()

    def close(self) -> None:
        try:
            self.wait_until_finished()
        finally:   # never leak the worker, even when the save failed
            self._pool.shutdown(wait=True)

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
