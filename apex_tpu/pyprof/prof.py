"""Kernel-parse half of the pyprof shim (reference: apex/pyprof/prof —
the toolkit that parsed captured profiles into per-kernel tables;
SURVEY.md §5 tracing).

The TPU capture side is `jax.profiler.trace` (driven through
`apex_tpu.telemetry.profiler.capture` — tools/profile_step.py and the
observatory share that one code path); THIS module turns the written
trace directory into the op-level table the reference's parsers
produced — top ops by total time, from the Chrome-format trace, with
no xprof/tensorboard dependency.  Typed event parsing itself lives in
`apex_tpu.telemetry.profiler.events`; this is the thin table layer.

    from apex_tpu.pyprof import prof
    rows = prof.summarize_device_ops("/tmp/apex_tpu_trace")
    rows = prof.summarize_ops("/tmp/apex_tpu_trace")   # + host ranges

    python -m apex_tpu.pyprof.prof /tmp/apex_tpu_trace
"""

from __future__ import annotations

import collections
import json
from typing import List

__all__ = ["summarize_device_ops", "summarize_ops", "main"]


def summarize_device_ops(outdir: str, top: int = 12):
    """Top device ops by total time.  Returns [[name, total_ms, pct],
    ...].

    Only the device op timeline is aggregated (the round-4 capture
    held ~1M host python events against 434 device ops — counting
    hosts would bury the signal this table exists to surface); on the
    CPU fallback the XLA executor threads stand in.  Parsing —
    including newest-capture-by-mtime selection — delegates to
    `apex_tpu.telemetry.profiler.events`."""
    from apex_tpu.telemetry.profiler.events import load_device_events
    agg = collections.Counter()
    for ev in load_device_events(outdir, prefer="json"):
        agg[ev.name] += ev.dur_us
    total = sum(agg.values())
    if not total:
        return []
    return [[name, round(dur / 1e3, 3), round(dur / total * 100, 1)]
            for name, dur in agg.most_common(top)]


def _host_ranges(doc: dict) -> collections.Counter:
    """Aggregate nvtx-style host ranges from a parsed Chrome doc:
    named spans on host-process threads (``PjitFunction(step)``,
    TraceMe annotations, user range names) — python-tracer stack
    frames (``$file:line fn``) and the XLA executor threads (the CPU
    fallback's "device" side, selected by
    `events.device_events_from_chrome`) are excluded."""
    ev = doc.get("traceEvents", [])
    agg: collections.Counter = collections.Counter()
    host_pids = {e.get("pid") for e in ev
                 if e.get("ph") == "M" and e.get("name") == "process_name"
                 and "/host:" in str(e.get("args", {}).get("name"))}
    skip_tids = {(e.get("pid"), e.get("tid")) for e in ev
                 if e.get("ph") == "M" and e.get("name") == "thread_name"
                 and str(e.get("args", {}).get("name"))
                 .startswith("tf_XLA")}
    for e in ev:
        name = str(e.get("name", ""))
        if (e.get("ph") != "X" or e.get("pid") not in host_pids
                or (e.get("pid"), e.get("tid")) in skip_tids
                or name.startswith(("$", "ThreadpoolListener"))
                or not e.get("dur")):
            continue
        agg[name] += float(e["dur"])
    return agg


def summarize_ops(outdir: str, top: int = 12) -> List[list]:
    """Device ops MERGED with nvtx host ranges when the capture holds
    both: [[name, where, total_ms, pct], ...], ``where`` is
    ``"device"`` or ``"host"``.  Shares (``pct``) are computed within
    each side — device and host timelines overlap in wall time, so a
    cross-side percentage would be meaningless.  A device-only trace
    yields exactly the `summarize_device_ops` rows plus the column.
    The (multi-MB on real captures) trace file is parsed ONCE; both
    views derive from the same doc."""
    from apex_tpu.telemetry.profiler.events import (
        device_events_from_chrome, find_trace_files, read_chrome_doc)
    path = find_trace_files(outdir).get("json")
    if path is None:
        return []
    try:
        doc = read_chrome_doc(path)
    except Exception:
        return []
    agg: collections.Counter = collections.Counter()
    for d in device_events_from_chrome(doc):
        agg[d.name] += d.dur_us
    dev_total = sum(agg.values())
    rows = [[name, "device", round(dur / 1e3, 3),
             round(dur / dev_total * 100, 1)]
            for name, dur in agg.most_common(top)] if dev_total else []
    host = _host_ranges(doc)
    host_total = sum(host.values())
    if host_total:
        rows += [[name, "host", round(dur / 1e3, 3),
                  round(dur / host_total * 100, 1)]
                 for name, dur in host.most_common(top)]
    return rows


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="op-level table from a jax.profiler trace dir")
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=12)
    ap.add_argument("--device-only", action="store_true",
                    help="suppress the host-range rows even when the "
                         "capture holds them")
    ap.add_argument("--json", action="store_true",
                    help="emit the table as JSON rows (for telemetry "
                         "reports / CI embedding)")
    args = ap.parse_args(argv)
    rows = ([[n, "device", ms, pct] for n, ms, pct in
             summarize_device_ops(args.trace_dir, top=args.top)]
            if args.device_only
            else summarize_ops(args.trace_dir, top=args.top))
    # exit-code contract (both output modes): no DEVICE rows is a
    # failed summarize (host-only trace / wrong dir) — host ranges
    # alone cannot stand in for the op breakdown
    ok = any(r[1] == "device" for r in rows)
    if args.json:
        print(json.dumps([{"op": n, "where": where, "total_ms": ms,
                           "pct": pct}
                          for n, where, ms, pct in rows]))
        return 0 if ok else 1
    if not ok:
        print("no device op events found (host-only trace, or wrong "
              "directory)")
        return 1
    w = max(len(r[0]) for r in rows)
    for name, where, ms, pct in rows:
        print(f"{name:<{w}}  {where:<6}  {ms:>10.3f} ms  {pct:>5.1f}%")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
