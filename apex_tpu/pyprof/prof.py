"""Kernel-parse half of the pyprof shim (reference: apex/pyprof/prof —
the toolkit that parsed captured profiles into per-kernel tables;
SURVEY.md §5 tracing).

The TPU capture side is `jax.profiler.trace` (driven by
tools/profile_step.py or `apex_tpu.pyprof.profile`); THIS module turns
the written trace directory into the op-level table the reference's
parsers produced — top device ops by total time, from the
Chrome-format trace, with no xprof/tensorboard dependency.

    from apex_tpu.pyprof import prof
    rows = prof.summarize_device_ops("/tmp/apex_tpu_trace")

    python -m apex_tpu.pyprof.prof /tmp/apex_tpu_trace
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os

__all__ = ["summarize_device_ops", "main"]


def summarize_device_ops(outdir: str, top: int = 12):
    """Top device ops by total time from the Chrome-format trace the
    profiler writes (device thread named "XLA Ops" under a /device:*
    process).  Returns [[name, total_ms, pct], ...].

    Only the device op thread is aggregated: the round-4 capture held
    ~1M host python events against 434 device ops — counting hosts
    would bury the signal this table exists to surface."""
    paths = glob.glob(os.path.join(
        outdir, "plugins", "profile", "*", "*.trace.json.gz"))
    if not paths:
        return []
    # NEWEST capture by mtime: profiler run dirs are wall-clock named,
    # but the format has changed across versions and hosts ("2026_01_02"
    # vs "localhost_2026...") — lexicographic order would then pick an
    # arbitrary old capture, silently summarizing a stale run
    with gzip.open(max(paths, key=os.path.getmtime)) as f:
        d = json.load(f)
    ev = d.get("traceEvents", [])
    device_pids = {e.get("pid") for e in ev
                   if e.get("ph") == "M"
                   and e.get("name") == "process_name"
                   and "/device:" in str(e.get("args", {}).get("name"))}
    op_tids = {(e.get("pid"), e.get("tid")) for e in ev
               if e.get("ph") == "M" and e.get("name") == "thread_name"
               and e.get("pid") in device_pids
               and e.get("args", {}).get("name") == "XLA Ops"}
    agg = collections.Counter()
    for e in ev:
        if (e.get("ph") == "X"
                and (e.get("pid"), e.get("tid")) in op_tids):
            agg[e["name"]] += e.get("dur", 0)
    total = sum(agg.values())
    if not total:
        return []
    return [[name, round(dur / 1e3, 3), round(dur / total * 100, 1)]
            for name, dur in agg.most_common(top)]


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="op-level table from a jax.profiler trace dir")
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=12)
    ap.add_argument("--json", action="store_true",
                    help="emit the table as JSON rows (for telemetry "
                         "reports / CI embedding)")
    args = ap.parse_args(argv)
    rows = summarize_device_ops(args.trace_dir, top=args.top)
    if args.json:
        # same exit-code contract as the text path: an empty table is
        # a failed summarize (host-only trace / wrong dir), but the
        # output stays machine-parseable either way
        print(json.dumps([{"op": n, "total_ms": ms, "pct": pct}
                          for n, ms, pct in rows]))
        return 0 if rows else 1
    if not rows:
        print("no device op events found (host-only trace, or wrong "
              "directory)")
        return 1
    w = max(len(r[0]) for r in rows)
    for name, ms, pct in rows:
        print(f"{name:<{w}}  {ms:>10.3f} ms  {pct:>5.1f}%")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
