"""nvtx-shaped annotation API over jax.named_scope (reference:
apex/pyprof/nvtx/nvmarker.py).

range_push/range_pop manage a stack of named_scope context managers;
`range` is the decorator/context form; `profile` wraps
jax.profiler.trace for XProf capture.  Scopes show up in TPU traces the
way nvtx ranges show up in nsight.
"""

from __future__ import annotations

import contextlib
import functools
from typing import List

import jax

_stack: List = []


def range_push(msg: str) -> int:
    cm = jax.named_scope(msg)
    cm.__enter__()
    _stack.append(cm)
    return len(_stack)


def range_pop() -> int:
    if not _stack:
        return 0
    cm = _stack.pop()
    cm.__exit__(None, None, None)
    return len(_stack)


@contextlib.contextmanager
def range(msg: str):
    with jax.named_scope(msg):
        yield


def annotate(msg: str = None):
    """Decorator: wrap a function in a named scope (nvmarker's wrapped
    torch-function behavior, opt-in per function here)."""
    def deco(fn):
        name = msg or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with jax.named_scope(name):
                return fn(*a, **kw)
        return wrapper
    return deco


@contextlib.contextmanager
def profile(logdir: str):
    """Capture an XProf trace of the enclosed region (TensorBoard-viewable
    — the DLProf story, natively)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
