"""nvtx-shaped annotation API over jax.named_scope (reference:
apex/pyprof/nvtx/nvmarker.py).

range_push/range_pop manage a stack of named_scope context managers;
`range` is the decorator/context form; `profile` wraps
jax.profiler.trace for XProf capture.  Scopes show up in TPU traces the
way nvtx ranges show up in nsight.

The push/pop stack is THREAD-LOCAL: a prefetch thread annotating its
own work must never pop a scope the main thread pushed (the reference
nvtx API is per-thread for the same reason).  ``range_pop`` is also
best-effort on teardown — a scope body that raised can leave
``jax.named_scope``'s own context in a state where ``__exit__``
raises, and an unwinding caller (``telemetry.span``'s finally, an
except-branch cleanup) must still get its stack balanced rather than
a second exception.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import List

import jax

_tls = threading.local()


def _stack() -> List:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def range_push(msg: str) -> int:
    cm = jax.named_scope(msg)
    cm.__enter__()
    stack = _stack()
    stack.append(cm)
    return len(stack)


def range_pop() -> int:
    stack = _stack()
    if not stack:
        return 0
    cm = stack.pop()
    try:
        cm.__exit__(None, None, None)
    except Exception:
        # best-effort unwind: the scope bookkeeping may already be
        # torn (a raising scope body, interpreter shutdown); the
        # caller's stack must still balance
        pass
    return len(stack)


@contextlib.contextmanager
def range(msg: str):
    with jax.named_scope(msg):
        yield


def annotate(msg: str = None):
    """Decorator: wrap a function in a named scope (nvmarker's wrapped
    torch-function behavior, opt-in per function here)."""
    def deco(fn):
        name = msg or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with jax.named_scope(name):
                return fn(*a, **kw)
        return wrapper
    return deco


@contextlib.contextmanager
def profile(logdir: str):
    """Capture an XProf trace of the enclosed region (TensorBoard-viewable
    — the DLProf story, natively)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
