"""apex.pyprof parity shim (reference: historical apex/pyprof — BOTH
halves: the nvtx annotation toolkit wrapping torch functions with
torch.cuda.nvtx.range_push/pop, and the pyprof/prof parsers that
turned captured profiles into per-kernel tables; SURVEY.md §5
tracing).

TPU equivalents: `jax.named_scope` annotations + `jax.profiler` trace
capture (the nvtx half, `apex_tpu.pyprof.nvtx`), and the trace
distiller that parses the written profile into a top-device-ops table
(the prof half, `apex_tpu.pyprof.prof`).

Run-time training telemetry (metric rings, span timing, retrace
counters) is the sibling layer `apex_tpu.telemetry`:
``telemetry.span(name)`` nests on nvtx's (thread-local) range stack,
so telemetry spans land in XProf traces exactly like `annotate`d
functions do.
"""

from apex_tpu.pyprof import nvtx, prof  # noqa: F401
from apex_tpu.pyprof.nvtx import annotate, profile  # noqa: F401

_enabled = False


def init():
    """Reference parity: pyprof.init() enabled global annotation.  Here
    named scopes are always legal; init just flips the marker flag."""
    global _enabled
    _enabled = True


def enabled() -> bool:
    return _enabled


__all__ = ["init", "enabled", "nvtx", "prof", "annotate", "profile"]
