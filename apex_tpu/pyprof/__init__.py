"""apex.pyprof parity shim (reference: historical apex/pyprof — nvtx
annotation toolkit wrapping torch functions with
torch.cuda.nvtx.range_push/pop, SURVEY.md §5 tracing).

TPU equivalent: `jax.named_scope` annotations (visible in XProf/
TensorBoard traces) and `jax.profiler` trace capture — strictly better
tooling for free.  The nvtx push/pop surface is preserved so reference
code annotating hot regions ports unchanged.
"""

from apex_tpu.pyprof import nvtx  # noqa: F401

_enabled = False


def init():
    """Reference parity: pyprof.init() enabled global annotation.  Here
    named scopes are always legal; init just flips the marker flag."""
    global _enabled
    _enabled = True


def enabled() -> bool:
    return _enabled


__all__ = ["init", "enabled", "nvtx"]
