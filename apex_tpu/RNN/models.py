"""apex.RNN parity — fused recurrent cells (reference: apex/RNN/*.py:
LSTM, GRU, mLSTM factories over fused pointwise cells; deprecated
upstream but part of the surface, SURVEY.md §2.1).

TPU-native structure: the input-to-hidden projection for ALL timesteps is
ONE batched (T*B, 4H) GEMM on the MXU before the loop (the reference
fuses per-step GEMMs instead — on TPU hoisting is strictly better); only
the hidden-to-hidden matmul and the pointwise gate math live inside a
`lax.scan`, which XLA compiles to a single fused step — the same effect
as the reference's fused pointwise CUDA cells, minus the launches.

Layout: (T, B, input_size) seq-first, matching the reference.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _dense(feats, name, bias=True):
    return nn.Dense(feats, use_bias=bias, name=name)


class _StackedRNNBase(nn.Module):
    """Shared stacked-layer scaffolding."""

    def h2h_params(self, layer, n_gates):
        h = self.hidden_size
        wh = self.param(f"l{layer}_h2h_kernel",
                        nn.initializers.lecun_normal(), (h, n_gates * h))
        bh = (self.param(f"l{layer}_h2h_bias", nn.initializers.zeros,
                         (n_gates * h,)) if self.bias else None)
        return wh, bh

    def inter_layer_dropout(self, outs, layer, is_training):
        """Reference parity: dropout between stacked layers, not after
        the last."""
        if self.dropout > 0.0 and layer < self.num_layers - 1:
            outs = nn.Dropout(self.dropout)(
                outs, deterministic=not is_training)
        return outs


def _lstm_gates(g, c):
    i, f, gg, o = jnp.split(g, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gg)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


class LSTM(_StackedRNNBase):
    """Multi-layer LSTM, reference-factory shape:
    LSTM(input_size, hidden_size, num_layers, bias, dropout).
    Gate order i, f, g, o."""

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, hx: Optional[tuple] = None,
                 is_training: bool = False):
        t, b, _ = x.shape
        outs = x
        finals = []
        for layer in range(self.num_layers):
            gi = _dense(4 * self.hidden_size, f"l{layer}_i2h",
                        self.bias)(outs)                    # (T, B, 4H)
            wh, bh = self.h2h_params(layer, 4)
            if hx is None:
                h0 = jnp.zeros((b, self.hidden_size), x.dtype)
                carry = (h0, h0)
            else:
                carry = (hx[0][layer], hx[1][layer])

            def step(carry, g_t, wh=wh, bh=bh):
                h, c = carry
                g = g_t + h @ wh + (bh if bh is not None else 0.0)
                h, c = _lstm_gates(g, c)
                return (h, c), h

            carry, outs = jax.lax.scan(step, carry, gi)
            outs = self.inter_layer_dropout(outs, layer, is_training)
            finals.append(carry)
        h_n = jnp.stack([f[0] for f in finals])
        c_n = jnp.stack([f[1] for f in finals])
        return outs, (h_n, c_n)


class GRU(_StackedRNNBase):
    """Gate order r, z, n (torch/reference convention: the candidate's
    hidden projection is gated by r BEFORE the bias-add of hn)."""

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, hx: Optional[jnp.ndarray] = None,
                 is_training: bool = False):
        t, b, _ = x.shape
        outs = x
        finals = []
        for layer in range(self.num_layers):
            gi = _dense(3 * self.hidden_size, f"l{layer}_i2h",
                        self.bias)(outs)
            wh, bh = self.h2h_params(layer, 3)
            carry = (jnp.zeros((b, self.hidden_size), x.dtype)
                     if hx is None else hx[layer])

            def step(h, g_t, wh=wh, bh=bh):
                gh = h @ wh + (bh if bh is not None else 0.0)
                ir, iz, in_ = jnp.split(g_t, 3, axis=-1)
                hr, hz, hn = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                n = jnp.tanh(in_ + r * hn)
                h = (1.0 - z) * n + z * h
                return h, h

            carry, outs = jax.lax.scan(step, carry, gi)
            outs = self.inter_layer_dropout(outs, layer, is_training)
            finals.append(carry)
        return outs, jnp.stack(finals)


class mLSTM(_StackedRNNBase):
    """Multiplicative LSTM (reference apex/RNN/models.py::mLSTM): the
    hidden state is modulated by m = (W_mx x) * (W_mh h) and the
    hidden-to-hidden gates read m instead of h."""

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, hx: Optional[tuple] = None,
                 is_training: bool = False):
        t, b, _ = x.shape
        outs = x
        finals = []
        for layer in range(self.num_layers):
            gi = _dense(4 * self.hidden_size, f"l{layer}_i2h",
                        self.bias)(outs)
            mx = _dense(self.hidden_size, f"l{layer}_mx", False)(outs)
            w_mh = self.param(f"l{layer}_mh_kernel",
                              nn.initializers.lecun_normal(),
                              (self.hidden_size, self.hidden_size))
            wh, bh = self.h2h_params(layer, 4)
            if hx is None:
                h0 = jnp.zeros((b, self.hidden_size), x.dtype)
                carry = (h0, h0)
            else:
                carry = (hx[0][layer], hx[1][layer])

            def step(carry, inp, w_mh=w_mh, wh=wh, bh=bh):
                h, c = carry
                g_t, mx_t = inp
                m = mx_t * (h @ w_mh)
                g = g_t + m @ wh + (bh if bh is not None else 0.0)
                h, c = _lstm_gates(g, c)
                return (h, c), h

            carry, outs = jax.lax.scan(step, carry, (gi, mx))
            outs = self.inter_layer_dropout(outs, layer, is_training)
            finals.append(carry)
        h_n = jnp.stack([f[0] for f in finals])
        c_n = jnp.stack([f[1] for f in finals])
        return outs, (h_n, c_n)


class RNNCell(nn.Module):
    """Plain tanh/ReLU cell (reference RNNCell parity)."""

    input_size: int
    hidden_size: int
    nonlinearity: str = "tanh"
    bias: bool = True

    @nn.compact
    def __call__(self, x, h):
        act = jnp.tanh if self.nonlinearity == "tanh" else jax.nn.relu
        return act(_dense(self.hidden_size, "i2h", self.bias)(x)
                   + _dense(self.hidden_size, "h2h", self.bias)(h))
