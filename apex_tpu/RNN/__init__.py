from apex_tpu.RNN.models import GRU, LSTM, mLSTM, RNNCell  # noqa: F401

__all__ = ["LSTM", "GRU", "mLSTM", "RNNCell"]
