"""apex_tpu.optimizers — fused optimizers (reference: apex/optimizers).

Each class keeps the reference's constructor surface and `step` idiom but
is a thin stateful facade over a pure jitted pytree update
(see _base.FusedOptimizerBase).  For fully-functional training loops, use
``opt.functional_step`` inside your own jit, or the per-leaf math in
apex_tpu.optimizers._functional.
"""

from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.optimizers.fused_lamb import FusedLAMB, FusedMixedPrecisionLamb
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad
from apex_tpu.optimizers import _functional as functional

__all__ = [
    "FusedAdam", "FusedSGD", "FusedLAMB", "FusedMixedPrecisionLamb",
    "FusedNovoGrad", "FusedAdagrad", "functional",
]
