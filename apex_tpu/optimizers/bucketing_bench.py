"""Per-leaf vs bucketed optimizer-step microbench.

The point of the bucketed flat path is amortizing per-leaf dispatch: a
ResNet-50/BERT-sized pytree is hundreds of small XLA ops per step on
the per-leaf path versus one flat Pallas kernel per dtype bucket.  This
module times both paths over the SAME many-leaf pytree with benchlib's
amortized on-device loop (one dispatch runs many steps serially, so a
tunneled session measures the program, not the relay).

``bench_amp_pipeline`` extends the comparison to the FULL amp gradient
side of a train step (unscale + finite check + global-norm clip +
optimizer update): per-leaf amp ops vs the flat pipeline's pack-once /
fused-kernel-per-bucket chain (amp/flat_pipeline.py).

Shared by bench.py (TPU extras), tools/kernel_bench.py (JSON row) and
the tier-1 smoke test (tiny shapes, CPU: proves the harness, not
performance).
"""

from __future__ import annotations


def many_leaf_params(jax, jnp, layers: int = 48, hidden: int = 256):
    """A transformer-ish pytree: per layer one square matrix plus three
    small vectors — the shape mix (few big, many tiny leaves) where
    per-leaf stepping drowns in dispatch."""
    keys = jax.random.split(jax.random.key(0), layers)
    return {
        f"layer{i:03d}": {
            "w": jax.random.normal(keys[i], (hidden, hidden), jnp.float32),
            "b": jnp.zeros((hidden,), jnp.float32),
            "scale": jnp.ones((hidden,), jnp.float32),
            "shift": jnp.zeros((hidden,), jnp.float32),
        }
        for i in range(layers)
    }


def many_leaf_loss(jnp):
    """The loss over a :func:`many_leaf_params` tree (tanh stack with
    scale/shift), shared so every consumer measures the SAME model:
    bench_grad_accum's train legs ground the perf-budget row
    (grad_accum_n8_speedup) that tools/autotune.py restamps, and the
    autotuner's pipeline-chunk sweep must not drift onto a different
    toy network."""
    def loss_fn(p, x):
        h = x
        for k in sorted(p):
            h = jnp.tanh(h @ p[k]["w"] + p[k]["b"]) \
                * p[k]["scale"] + p[k]["shift"]
        return jnp.mean(h ** 2)
    return loss_fn


def bench_optimizer_bucketing(layers: int = 48, hidden: int = 256,
                              iters: int = 10, reps: int = 3,
                              optimizer: str = "adam"):
    """Times one optimizer step, per-leaf vs bucketed, on a many-leaf
    pytree.  Returns a dict of ms timings plus the speedup and the
    bucket plan summary."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.benchlib import timeit
    from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedSGD

    cls = {"adam": FusedAdam, "sgd": FusedSGD, "lamb": FusedLAMB}[optimizer]
    params = many_leaf_params(jax, jnp, layers, hidden)
    grads = jax.tree_util.tree_map(lambda p: p * 1e-3 + 1e-4, params)

    out = {
        "optim": optimizer,
        "optim_leaves": len(jax.tree_util.tree_leaves(params)),
        "optim_elements": sum(int(l.size) for l in
                              jax.tree_util.tree_leaves(params)),
    }
    for fuse, label in ((False, "perleaf"), (True, "bucketed")):
        opt = cls(params, lr=1e-3, fuse_buckets=fuse)
        if fuse:
            out["optim_buckets"] = opt._plan.describe()
            args = (opt._param_bufs, None, opt.opt_state)
        else:
            args = (opt.params, None, opt.opt_state)
        hypers = {k: jnp.asarray(v, jnp.float32)
                  for k, v in opt.hypers.items()
                  if isinstance(v, float)}
        # the pure step body (what a train loop embeds); jitted fresh ON
        # PURPOSE: the loop has exactly two iterations (per-leaf vs
        # bucketed are different programs), not a hot path
        # apexlint: disable-next=APX302
        step_fn = jax.jit(opt._full_step_impl)
        ms = timeit(step_fn, *args, grads, jnp.int32(2),
                    jnp.float32(1.0), hypers, iters=iters, reps=reps)
        out[f"optim_step_{label}_ms"] = round(ms, 3)
    if out["optim_step_bucketed_ms"]:
        out["optim_bucketing_speedup"] = round(
            out["optim_step_perleaf_ms"] / out["optim_step_bucketed_ms"], 2)
    return out


def bench_amp_pipeline(layers: int = 48, hidden: int = 256,
                       iters: int = 10, reps: int = 3,
                       max_grad_norm: float = 1.0):
    """Full AMP gradient epilogue, per-leaf vs flat, same grads.

    Per-leaf: ``check_finite`` + ``unscale_grads`` + ``clip_grad_norm``
    + per-leaf fused-Adam step — 3 full pytree walks plus the ravel
    clip_grad does, then per-leaf update math.  Flat: ONE pack,
    ``flat_unscale_norm`` per bucket (unscale + flag + Σg² in one HBM
    read), clip coefficient folded into the flat Adam kernels' grad
    scaling.  Grads are precomputed (identical input to both paths) so
    the number isolates the gradient pipeline, not the backward."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.benchlib import timeit
    from apex_tpu.contrib.clip_grad import clip_grad_norm
    from apex_tpu.optimizers import FusedAdam

    params = many_leaf_params(jax, jnp, layers, hidden)
    scaler = amp.LossScaleState.create(2.0 ** 12)
    scale = float(scaler.loss_scale)
    grads = jax.tree_util.tree_map(
        lambda p: (p * 1e-3 + 1e-4) * scale, params)   # "scaled" grads

    out = {
        "amp_leaves": len(jax.tree_util.tree_leaves(params)),
        "amp_elements": sum(int(l.size) for l in
                            jax.tree_util.tree_leaves(params)),
        "amp_max_grad_norm": max_grad_norm,
    }

    # --- per-leaf oracle path -------------------------------------------
    opt_pl = FusedAdam(params, lr=1e-3, fuse_buckets=False)

    def per_leaf_step(work, opt_state, grads, scaler_state, step):
        found_inf = amp.check_finite(grads)
        g = amp.unscale_grads(grads, scaler_state)
        g, _norm = clip_grad_norm(g, max_grad_norm)
        new_work, new_state = opt_pl.functional_step(
            work, opt_state, g, step)
        return new_work, new_state, found_inf

    # --- flat pipeline path ---------------------------------------------
    opt_fl = FusedAdam(params, lr=1e-3, fuse_buckets=True)
    pipe = amp.FlatGradPipeline(optimizer=opt_fl,
                                max_grad_norm=max_grad_norm)

    def flat_step(work, opt_state, grads, scaler_state, step):
        flat = pipe.unscale_and_norm(pipe.pack(grads), scaler_state)
        new_work, new_state = opt_fl.functional_step(
            work, opt_state, flat.bufs, step, clip_coef=flat.clip_coef)
        return new_work, new_state, flat.found_inf

    for label, fn, opt in (("per_leaf", per_leaf_step, opt_pl),
                           ("flat", flat_step, opt_fl)):
        # two programs, two compiles — not a hot-loop retrace
        # apexlint: disable-next=APX302
        step_fn = jax.jit(fn)
        ms = timeit(step_fn, params, opt.opt_state, grads, scaler,
                    jnp.int32(2), iters=iters, reps=reps)
        out[f"amp_step_{label}_ms"] = round(ms, 3)
    if out["amp_step_flat_ms"]:
        out["amp_pipeline_speedup"] = round(
            out["amp_step_per_leaf_ms"] / out["amp_step_flat_ms"], 2)
    return out


def bench_flat_accumulate(layers: int = 48, hidden: int = 256,
                          iters: int = 10, reps: int = 3):
    """One microbatch accumulation, per-leaf tree-map-add vs fused
    flat: the loop body a grad-accumulation train step pays N_micro
    times per step.  Per-leaf: one XLA add per leaf (hundreds of tiny
    dispatches on a transformer tree) into a per-leaf f32 accumulator
    tree.  Flat: grads arrive PACKED (the pipeline's reality — packed
    once at the backward) and ``flat_accumulate`` does one fused
    read-modify-write per dtype bucket with the found_inf latch from
    the same HBM sweep.  The per-leaf side gets its latch the per-leaf
    way (``check_finite``), so both sides answer the same question."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.benchlib import timeit
    from apex_tpu.optimizers import FusedAdam

    params = many_leaf_params(jax, jnp, layers, hidden)
    grads = jax.tree_util.tree_map(lambda p: p * 1e-3 + 1e-4, params)

    opt = FusedAdam(params, lr=1e-3)
    pipe = amp.FlatGradPipeline(optimizer=opt)
    acc_flat = opt.grad_accum_init()
    acc_tree = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    packed = opt._plan.pack_grads(grads)

    def per_leaf(acc, grads, bad):
        new = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), acc, grads)
        return new, jnp.maximum(bad, amp.check_finite(new))

    def flat(acc, bufs):
        return pipe.accumulate(acc, bufs)

    out = {
        "accum_leaves": len(jax.tree_util.tree_leaves(params)),
        "accum_elements": sum(int(l.size) for l in
                              jax.tree_util.tree_leaves(params)),
    }
    # two programs, two compiles — not a hot-loop retrace
    # apexlint: disable-next=APX302
    ms_pl = timeit(jax.jit(per_leaf), acc_tree, grads, jnp.int32(0),
                   iters=iters, reps=reps)
    # apexlint: disable-next=APX302
    ms_fl = timeit(jax.jit(flat), acc_flat, packed,
                   iters=iters, reps=reps)
    out["accum_per_leaf_ms"] = round(ms_pl, 3)
    out["accum_flat_ms"] = round(ms_fl, 3)
    if ms_fl:
        out["accum_flat_speedup"] = round(ms_pl / ms_fl, 2)
    return out


def bench_grad_accum(layers: int = 16, hidden: int = 128,
                     batch: int = 32, n_micro=(1, 4, 8),
                     iters: int = 5, reps: int = 3):
    """Full microbatched AMP train steps, per-leaf vs flat
    accumulation, at N_micro in {1,4,8} (bench.py's grad_accum train
    legs).  Each leg is one jitted step: scaled_value_and_grad with
    ``microbatches=N`` on the respective layout, then the fused (or
    per-leaf) optimizer update with the latched found_inf."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.benchlib import timeit
    from apex_tpu.optimizers import FusedAdam

    params = many_leaf_params(jax, jnp, layers, hidden)
    x = jax.random.normal(jax.random.key(1), (batch, hidden))
    scaler = amp.LossScaleState.create(2.0 ** 12)
    loss_fn = many_leaf_loss(jnp)

    out = {"grad_accum_batch": batch,
           "grad_accum_leaves":
           len(jax.tree_util.tree_leaves(params))}
    opt_fl = FusedAdam(params, lr=1e-3)
    pipe = amp.FlatGradPipeline(optimizer=opt_fl)
    opt_pl = FusedAdam(params, lr=1e-3, fuse_buckets=False)
    hypers = {k: jnp.asarray(v, jnp.float32)
              for k, v in opt_fl.hypers.items()
              if isinstance(v, float)}
    for n in n_micro:
        def flat_step(work, opt_state, x, step, n=n):
            ptree = pipe.plan.unpack(work)
            loss, flat = pipe.scaled_value_and_grad(
                loss_fn, scaler, ptree, x, microbatches=n)
            new_w, _, new_s = opt_fl._full_step_flat(
                work, None, opt_state, flat.bufs, step, 1.0,
                hypers, flat.found_inf)
            return loss, new_w, new_s

        def per_leaf_step(work, opt_state, x, step, n=n):
            loss, grads, found = amp.scaled_value_and_grad(
                loss_fn, scaler, work, x, microbatches=n)
            new_w, new_s = opt_pl.functional_step(
                work, opt_state, grads, step, found_inf=found)
            return loss, new_w, new_s

        # each (layout, N) pair is its own program by design (not a
        # hot-loop retrace), and the bench reruns one program many
        # times over the SAME state arrays — donating opt_state would
        # delete the inputs after the first rep
        # apexlint: disable-next=APX302
        ms_fl = timeit(jax.jit(flat_step), opt_fl._param_bufs,   # apexlint: disable=APX401
                       opt_fl.opt_state, x, jnp.int32(2),
                       iters=iters, reps=reps)
        # apexlint: disable-next=APX302
        ms_pl = timeit(jax.jit(per_leaf_step), params,   # apexlint: disable=APX401
                       opt_pl.opt_state, x, jnp.int32(2),
                       iters=iters, reps=reps)
        out[f"grad_accum_flat_n{n}_ms"] = round(ms_fl, 3)
        out[f"grad_accum_per_leaf_n{n}_ms"] = round(ms_pl, 3)
        if ms_fl:
            out[f"grad_accum_n{n}_speedup"] = round(ms_pl / ms_fl, 2)
    return out


def mixed_dtype_params(jax, jnp, layers: int = 48, hidden: int = 256):
    """The many-leaf tree in amp-O2 clothing: bf16 matmul weights plus
    f32 norm vectors per layer — two dtype buckets, masters for the
    bf16 leaves, the state mix a real checkpoint carries."""
    base = many_leaf_params(jax, jnp, layers, hidden)
    return {
        name: {"w": leaves["w"].astype(jnp.bfloat16), "b": leaves["b"],
               "scale": leaves["scale"], "shift": leaves["shift"]}
        for name, leaves in base.items()
    }


def bench_checkpoint_snapshot(layers: int = 48, hidden: int = 256,
                              reps: int = 5):
    """Training-state snapshot+serialize time, bucket-native (v2) vs
    per-leaf (v1), over the same realistic mixed-dtype tree.

    Each rep is one full ``save_training_state`` to a scratch file:
    snapshot (device copies / per-leaf state_dict walk), device->host
    transfer, checksum, header and the sequential write.  This is a
    HOST path — disk and PCIe, not a jittable device program — so it
    is timed by wall-clock median over reps (the telemetry_flush_ms
    idiom), not benchlib's on-device loop; the file lands in a tmpdir
    so the numbers include real filesystem work."""
    import os
    import shutil
    import statistics
    import tempfile
    import time

    import jax
    import jax.numpy as jnp

    from apex_tpu import checkpoint as ckpt
    from apex_tpu.optimizers import FusedAdam

    params = mixed_dtype_params(jax, jnp, layers, hidden)
    grads = jax.tree_util.tree_map(
        lambda p: (p * 1e-3 + 1e-4).astype(p.dtype), params)

    tmpdir = tempfile.mkdtemp(prefix="apex_ckpt_bench_")
    out = {
        "ckpt_leaves": len(jax.tree_util.tree_leaves(params)),
        "ckpt_elements": sum(int(l.size) for l in
                             jax.tree_util.tree_leaves(params)),
    }
    try:
        for fuse, fmt, label in ((True, "v2", "bucketed"),
                                 (False, "v1", "perleaf")):
            opt = FusedAdam(params, lr=1e-3, fuse_buckets=fuse)
            opt.step(grads)            # realistic non-zero opt state
            path = os.path.join(tmpdir, f"snap_{label}.ckpt")
            ms = []
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                ckpt.save_training_state(path, optimizer=opt,
                                         step=1, format=fmt)
                ms.append((time.perf_counter() - t0) * 1e3)
            out[f"ckpt_snapshot_{label}_ms"] = round(
                statistics.median(ms), 3)
            out[f"ckpt_bytes_{label}"] = os.path.getsize(path)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    if out["ckpt_snapshot_bucketed_ms"]:
        out["ckpt_snapshot_speedup"] = round(
            out["ckpt_snapshot_perleaf_ms"]
            / out["ckpt_snapshot_bucketed_ms"], 2)
    return out
