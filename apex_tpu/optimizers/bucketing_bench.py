"""Per-leaf vs bucketed optimizer-step microbench.

The point of the bucketed flat path is amortizing per-leaf dispatch: a
ResNet-50/BERT-sized pytree is hundreds of small XLA ops per step on
the per-leaf path versus one flat Pallas kernel per dtype bucket.  This
module times both paths over the SAME many-leaf pytree with benchlib's
amortized on-device loop (one dispatch runs many steps serially, so a
tunneled session measures the program, not the relay).

Shared by bench.py (TPU extras), tools/kernel_bench.py (JSON row) and
the tier-1 smoke test (tiny shapes, CPU: proves the harness, not
performance).
"""

from __future__ import annotations


def many_leaf_params(jax, jnp, layers: int = 48, hidden: int = 256):
    """A transformer-ish pytree: per layer one square matrix plus three
    small vectors — the shape mix (few big, many tiny leaves) where
    per-leaf stepping drowns in dispatch."""
    keys = jax.random.split(jax.random.key(0), layers)
    return {
        f"layer{i:03d}": {
            "w": jax.random.normal(keys[i], (hidden, hidden), jnp.float32),
            "b": jnp.zeros((hidden,), jnp.float32),
            "scale": jnp.ones((hidden,), jnp.float32),
            "shift": jnp.zeros((hidden,), jnp.float32),
        }
        for i in range(layers)
    }


def bench_optimizer_bucketing(layers: int = 48, hidden: int = 256,
                              iters: int = 10, reps: int = 3,
                              optimizer: str = "adam"):
    """Times one optimizer step, per-leaf vs bucketed, on a many-leaf
    pytree.  Returns a dict of ms timings plus the speedup and the
    bucket plan summary."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.benchlib import timeit
    from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedSGD

    cls = {"adam": FusedAdam, "sgd": FusedSGD, "lamb": FusedLAMB}[optimizer]
    params = many_leaf_params(jax, jnp, layers, hidden)
    grads = jax.tree_util.tree_map(lambda p: p * 1e-3 + 1e-4, params)

    out = {
        "optim": optimizer,
        "optim_leaves": len(jax.tree_util.tree_leaves(params)),
        "optim_elements": sum(int(l.size) for l in
                              jax.tree_util.tree_leaves(params)),
    }
    for fuse, label in ((False, "perleaf"), (True, "bucketed")):
        opt = cls(params, lr=1e-3, fuse_buckets=fuse)
        if fuse:
            out["optim_buckets"] = opt._plan.describe()
            args = (opt._param_bufs, None, opt.opt_state)
        else:
            args = (opt.params, None, opt.opt_state)
        hypers = {k: jnp.asarray(v, jnp.float32)
                  for k, v in opt.hypers.items()
                  if isinstance(v, float)}
        # the pure step body (what a train loop embeds); jitted fresh ON
        # PURPOSE: the loop has exactly two iterations (per-leaf vs
        # bucketed are different programs), not a hot path
        # apexlint: disable-next=APX302
        step_fn = jax.jit(opt._full_step_impl)
        ms = timeit(step_fn, *args, grads, jnp.int32(2),
                    jnp.float32(1.0), hypers, iters=iters, reps=reps)
        out[f"optim_step_{label}_ms"] = round(ms, 3)
    if out["optim_step_bucketed_ms"]:
        out["optim_bucketing_speedup"] = round(
            out["optim_step_perleaf_ms"] / out["optim_step_bucketed_ms"], 2)
    return out
