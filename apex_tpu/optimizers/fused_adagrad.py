"""FusedAdagrad (reference: apex/optimizers/fused_adagrad.py);
cf. csrc/multi_tensor_adagrad.cu.

Flat AMP pipeline: ``step()`` takes already-packed per-bucket gradient
buffers and a traced ``clip_coef`` folded into ``flat_adagrad``'s
in-kernel ``inv_scale`` (optimizers/_base._fold_clip)."""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import _functional as F
from apex_tpu.optimizers._base import FusedOptimizerBase, tree_map, unzip_tree


class FusedAdagrad(FusedOptimizerBase):
    defaults = dict(lr=1e-2, eps=1e-10, weight_decay=0.0,
                    adagrad_w_mode=False, set_grad_none=True)

    def init_state(self, params):
        return {"sum": tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)}

    def _step_math(self, params, grads, opt_state, step, grad_scale, hypers):
        h = self._merge_hypers(hypers)

        def leaf(p, g, s):
            return F.adagrad_step(p, g, s, lr=h["lr"], eps=h["eps"],
                                  weight_decay=h["weight_decay"],
                                  grad_scale=grad_scale)

        out = tree_map(leaf, params, grads, opt_state["sum"])
        new_p, new_s = unzip_tree(params, out, 2)
        return new_p, {"sum": new_s}

    def _flat_bucket_step(self, bucket_index, p, g, state, step, grad_scale,
                          hypers, extra):
        h = self._merge_hypers(hypers)
        po, ho = mt.flat_adagrad(
            p, g, state["sum"], lr=h["lr"], eps=h["eps"],
            weight_decay=h["weight_decay"], grad_scale=grad_scale)
        return po, {"sum": ho}
