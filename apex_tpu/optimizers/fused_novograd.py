"""FusedNovoGrad (reference: apex/optimizers/fused_novograd.py).

NovoGrad: layer-wise (per-tensor scalar) second moment normalizing the
gradient before the first-moment EMA; cf. csrc/multi_tensor_novograd.cu.

Flat AMP pipeline: ``step()`` takes already-packed per-bucket gradient
buffers and a traced ``clip_coef`` folded into the gradient scaling
(optimizers/_base._fold_clip); the per-tensor second-moment norms are
then norms of the CLIPPED gradients, matching the per-leaf oracle fed
pre-clipped grads.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import _functional as F
from apex_tpu.optimizers._base import FusedOptimizerBase, tree_map, unzip_tree


class FusedNovoGrad(FusedOptimizerBase):
    defaults = dict(lr=1e-3, beta1=0.95, beta2=0.98, eps=1e-8,
                    weight_decay=0.0, grad_averaging=True, amsgrad=False,
                    bias_correction=True, reg_inside_moment=False,
                    norm_type=2, init_zero=False, set_grad_none=True)

    def __init__(self, params, betas=None, **kw):
        if betas is not None:
            kw["beta1"], kw["beta2"] = betas
        if kw.pop("amsgrad", False):
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad "
                               "variant.")
        super().__init__(params, **kw)

    def init_state(self, params):
        return {
            "exp_avg": tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "exp_avg_sq": tree_map(
                lambda p: jnp.zeros((), jnp.float32), params),
        }

    def _step_math(self, params, grads, opt_state, step, grad_scale, hypers):
        h = self._merge_hypers(hypers)
        first = step == 1

        if self.hypers["norm_type"] != 2:
            raise ValueError("FusedNovoGrad only supports norm_type=2")

        def leaf(p, g, m, v):
            return F.novograd_step(
                p, g, m, v, lr=h["lr"], beta1=h["beta1"], beta2=h["beta2"],
                eps=h["eps"], weight_decay=h["weight_decay"],
                first_run=first,
                grad_averaging=self.hypers["grad_averaging"],
                grad_scale=grad_scale,
                init_zero=self.hypers["init_zero"],
                reg_inside_moment=self.hypers["reg_inside_moment"])

        out = tree_map(leaf, params, grads, opt_state["exp_avg"],
                       opt_state["exp_avg_sq"])
        new_p, new_m, new_v = unzip_tree(params, out, 3)
        return new_p, {"exp_avg": new_m, "exp_avg_sq": new_v}

    def _flat_bucket_step(self, bucket_index, p, g, state, step, grad_scale,
                          hypers, extra):
        if self.hypers["norm_type"] != 2:
            raise ValueError("FusedNovoGrad only supports norm_type=2")
        h = self._merge_hypers(hypers)
        # per-tensor second moments ride the bucket's segment ids: the
        # packed exp_avg_sq is one (num leaves,) vector per bucket
        po, mo, vo = mt.flat_novograd(
            p, g, state["exp_avg"], state["exp_avg_sq"],
            self._plan.segment_ids(bucket_index),
            lr=h["lr"], beta1=h["beta1"], beta2=h["beta2"], eps=h["eps"],
            weight_decay=h["weight_decay"], first_run=step == 1,
            grad_averaging=self.hypers["grad_averaging"],
            init_zero=self.hypers["init_zero"],
            reg_inside_moment=self.hypers["reg_inside_moment"],
            grad_scale=grad_scale)
        return po, {"exp_avg": mo, "exp_avg_sq": vo}
