"""FusedLAMB (reference: apex/optimizers/fused_lamb.py).

LAMB = Adam moments + per-tensor trust ratio (||p||/||update||), with an
optional global-gradient-norm clip computed first — the reference's
two-stage multi_tensor_lamb with a multi_tensor_l2norm prologue
(SURVEY.md §2.1).  The global norm here is one fused reduction across the
pytree; the trust ratio stays per-leaf exactly as the reference keeps it
per-tensor.

Flat AMP pipeline: ``step()`` takes already-packed per-bucket gradient
buffers and a traced pipeline ``clip_coef`` folded into the gradient
scaling (optimizers/_base._fold_clip).  The two clips COMPOSE: the
max_grad_norm prologue divides its measured norm by the effective
grad_scale, so it judges the gradients as the pipeline already clipped
them — prefer ONE owner (pipeline ``max_grad_norm`` or LAMB's, not
both) unless double clipping is intended.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import _functional as F
from apex_tpu.optimizers._base import FusedOptimizerBase, tree_map, unzip_tree


class FusedLAMB(FusedOptimizerBase):
    defaults = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-6,
                    weight_decay=0.01, amsgrad=False, adam_w_mode=True,
                    grad_averaging=True, set_grad_none=True,
                    bias_correction=True, max_grad_norm=1.0,
                    use_nvlamb=False)

    def __init__(self, params, betas=None, **kw):
        if betas is not None:
            kw["beta1"], kw["beta2"] = betas
        if kw.pop("amsgrad", False):
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant.")
        super().__init__(params, **kw)

    def init_state(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {"exp_avg": tree_map(zeros, params),
                "exp_avg_sq": tree_map(zeros, params)}

    def _step_math(self, params, grads, opt_state, step, grad_scale, hypers):
        h = self._merge_hypers(hypers)
        gnorm = F.global_grad_norm(grads) / grad_scale
        maxn = h["max_grad_norm"]
        clip = jnp.where((maxn > 0) & (gnorm > maxn),
                         maxn / gnorm, jnp.float32(1.0))

        def leaf(p, g, m, v):
            return F.lamb_step(
                p, g, m, v, lr=h["lr"], beta1=h["beta1"], beta2=h["beta2"],
                eps=h["eps"], weight_decay=h["weight_decay"], step=step,
                bias_correction=self.hypers["bias_correction"],
                grad_scale=grad_scale,
                clip_coeff=clip, use_nvlamb=self.hypers["use_nvlamb"])

        out = tree_map(leaf, params, grads, opt_state["exp_avg"],
                       opt_state["exp_avg_sq"])
        new_p, new_m, new_v = unzip_tree(params, out, 3)
        return new_p, {"exp_avg": new_m, "exp_avg_sq": new_v}

    def _flat_prologue(self, work_bufs, grad_bufs, step, grad_scale,
                       hypers):
        """Global-grad-norm clip coefficient, computed once across ALL
        buckets (the reference's multi_tensor_l2norm prologue): one
        fused reduction per bucket, rss-combined."""
        h = self._merge_hypers(hypers)
        gnorm = jnp.sqrt(sum(mt.flat_l2norm(g) ** 2 for g in grad_bufs))
        gnorm = gnorm / grad_scale
        maxn = h["max_grad_norm"]
        return jnp.where((maxn > 0) & (gnorm > maxn),
                         maxn / gnorm, jnp.float32(1.0))

    def _flat_bucket_step(self, bucket_index, p, g, state, step, grad_scale,
                          hypers, extra):
        h = self._merge_hypers(hypers)
        po, mo, vo = mt.flat_lamb(
            p, g, state["exp_avg"], state["exp_avg_sq"],
            self._plan.segment_ids(bucket_index),
            self._plan.num_segments(bucket_index),
            lr=h["lr"], beta1=h["beta1"], beta2=h["beta2"], eps=h["eps"],
            weight_decay=h["weight_decay"], step=step,
            bias_correction=self.hypers["bias_correction"],
            grad_scale=grad_scale, clip_coeff=extra,
            use_nvlamb=self.hypers["use_nvlamb"])
        return po, {"exp_avg": mo, "exp_avg_sq": vo}


class FusedMixedPrecisionLamb(FusedLAMB):
    """Reference: apex/optimizers/fused_mixed_precision_lamb.py — LAMB
    stepping f32 masters for low-precision model params.  The base class
    already keeps masters whenever params are bf16/fp16; this subclass
    just forces it on."""

    def __init__(self, params, **kw):
        kw["master_weights"] = True
        super().__init__(params, **kw)
