"""Shared machinery for the fused optimizer facades.

The reference optimizers subclass torch.optim.Optimizer and mutate params
in place via one multi-tensor launch (e.g. apex/optimizers/fused_adam.py,
SURVEY.md §3.3).  The JAX facade keeps that class shape — construct with a
params pytree, call ``step(grads)`` — but is a thin stateful wrapper over
a pure, jitted ``(params, opt_state, grads, scalars) -> (params,
opt_state)`` function, so the same math can also be embedded directly in a
user's jitted train step via the ``functional_step`` attribute.

Master weights: when params are bf16/fp16 and ``master_weights=True`` the
facade keeps f32 masters, steps those, and writes back model-dtype params
(reference O2 contract, apex/amp/_process_optimizer.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

Pytree = Any
tree_map = jax.tree_util.tree_map


def _host_sharding(x: jax.Array):
    """The array's own sharding, re-homed to pinned host memory (the
    TPU host-offload target; CPU also exposes the kind)."""
    return x.sharding.with_memory_kind("pinned_host")


def place_on_host(tree: Pytree) -> Pytree:
    """Eagerly move every array leaf to pinned host memory, preserving
    its device/mesh sharding."""
    return tree_map(
        lambda x: jax.device_put(x, _host_sharding(x))
        if isinstance(x, jax.Array) else x, tree)


def place_on_device(tree: Pytree) -> Pytree:
    return tree_map(
        lambda x: jax.device_put(
            x, x.sharding.with_memory_kind("device"))
        if isinstance(x, jax.Array) else x, tree)


def unzip_tree(like: Pytree, tree_of_tuples: Pytree, n: int):
    """pytree-of-n-tuples -> n-tuple of pytrees (robust to tuples INSIDE
    the params pytree, unlike is_leaf=isinstance(tuple))."""
    outer = jax.tree_util.tree_structure(like)
    inner = jax.tree_util.tree_structure(tuple(range(n)))
    return jax.tree_util.tree_transpose(outer, inner, tree_of_tuples)


def _is_low_precision(tree) -> bool:
    return any(l.dtype in (jnp.bfloat16, jnp.float16)
               for l in jax.tree_util.tree_leaves(tree)
               if jnp.issubdtype(l.dtype, jnp.floating))


class FusedOptimizerBase:
    """Subclasses set ``defaults`` and implement ``_step_math``."""

    def __init__(self, params: Pytree, master_weights: Optional[bool] = None,
                 masters: Optional[Pytree] = None,
                 offload_state: bool = False, **hypers):
        self.hypers: Dict[str, Any] = dict(self.defaults)
        unknown = set(hypers) - set(self.hypers)
        if unknown:
            raise TypeError(f"unexpected arguments {sorted(unknown)}")
        self.hypers.update(hypers)
        if masters is not None:
            # externally-sourced masters (amp.initialize's copies made
            # from the ORIGINAL f32 init — upcasting the rounded half
            # params here would lose the low bits, apex O2 contract)
            if master_weights is False:
                raise ValueError(
                    "masters= provided together with "
                    "master_weights=False — contradictory")
            if not _is_low_precision(params):
                raise ValueError(
                    "masters= provided but params are not low-precision"
                    " — masters only apply to half-precision params")
            if (jax.tree_util.tree_structure(masters)
                    != jax.tree_util.tree_structure(params)):
                raise ValueError(
                    "masters pytree structure does not match params")
            master_weights = True
        if master_weights is None:
            master_weights = _is_low_precision(params)
        self.master_weights = master_weights and _is_low_precision(params)
        self.params = params
        if not self.master_weights:
            masters = None
        else:
            masters = tree_map(
                lambda x: x.astype(jnp.float32)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                masters if masters is not None else params)
        self.masters = masters
        self.opt_state = self.init_state(masters if masters is not None
                                         else params)
        self.step_count = jnp.int32(0)
        # Host-offloaded optimizer state (beyond-reference; the HBM
        # relief the reference gets from ZeRO sharding alone).  On TPU
        # the step is ONE program: state transfers in from pinned host,
        # math runs on device, out_shardings land the new state back on
        # host (XLA overlaps the DMAs with compute).  Elsewhere (CPU CI)
        # the in-jit placement custom call doesn't exist, so step()
        # moves the state eagerly around a plain device step.
        self.offload_state = offload_state
        self._fused_offload = False
        if offload_state:
            from apex_tpu.ops._dispatch import on_tpu
            self.opt_state = place_on_host(self.opt_state)
            self._fused_offload = on_tpu()
            if self._fused_offload:
                # no donation: the state crosses memory kinds
                # (pinned_host in, device math, pinned_host out) and
                # donating across spaces is not aliasable anyway
                self._jit_step = jax.jit(  # apexlint: disable=APX401
                    self._full_step_offload,
                    out_shardings=(None, None,
                                   tree_map(_host_sharding,
                                            self.opt_state)))
            else:
                self._jit_step = jax.jit(self._full_step,
                                         donate_argnums=(2,))
        else:
            self._jit_step = jax.jit(self._full_step,
                                     donate_argnums=(2,))

    # ---- functional core -------------------------------------------------
    def init_state(self, params: Pytree) -> Pytree:
        raise NotImplementedError

    def _step_math(self, params, grads, opt_state, step, grad_scale, hypers):
        """Pure update on the (possibly master) params."""
        raise NotImplementedError

    def _full_step(self, params, masters, opt_state, grads, step, grad_scale,
                   hypers):
        work = masters if masters is not None else params
        new_work, opt_state = self._step_math(
            work, grads, opt_state, step, grad_scale, hypers)
        if masters is not None:
            new_params = tree_map(lambda p, m: m.astype(p.dtype)
                                  if jnp.issubdtype(p.dtype, jnp.floating)
                                  else m, params, new_work)
            return new_params, new_work, opt_state
        return new_work, None, opt_state

    def _full_step_offload(self, params, masters, opt_state, grads, step,
                           grad_scale, hypers):
        """TPU fused-offload step body: pull state from pinned host at
        the top; out_shardings push the new state back."""
        opt_state = tree_map(
            lambda x: jax.device_put(x, jax.memory.Space.Device),
            opt_state)
        return self._full_step(params, masters, opt_state, grads, step,
                               grad_scale, hypers)

    def functional_step(self, params, opt_state, grads, step, grad_scale=1.0):
        """Embed-in-your-own-jit entry point (no master handling)."""
        return self._step_math(params, grads, opt_state, step,
                               jnp.asarray(grad_scale, jnp.float32),
                               dict(self.hypers))

    # ---- stateful facade -------------------------------------------------
    def step(self, grads: Pytree, grad_scale=1.0) -> Pytree:
        """Apply one update; returns (and stores) the new params."""
        self.step_count = self.step_count + 1
        state = self.opt_state
        eager_offload = self.offload_state and not self._fused_offload
        if eager_offload:   # CPU fallback: explicit round trip
            state = place_on_device(state)
        self.params, self.masters, self.opt_state = self._jit_step(
            self.params, self.masters, state, grads,
            self.step_count, jnp.asarray(grad_scale, jnp.float32),
            {k: jnp.asarray(v, jnp.float32) if isinstance(v, float) else v
             for k, v in self.hypers.items()
             if isinstance(v, (int, float)) and not isinstance(v, bool)})
        if eager_offload:
            self.opt_state = place_on_host(self.opt_state)
        return self.params

    def zero_grad(self):
        """No-op for parity: JAX grads are freshly computed, never stored."""

    # ---- serialization (torch Optimizer.state_dict shape) ---------------
    def state_dict(self):
        # copy the state out: the next step() DONATES self.opt_state to
        # the compiled update, which deletes the buffers a by-reference
        # snapshot would still point at ("Array has been deleted" at
        # serialization time)
        return {
            "step": int(self.step_count),
            "hypers": dict(self.hypers),
            "state": tree_map(
                lambda x: jnp.array(x, copy=True)
                if isinstance(x, jax.Array) else x, self.opt_state),
            "masters": self.masters,
        }

    def load_state_dict(self, sd):
        self.step_count = jnp.int32(sd["step"])
        self.hypers.update(sd["hypers"])
        # copy: step() donates opt_state to the compiled update, and the
        # caller's checkpoint dict must stay readable after we step
        self.opt_state = tree_map(
            lambda x: jnp.array(x, copy=True)
            if isinstance(x, jax.Array) else x, sd["state"])
        if self.offload_state:
            # restore must respect the host-residency invariant NOW —
            # waiting for the next step to re-home it would leave the
            # full f32 state in HBM at exactly the tight-memory moment
            # offloading exists for
            self.opt_state = place_on_host(self.opt_state)
        if sd.get("masters") is not None:
            self.masters = sd["masters"]

    # hyper access in the torch param_group idiom: opt.lr = ...
    @property
    def lr(self):
        return self.hypers["lr"]

    @lr.setter
    def lr(self, value):
        self.hypers["lr"] = value

    def _merge_hypers(self, traced_hypers):
        """Traced float hypers override statics inside the jitted step."""
        merged = dict(self.hypers)
        merged.update(traced_hypers)
        return merged
