"""Shared machinery for the fused optimizer facades.

The reference optimizers subclass torch.optim.Optimizer and mutate params
in place via one multi-tensor launch (e.g. apex/optimizers/fused_adam.py,
SURVEY.md §3.3).  The JAX facade keeps that class shape — construct with a
params pytree, call ``step(grads)`` — but is a thin stateful wrapper over
a pure, jitted ``(params, opt_state, grads, scalars) -> (params,
opt_state)`` function, so the same math can also be embedded directly in a
user's jitted train step via the ``functional_step`` attribute.

Bucketed flat path (default, ``fuse_buckets=True``): at construction a
one-time :class:`~apex_tpu.multi_tensor_apply.packer.BucketPlan`
concatenates dtype-homogeneous leaves into flat HBM buffers, and the
jitted step runs ONE flat Pallas kernel per bucket
(apex_tpu.ops.multi_tensor) — the TPU realization of the reference's
``multi_tensor_apply`` + ``amp_C`` design.  Params, masters and
optimizer state stay PACKED between steps; the per-leaf pytree view is
rebuilt lazily (one compiled unpack program) only for ``state_dict()``,
``load_state_dict()`` and the ``params``/``masters`` properties, and the
checkpoint layout is unchanged — old per-leaf checkpoints load into
bucketed optimizers and vice versa.  ``fuse_buckets=False`` (or any
tree the packer declines: non-float leaves, multi-device shardings)
falls back to the traced per-leaf update.

Master weights: when params are bf16/fp16 and ``master_weights=True`` the
facade keeps f32 masters, steps those, and writes back model-dtype params
(reference O2 contract, apex/amp/_process_optimizer.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply.packer import BucketPlan
from apex_tpu.telemetry import _tape

Pytree = Any
tree_map = jax.tree_util.tree_map

# in-jit "move to device memory" marker: jax.memory.Space.Device where it
# exists, else the older TransferToMemoryKind spelling
try:
    _DEVICE_MEMORY = jax.memory.Space.Device
except AttributeError:
    try:
        from jax.sharding import TransferToMemoryKind as _TTMK
    except ImportError:  # pre-public spelling
        from jax._src.sharding_impls import TransferToMemoryKind as _TTMK
    _DEVICE_MEMORY = _TTMK("device")


def _memory_kinds(x: jax.Array):
    dev = next(iter(x.sharding.device_set))
    try:
        return {m.kind for m in dev.addressable_memories()}
    except Exception:
        return set()


def _host_sharding(x: jax.Array):
    """The array's own sharding, re-homed to host memory: pinned_host
    (the TPU host-offload target) where the backend exposes it, else
    unpinned_host (what older-jax CPU backends call their only space)."""
    kinds = _memory_kinds(x)
    if "pinned_host" in kinds:
        return x.sharding.with_memory_kind("pinned_host")
    if "unpinned_host" in kinds:
        return x.sharding.with_memory_kind("unpinned_host")
    return x.sharding


def _device_sharding(x: jax.Array):
    kinds = _memory_kinds(x)
    if "device" in kinds:
        return x.sharding.with_memory_kind("device")
    dev = next(iter(x.sharding.device_set))
    try:
        return x.sharding.with_memory_kind(dev.default_memory().kind)
    except Exception:
        return x.sharding


def place_on_host(tree: Pytree) -> Pytree:
    """Eagerly move every array leaf to host memory, preserving its
    device/mesh sharding."""
    return tree_map(
        lambda x: jax.device_put(x, _host_sharding(x))
        if isinstance(x, jax.Array) else x, tree)


def place_on_device(tree: Pytree) -> Pytree:
    return tree_map(
        lambda x: jax.device_put(x, _device_sharding(x))
        if isinstance(x, jax.Array) else x, tree)


def _device_copy(buf: jax.Array) -> jax.Array:
    """Async device-side copy of one flat buffer (dispatch returns
    immediately).  The bucket-native checkpoint path routes every copy
    through this seam so tests can assert structurally that a packed
    snapshot is exactly one copy per buffer and nothing else."""
    return buf.copy()


def unzip_tree(like: Pytree, tree_of_tuples: Pytree, n: int):
    """pytree-of-n-tuples -> n-tuple of pytrees (robust to tuples INSIDE
    the params pytree, unlike is_leaf=isinstance(tuple))."""
    outer = jax.tree_util.tree_structure(like)
    inner = jax.tree_util.tree_structure(tuple(range(n)))
    return jax.tree_util.tree_transpose(outer, inner, tree_of_tuples)


def _is_low_precision(tree) -> bool:
    return any(l.dtype in (jnp.bfloat16, jnp.float16)
               for l in jax.tree_util.tree_leaves(tree)
               if jnp.issubdtype(l.dtype, jnp.floating))


def _select(keep, new_tree, old_tree):
    """Branch-free elementwise keep?new:old over matching pytrees (the
    amp found_inf skip — mirrors amp.scaler.conditional_step, never a
    host sync)."""
    return tree_map(lambda a, b: jnp.where(keep, a, b), new_tree, old_tree)


def _skip_on_overflow(found_inf, new_work, old_work, new_state,
                      old_state):
    """The branch-free found_inf skip, shared by every step body:
    keep the old values when the flag is set, and report the skip.
    The telemetry emission lands only when the body is traced inside
    an instrumented jit (functional_step, or a train step embedding
    ``_full_step_impl``); the stateful ``step()`` facade's internal
    jit cannot report into an outer ring — the tape correctly drops
    its tracers (telemetry._tape docstring)."""
    keep = jnp.asarray(found_inf) == 0
    _tape.emit("optim/skipped", jnp.asarray(found_inf) > 0,
               reduce="max")
    return (_select(keep, new_work, old_work),
            _select(keep, new_state, old_state))


def _fold_clip(grad_scale, clip_coef):
    """Fold a global-norm clip coefficient into the gradient scale.

    Every flat_* kernel (and the per-leaf math) multiplies grads by
    ``1/grad_scale``; an effective scale of ``grad_scale/clip_coef``
    therefore multiplies by ``clip_coef/grad_scale`` — clipping rides
    the scaling the kernels already do, with no extra gradient pass or
    copy.  LAMB's global-grad-norm prologue composes correctly: it sees
    the norm of the gradients AS CLIPPED, which is what its own
    max_grad_norm logic should be judging."""
    gs = jnp.asarray(grad_scale, jnp.float32)
    if clip_coef is None:
        return gs
    return gs / jnp.asarray(clip_coef, jnp.float32)


# fp8 delayed-scaling state carried as packed optimizer slots (see
# enable_fp8): updated by the step itself from the post-update work
# buffers, donated/offloaded/checkpointed like every other slot, and
# excluded from the per-bucket optimizer math.
_FP8_SLOTS = ("fp8_amax_history", "fp8_scale")


class FusedOptimizerBase:
    """Subclasses set ``defaults`` and implement ``_step_math`` (per-leaf
    oracle path) plus ``_flat_bucket_step`` (bucketed flat path)."""

    def __init__(self, params: Pytree, master_weights: Optional[bool] = None,
                 masters: Optional[Pytree] = None,
                 offload_state: bool = False,
                 fuse_buckets: bool = True,
                 max_bucket_bytes: Optional[int] = None, **hypers):
        self.hypers: Dict[str, Any] = dict(self.defaults)
        unknown = set(hypers) - set(self.hypers)
        if unknown:
            raise TypeError(f"unexpected arguments {sorted(unknown)}")
        self.hypers.update(hypers)
        if masters is not None:
            # externally-sourced masters (amp.initialize's copies made
            # from the ORIGINAL f32 init — upcasting the rounded half
            # params here would lose the low bits, apex O2 contract)
            if master_weights is False:
                raise ValueError(
                    "masters= provided together with "
                    "master_weights=False — contradictory")
            if not _is_low_precision(params):
                raise ValueError(
                    "masters= provided but params are not low-precision"
                    " — masters only apply to half-precision params")
            if (jax.tree_util.tree_structure(masters)
                    != jax.tree_util.tree_structure(params)):
                raise ValueError(
                    "masters pytree structure does not match params")
            master_weights = True
        if master_weights is None:
            master_weights = _is_low_precision(params)
        self.master_weights = master_weights and _is_low_precision(params)
        if not self.master_weights:
            masters = None
        else:
            masters = tree_map(
                lambda x: x.astype(jnp.float32)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                masters if masters is not None else params)
        work = masters if masters is not None else params

        # ---- bucket plan (tentpole): one-time packing layout --------------
        # max_bucket_bytes: optional chunking cap — multiple buckets
        # per dtype group so the DDP collectives become per-chunk and
        # schedulable under the remaining backward (docs/perf.md
        # "Overlap schedule"); None keeps the maximal-fusion default
        self._plan = (BucketPlan.from_tree(
            work, params if masters is not None else None,
            max_bucket_bytes=max_bucket_bytes)
            if fuse_buckets else None)
        self.fuse_buckets = self._plan is not None
        self._params_tree = None
        self._masters_tree = None
        self._params_cache = None
        self._masters_cache = None
        if self._plan is not None:
            self._param_bufs = self._plan.pack_model(params)
            self._master_bufs = (self._plan.pack_work(masters)
                                 if masters is not None else None)
            self._params_cache = params
            self._masters_cache = masters
            self._unpack_model_jit = jax.jit(self._plan.unpack_model)
            self._unpack_work_jit = jax.jit(self._plan.unpack)
            self.opt_state = self.init_state_packed(self._plan, work)
            self._full_step_impl = self._full_step_flat
        else:
            self._params_tree = params
            self._masters_tree = masters
            self.opt_state = self.init_state(work)
            self._full_step_impl = self._full_step
        self.step_count = jnp.int32(0)
        # Host-offloaded optimizer state (beyond-reference; the HBM
        # relief the reference gets from ZeRO sharding alone).  On TPU
        # the step is ONE program: state transfers in from pinned host,
        # math runs on device, out_shardings land the new state back on
        # host (XLA overlaps the DMAs with compute).  Bucketed state
        # offloads as WHOLE flat buffers — a handful of large DMAs
        # instead of one per leaf.  Elsewhere (CPU CI) the in-jit
        # placement custom call doesn't exist, so step() moves the
        # state eagerly around a plain device step.
        self.offload_state = offload_state
        self._fused_offload = False
        if offload_state:
            from apex_tpu.ops._dispatch import on_tpu
            self.opt_state = place_on_host(self.opt_state)
            self._fused_offload = on_tpu()
            if self._fused_offload:
                # no donation: the state crosses memory kinds
                # (pinned_host in, device math, pinned_host out) and
                # donating across spaces is not aliasable anyway
                self._jit_step = jax.jit(  # apexlint: disable=APX401
                    self._full_step_offload,
                    out_shardings=(None, None,
                                   tree_map(_host_sharding,
                                            self.opt_state)))
            else:
                self._jit_step = jax.jit(self._full_step_impl,
                                         donate_argnums=(2,))
        else:
            self._jit_step = jax.jit(self._full_step_impl,
                                     donate_argnums=(2,))

    # ---- packed views ----------------------------------------------------
    @property
    def params(self) -> Pytree:
        """The current params pytree.  On the bucketed path this unpacks
        lazily — ONE compiled slice-and-reshape program per step, cached
        until the next step — so the packed buffers stay the canonical
        representation."""
        if self._plan is None:
            return self._params_tree
        if self._params_cache is None:
            self._params_cache = self._unpack_model_jit(self._param_bufs)
        return self._params_cache

    @params.setter
    def params(self, value: Pytree):
        if self._plan is None:
            self._params_tree = value
        else:
            self._param_bufs = self._plan.pack_model(value)
            self._params_cache = value

    @property
    def masters(self) -> Optional[Pytree]:
        if self._plan is None:
            return self._masters_tree
        if self._master_bufs is None:
            return None
        if self._masters_cache is None:
            self._masters_cache = self._unpack_work_jit(self._master_bufs)
        return self._masters_cache

    @masters.setter
    def masters(self, value: Optional[Pytree]):
        if self._plan is None:
            self._masters_tree = value
        elif value is None:
            self._master_bufs = None
            self._masters_cache = None
        else:
            self._master_bufs = self._plan.pack_work(value)
            self._masters_cache = value

    # ---- functional core -------------------------------------------------
    def init_state(self, params: Pytree) -> Pytree:
        raise NotImplementedError

    def init_state_packed(self, plan: BucketPlan, work: Pytree) -> Pytree:
        """Packed optimizer state: each field of the per-leaf state,
        bucket-packed (param-shaped fields -> flat buffers; per-tensor
        scalar fields -> one (num leaves,) vector per bucket)."""
        state = self.init_state(work)
        return {k: plan.pack_state_field(v) for k, v in state.items()}

    def _step_math(self, params, grads, opt_state, step, grad_scale, hypers):
        """Pure per-leaf update on the (possibly master) params."""
        raise NotImplementedError

    def _flat_bucket_step(self, bucket_index: int, p, g, state, step,
                          grad_scale, hypers, extra):
        """One bucket's flat-kernel update: ``p``/``g`` are flat buffers,
        ``state`` maps field name -> this bucket's buffer.  Returns
        (new_p, new_state).  ``extra`` is whatever ``_flat_prologue``
        returned (e.g. LAMB's global-norm clip coefficient)."""
        raise NotImplementedError

    def _flat_prologue(self, work_bufs, grad_bufs, step, grad_scale,
                       hypers):
        """Cross-bucket prologue for the flat path (default: nothing)."""
        return None

    def _flat_step_math(self, work_bufs, grad_bufs, opt_state, step,
                        grad_scale, hypers):
        # fp8 delayed-scaling slots are carried state, not optimizer
        # math: split them out of the per-bucket loop and update them
        # from the POST-step work buffers below (delayed scaling: the
        # scale the next forward quantizes with reflects this step's
        # weights)
        fp8_state = {k: opt_state[k] for k in _FP8_SLOTS
                     if k in opt_state}
        core = {k: v for k, v in opt_state.items()
                if k not in fp8_state}
        extra = self._flat_prologue(work_bufs, grad_bufs, step,
                                    grad_scale, hypers)
        new_bufs: List[Any] = []
        new_state: Dict[str, List[Any]] = {k: [] for k in core}
        for bi, (p, g) in enumerate(zip(work_bufs, grad_bufs)):
            bucket_state = {k: v[bi] for k, v in core.items()}
            np_, ns = self._flat_bucket_step(
                bi, p, g, bucket_state, step, grad_scale, hypers, extra)
            new_bufs.append(np_)
            for k in new_state:
                new_state[k].append(ns[k])
        if fp8_state:
            new_state.update(self._fp8_slot_update(new_bufs, fp8_state,
                                                   step))
        return new_bufs, new_state

    def _fp8_slot_update(self, new_work_bufs, fp8_state, step):
        """The packed fp8 weight-scale slots' delayed-scaling
        transition over the post-step work buffers, riding the step's
        own jit and donation — the same shared per-bucket pass as the
        pipeline's gradient-side state (``amp.fp8.update_packed``),
        gated by the step clock instead of an Fp8State counter (a
        skipped step's held clock therefore also holds the fp8
        cadence)."""
        from apex_tpu.amp.fp8 import update_packed
        policy = getattr(self, "fp8_policy", None)
        if policy is None:              # foreign slots: carry through
            return fp8_state
        do = jnp.equal(jnp.asarray(step, jnp.int32)
                       % jnp.int32(policy.interval), 0)
        hist, scale, _ = update_packed(
            fp8_state["fp8_amax_history"], fp8_state["fp8_scale"],
            new_work_bufs, self._plan, policy, update=do,
            scale_min_metric="fp8/weight_scale_min")
        return {"fp8_amax_history": hist, "fp8_scale": scale}

    # ---- fp8 delayed-scaling slots ---------------------------------------
    def enable_fp8(self, policy=None) -> None:
        """Attach packed fp8 delayed-scaling state for the WEIGHTS as
        optimizer slots (``fp8_amax_history``: (n_leaves, H) per
        bucket; ``fp8_scale``: (n_leaves,) per bucket) — donated to
        the jitted step, offloaded, checkpointed (v1 and v2) and
        re-chunked like every other slot.  The step updates them from
        the post-update work buffers; read the current per-leaf
        scales with :meth:`fp8_scales` and feed them to
        ``fused_dense.fp8_matmul(w_scale=...)``.  Requires the
        bucketed path."""
        if self._plan is None:
            raise ValueError(
                "enable_fp8 requires the bucketed path "
                "(fuse_buckets=False or the packer declined this "
                "tree)")
        from apex_tpu.amp.fp8 import Fp8Policy, init_state
        if policy is None:
            policy = Fp8Policy()
        self.fp8_policy = policy
        st = init_state(self._plan, policy)
        slots = {"fp8_amax_history": list(st.amax_history),
                 "fp8_scale": list(st.scale)}
        if self.offload_state:
            slots = place_on_host(slots)
        # a new opt_state STRUCTURE: the jitted step re-traces on the
        # next call (jit keys on pytree structure), no re-jit needed
        self.opt_state = {**self.opt_state, **slots}

    def fp8_scales(self, opt_state=None) -> Pytree:
        """Per-leaf pytree of the current fp8 weight scales (scalar
        slices of the packed slot — they fuse into the caller's jit).
        Pass the ``opt_state`` threaded through an embedded
        ``functional_step`` loop, or omit it for the stateful
        facade's own state."""
        if self._plan is None or not hasattr(self, "fp8_policy"):
            raise ValueError("enable_fp8 was not called")
        state = self.opt_state if opt_state is None else opt_state
        from apex_tpu.amp.fp8 import Fp8State, scales_tree
        st = Fp8State(amax_history=list(state["fp8_amax_history"]),
                      scale=list(state["fp8_scale"]),
                      step=self.step_count)
        return scales_tree(self._plan, st)

    def _full_step(self, params, masters, opt_state, grads, step, grad_scale,
                   hypers, found_inf=None):
        work = masters if masters is not None else params
        new_work, new_state = self._step_math(
            work, grads, opt_state, step, grad_scale, hypers)
        if found_inf is not None:
            new_work, new_state = _skip_on_overflow(
                found_inf, new_work, work, new_state, opt_state)
        if masters is not None:
            new_params = tree_map(lambda p, m: m.astype(p.dtype)
                                  if jnp.issubdtype(p.dtype, jnp.floating)
                                  else m, params, new_work)
            return new_params, new_work, new_state
        return new_work, None, new_state

    def _full_step_flat(self, param_bufs, master_bufs, opt_state, grads,
                        step, grad_scale, hypers, found_inf=None):
        """Bucketed step body: grads pack (one concatenate per bucket)
        — or arrive ALREADY packed from the flat AMP pipeline, in which
        case zero pack work happens here — then ONE flat kernel chain
        per bucket; params/masters/state go in and come out packed."""
        work_bufs = master_bufs if master_bufs is not None else param_bufs
        grad_bufs = (list(grads) if self._plan.is_packed(grads)
                     else self._plan.pack(grads))
        new_work, new_state = self._flat_step_math(
            work_bufs, grad_bufs, opt_state, step, grad_scale, hypers)
        if found_inf is not None:
            new_work, new_state = _skip_on_overflow(
                found_inf, new_work, work_bufs, new_state, opt_state)
        if master_bufs is not None:
            new_params = [w.astype(b.model_dtype) for w, b in
                          zip(new_work, self._plan.buckets)]
            return new_params, new_work, new_state
        return new_work, None, new_state

    def _full_step_offload(self, params, masters, opt_state, grads, step,
                           grad_scale, hypers, found_inf=None):
        """TPU fused-offload step body: pull state from pinned host at
        the top (whole flat buffers on the bucketed path); out_shardings
        push the new state back."""
        opt_state = tree_map(
            lambda x: jax.device_put(x, _DEVICE_MEMORY), opt_state)
        return self._full_step_impl(params, masters, opt_state, grads,
                                    step, grad_scale, hypers, found_inf)

    def _state_is_packed(self, opt_state) -> bool:
        """True only for the plan's OWN packed layout: every field is a
        per-bucket list whose buffers structurally match the plan (1-D,
        bucket-sized flat or per-leaf-scalar vector).  A per-leaf state
        pytree that merely happens to be a list of the right length
        (e.g. list-shaped params) must not be mistaken for packed."""
        if self._plan is None or not isinstance(opt_state, dict) \
                or not opt_state:
            return False
        buckets = self._plan.buckets
        for field in opt_state.values():
            if not isinstance(field, (list, tuple)) \
                    or len(field) != len(buckets):
                return False
            for buf, b in zip(field, buckets):
                if getattr(buf, "ndim", None) == 2 \
                        and buf.shape[0] == len(b.leaves):
                    continue    # row-stacked per-leaf vectors (fp8)
                if getattr(buf, "ndim", None) != 1:
                    return False
                if tuple(buf.shape) not in ((b.size,), (len(b.leaves),)):
                    return False
        return True

    def functional_step(self, params, opt_state, grads, step,
                        grad_scale=1.0, clip_coef=None, found_inf=None):
        """Embed-in-your-own-jit entry point (no master handling).

        ``params``/``grads`` are pytrees; ``opt_state`` may be either a
        per-leaf state pytree (per-leaf math runs) or this optimizer's
        PACKED state (e.g. ``opt.opt_state`` of a bucketed optimizer) —
        then the flat bucket kernels run, the new state comes back
        packed, and the new params come back as a pytree (what a train
        step's model apply needs anyway; the repack/unpack concatenates
        and slices fuse into the caller's jit).  With packed state,
        ``grads`` may also arrive as the plan's per-bucket flat buffers
        (the flat AMP pipeline's layout) — no pack happens then — or as
        an ``amp.FlatGrads`` bundle, whose ``found_inf``/``clip_coef``
        apply unless overridden explicitly (``step()`` parity).

        ``clip_coef``: optional traced global-norm clip coefficient
        (e.g. ``FlatGrads.clip_coef``); folded into the kernels' grad
        scaling, so clipping never materializes a gradient copy.

        ``found_inf``: optional on-device overflow flag; when nonzero,
        params and state come back unchanged (branch-free skip — the
        caller owns the step clock and should likewise not advance it
        on a skipped step, as ``step()`` does)."""
        packed = self._state_is_packed(opt_state)
        if hasattr(grads, "bufs") and hasattr(grads, "found_inf"):
            # amp.FlatGrads (duck-typed, as in step())
            if not packed:
                raise ValueError(
                    "FlatGrads require the bucketed path — this call "
                    "runs per-leaf state; pass a gradient pytree "
                    "instead")
            if found_inf is None:
                found_inf = grads.found_inf
            if clip_coef is None:
                clip_coef = getattr(grads, "clip_coef", None)
            grads = grads.bufs
        gs = _fold_clip(grad_scale, clip_coef)
        hypers = dict(self.hypers)
        if packed:
            work_bufs = self._plan.pack_work(params)
            grad_bufs = (list(grads) if self._plan.is_packed(grads)
                         else self._plan.pack(grads))
            new_bufs, new_state = self._flat_step_math(
                work_bufs, grad_bufs, opt_state, step, gs, hypers)
            if found_inf is not None:
                new_bufs, new_state = _skip_on_overflow(
                    found_inf, new_bufs, work_bufs, new_state, opt_state)
            return self._plan.unpack(new_bufs), new_state
        new_params, new_state = self._step_math(
            params, grads, opt_state, step, gs, hypers)
        if found_inf is not None:
            new_params, new_state = _skip_on_overflow(
                found_inf, new_params, params, new_state, opt_state)
        return new_params, new_state

    # ---- stateful facade -------------------------------------------------
    def step(self, grads: Pytree, grad_scale=1.0, found_inf=None,
             clip_coef=None) -> Pytree:
        """Apply one update; returns (and stores) the new params.

        ``grads`` may be the usual pytree, the plan's per-bucket flat
        buffers (the flat AMP pipeline's pack-once layout — no re-pack
        happens), or an ``amp.FlatGrads`` bundle, whose ``found_inf``
        and ``clip_coef`` are used unless overridden explicitly.

        ``found_inf``: optional on-device i32/bool scalar (amp's overflow
        flag from ``scaled_value_and_grad`` or ``flat_scale``).  When
        given and nonzero, params/masters/state keep their old values
        and the step count does not advance — a branch-free skip, never
        a host sync.

        ``clip_coef``: optional traced global-norm clip coefficient in
        (0, 1]; folded into the kernels' grad scaling (see
        ``_fold_clip``) so clipping costs zero extra gradient passes."""
        if hasattr(grads, "bufs") and hasattr(grads, "found_inf"):
            # amp.FlatGrads (duck-typed: amp must stay import-light here)
            if self._plan is None:
                raise ValueError(
                    "FlatGrads/packed gradients require the bucketed "
                    "path — this optimizer runs per-leaf "
                    "(fuse_buckets=False or the packer declined its "
                    "tree); pass a gradient pytree instead")
            if found_inf is None:
                found_inf = grads.found_inf
            if clip_coef is None:
                clip_coef = getattr(grads, "clip_coef", None)
            grads = grads.bufs
        grad_scale = _fold_clip(grad_scale, clip_coef)
        self.step_count = self.step_count + 1
        state = self.opt_state
        eager_offload = self.offload_state and not self._fused_offload
        if eager_offload:   # CPU fallback: explicit round trip
            state = place_on_device(state)
        traced_hypers = {
            k: jnp.asarray(v, jnp.float32) if isinstance(v, float) else v
            for k, v in self.hypers.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}
        if self._plan is not None:
            self._param_bufs, self._master_bufs, self.opt_state = \
                self._jit_step(self._param_bufs, self._master_bufs, state,
                               grads, self.step_count,
                               jnp.asarray(grad_scale, jnp.float32),
                               traced_hypers, found_inf)
            self._params_cache = None
            self._masters_cache = None
        else:
            self._params_tree, self._masters_tree, self.opt_state = \
                self._jit_step(self._params_tree, self._masters_tree, state,
                               grads, self.step_count,
                               jnp.asarray(grad_scale, jnp.float32),
                               traced_hypers, found_inf)
        if eager_offload:
            self.opt_state = place_on_host(self.opt_state)
        if found_inf is not None:
            # a skipped step must not advance the bias-correction clock
            self.step_count = jnp.where(jnp.asarray(found_inf) > 0,
                                        self.step_count - 1,
                                        self.step_count)
        return self.params

    def zero_grad(self):
        """No-op for parity: JAX grads are freshly computed, never stored."""

    def grad_accum_init(self):
        """Fresh zeroed microbatch gradient-accumulation state in this
        optimizer's bucket layout (``amp.GradAccum``): per-bucket f32
        accumulator buffers + the cross-microbatch found_inf latch +
        the microbatch count.  Thread it through
        ``FlatGradPipeline.accumulate()`` per microbatch and hand the
        ``finalize()`` result to ``step(flat, found_inf=...)`` — a
        latched overflow skips the whole committed step and holds the
        step clock, exactly like a single-batch overflow.  Requires
        the bucketed path (the accumulators ARE bucket buffers)."""
        if self._plan is None:
            raise ValueError(
                "grad_accum_init requires the bucketed path "
                "(fuse_buckets=False or the packer declined this "
                "tree); accumulate per leaf with "
                "amp.scaled_value_and_grad(microbatches=N) instead")
        from apex_tpu.amp.flat_pipeline import GradAccum
        return GradAccum.zeros(self._plan)

    # ---- elastic re-chunking (fleet resize) ------------------------------
    def rechunk(self, max_bucket_bytes) -> bool:
        """Rebuild the :class:`BucketPlan` with a new
        ``max_bucket_bytes`` chunking cap and repack the LIVE training
        state (params, masters, every optimizer-state field) into the
        new layout.

        The elastic-resize hook: when the per-host HBM share changes
        because the fleet grew or shrank (``run_elastic``'s
        ``grow_max_bucket_bytes=``), the overlap schedule's chunk size
        should track it (docs/perf.md).  Chunk boundaries always fall
        on leaf boundaries, so the update math is bit-identical across
        layouts — only the packing changes (the chunked-vs-monolithic
        equivalence the overlap schedule already pins).  One eager
        per-leaf unpack + repack per resize — a rare event by
        construction.  Offloaded state round-trips through device for
        the repack and lands back on host.  Callers holding a
        ``FlatGradPipeline`` bound to the old plan must rebuild it
        (the pipeline snapshots the plan at construction).  Returns
        False (no-op) when the cap already matches."""
        if self._plan is None:
            raise RuntimeError(
                "rechunk requires the bucketed path (fuse_buckets="
                "True and a tree the packer accepted)")
        if max_bucket_bytes == self._plan.max_bucket_bytes:
            return False
        params = self.params              # cached lazy unpack
        masters = self.masters
        state = self.opt_state
        if self.offload_state:
            state = place_on_device(state)
        state_trees = {k: self._plan.unpack_state_field(v)
                       for k, v in state.items()}
        work = masters if masters is not None else params
        self._plan = BucketPlan.from_tree(
            work, params if masters is not None else None,
            max_bucket_bytes=max_bucket_bytes)
        self._param_bufs = self._plan.pack_model(params)
        self._master_bufs = (self._plan.pack_work(masters)
                             if masters is not None else None)
        self._params_cache = params
        self._masters_cache = masters
        self._unpack_model_jit = jax.jit(self._plan.unpack_model)
        self._unpack_work_jit = jax.jit(self._plan.unpack)
        self.opt_state = {k: self._plan.pack_state_field(v)
                          for k, v in state_trees.items()}
        if self.offload_state:
            self.opt_state = place_on_host(self.opt_state)
        # fresh jit: the step body closes over the plan
        if self._fused_offload:
            # no donation: the state crosses memory kinds (__init__)
            self._jit_step = jax.jit(  # apexlint: disable=APX401
                self._full_step_offload,
                out_shardings=(None, None,
                               tree_map(_host_sharding,
                                        self.opt_state)))
        else:
            self._jit_step = jax.jit(self._full_step_impl,
                                     donate_argnums=(2,))
        return True

    # ---- bucket-native checkpoint capture --------------------------------
    def packed_snapshot(self):
        """Checkpoint capture that NEVER unpacks: one async device-side
        copy per packed buffer (params, masters, every optimizer-state
        field), plus host scalars — the bucket-native checkpoint v2
        input (``checkpoint.save_training_state`` routes here when the
        optimizer runs bucketed).

        The copies are the double-buffer: the caller's next ``step()``
        donates ``opt_state`` (and rebinds the param buffers), so an
        in-flight device->host transfer must read from buffers the step
        cannot delete.  ``plan.unpack`` is never called — the whole
        point of the format (ISSUE 6 acceptance: zero per-leaf work).

        Returns ``{"step", "hypers", "plan", "param_bufs",
        "master_bufs", "state"}`` with jax-array buffer lists.  Raises
        ``ValueError`` on a per-leaf optimizer — callers fall back to
        ``state_dict()`` / the v1 format there."""
        if self._plan is None:
            raise ValueError(
                "packed_snapshot requires the bucketed path "
                "(fuse_buckets=False or the packer declined this tree);"
                " use state_dict() / the v1 checkpoint format instead")
        # offloaded state copies IN PLACE on the host (buf.copy()
        # preserves placement; the "d2h" later is a plain host memcpy)
        # — pulling it into HBM first would allocate the very
        # state-size the offload exists to avoid
        state = self.opt_state
        return {
            "step": int(self.step_count),
            "hypers": dict(self.hypers),
            "plan": self._plan,
            "param_bufs": [_device_copy(b) for b in self._param_bufs],
            "master_bufs": ([_device_copy(b) for b in self._master_bufs]
                            if self._master_bufs is not None else None),
            "state": {k: [_device_copy(b) for b in v]
                      for k, v in state.items()},
        }

    def load_packed_snapshot(self, step, hypers, param_bufs, master_bufs,
                             state):
        """Inverse of :meth:`packed_snapshot` — adopt packed buffers
        directly (one host->device put per bucket, zero per-leaf
        traffic).  Buffers may be numpy (fresh from a checkpoint read)
        or jax arrays; the caller has already validated the layout
        against this optimizer's plan (checkpoint.py compares the v2
        header's plan doc with ``self._plan.layout()``)."""
        if self._plan is None:
            raise ValueError(
                "load_packed_snapshot requires the bucketed path")
        self.step_count = jnp.int32(step)
        self.hypers.update(hypers)
        self._param_bufs = [jnp.asarray(b) for b in param_bufs]
        if master_bufs is not None:
            self._master_bufs = [jnp.asarray(b) for b in master_bufs]
        else:
            self._master_bufs = None
        self._params_cache = None
        self._masters_cache = None
        # the v2 payload stores every state buffer flattened; a
        # non-flat slot (the fp8 (n_leaves, H) amax history) adopts the
        # LIVE slot's shape back — same element count, the layout
        # check upstream already matched the plan
        old = self.opt_state

        def _shaped(b, o):
            # metadata-only reshape (numpy and jax alike): never a
            # copy, never an extra device placement
            want = (tuple(o.shape)
                    if o is not None and hasattr(o, "shape") else None)
            if want is not None \
                    and tuple(getattr(b, "shape", ())) != want \
                    and getattr(b, "size", None) == o.size:
                b = b.reshape(want)
            return b

        if self.offload_state:
            # adopt each buffer straight onto the existing (host)
            # placement — asarray-then-place_on_host would stage the
            # whole state in HBM, the state-size spike offloading
            # exists to avoid (the load_state_dict mirror of the
            # packed_snapshot in-place rule)
            self.opt_state = {
                k: [jax.device_put(_shaped(b, o), o.sharding)
                    for b, o in zip(v, old[k])]
                for k, v in state.items()}
        else:
            self.opt_state = {
                k: [jnp.asarray(_shaped(b, o))
                    for b, o in zip(v, old.get(k, [None] * len(v)))]
                for k, v in state.items()}

    # ---- serialization (torch Optimizer.state_dict shape) ---------------
    def state_dict(self):
        if self._plan is not None:
            # unpack to the per-leaf checkpoint layout (unchanged across
            # packing, so per-leaf and bucketed optimizers interload).
            # The slices are fresh buffers — safe against the next
            # step()'s donation of the packed state.
            state = self.opt_state
            if self.offload_state:
                state = place_on_device(state)
            state_tree = {k: self._plan.unpack_state_field(v)
                          for k, v in state.items()}
            return {
                "step": int(self.step_count),
                "hypers": dict(self.hypers),
                "state": state_tree,
                "masters": self.masters,
            }
        # copy the state out: the next step() DONATES self.opt_state to
        # the compiled update, which deletes the buffers a by-reference
        # snapshot would still point at ("Array has been deleted" at
        # serialization time)
        return {
            "step": int(self.step_count),
            "hypers": dict(self.hypers),
            "state": tree_map(
                lambda x: jnp.array(x, copy=True)
                if isinstance(x, jax.Array) else x, self.opt_state),
            "masters": self.masters,
        }

    def load_state_dict(self, sd):
        self.step_count = jnp.int32(sd["step"])
        self.hypers.update(sd["hypers"])
        if self._plan is not None:
            # per-leaf checkpoint layout -> packed buffers (the pack
            # concatenates, so the checkpoint dict is never aliased by
            # the donating step)
            self.opt_state = {k: self._plan.pack_state_field(v)
                              for k, v in sd["state"].items()}
        else:
            # copy: step() donates opt_state to the compiled update, and
            # the caller's checkpoint dict must stay readable after we
            # step
            self.opt_state = tree_map(
                lambda x: jnp.array(x, copy=True)
                if isinstance(x, jax.Array) else x, sd["state"])
        if self.offload_state:
            # restore must respect the host-residency invariant NOW —
            # waiting for the next step to re-home it would leave the
            # full f32 state in HBM at exactly the tight-memory moment
            # offloading exists for
            self.opt_state = place_on_host(self.opt_state)
        if sd.get("masters") is not None:
            self.masters = sd["masters"]

    # hyper access in the torch param_group idiom: opt.lr = ...
    @property
    def lr(self):
        return self.hypers["lr"]

    @lr.setter
    def lr(self, value):
        self.hypers["lr"] = value

    def _merge_hypers(self, traced_hypers):
        """Traced float hypers override statics inside the jitted step."""
        merged = dict(self.hypers)
        merged.update(traced_hypers)
        return merged
