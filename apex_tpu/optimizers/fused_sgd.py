"""FusedSGD (reference: apex/optimizers/fused_sgd.py).

torch.optim.SGD semantics (momentum / dampening / nesterov / weight
decay) as one fused pytree update; cf. csrc/multi_tensor_sgd_kernel.cu.

Flat AMP pipeline: ``step()`` takes already-packed per-bucket gradient
buffers and a traced ``clip_coef`` folded into ``flat_sgd``'s in-kernel
``inv_scale`` (optimizers/_base._fold_clip) — no per-leaf clip pass.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import _functional as F
from apex_tpu.optimizers._base import FusedOptimizerBase, tree_map, unzip_tree


class FusedSGD(FusedOptimizerBase):
    defaults = dict(lr=1e-3, momentum=0.0, dampening=0.0, weight_decay=0.0,
                    nesterov=False, wd_after_momentum=False,
                    materialize_master_grads=True, set_grad_none=False)

    def __init__(self, params, **kw):
        if kw.get("nesterov") and (
                kw.get("momentum", 0.0) <= 0 or kw.get("dampening", 0.0) != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        super().__init__(params, **kw)

    def init_state(self, params):
        return {"momentum_buffer":
                tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)}

    def _step_math(self, params, grads, opt_state, step, grad_scale, hypers):
        h = self._merge_hypers(hypers)
        first = step == 1

        def leaf(p, g, buf):
            return F.sgd_step(
                p, g, buf, lr=h["lr"],
                momentum=self.hypers["momentum"],
                dampening=self.hypers["dampening"],
                weight_decay=h["weight_decay"],
                nesterov=self.hypers["nesterov"],
                first_run=first, grad_scale=grad_scale)

        out = tree_map(leaf, params, grads, opt_state["momentum_buffer"])
        new_p, new_b = unzip_tree(params, out, 2)
        return new_p, {"momentum_buffer": new_b}

    def _flat_bucket_step(self, bucket_index, p, g, state, step, grad_scale,
                          hypers, extra):
        h = self._merge_hypers(hypers)
        po, bo = mt.flat_sgd(
            p, g, state["momentum_buffer"], lr=h["lr"],
            momentum=self.hypers["momentum"],
            dampening=self.hypers["dampening"],
            weight_decay=h["weight_decay"],
            nesterov=self.hypers["nesterov"],
            first_run=step == 1, grad_scale=grad_scale)
        return po, {"momentum_buffer": bo}
