"""Per-leaf optimizer math shared by all fused optimizer facades.

Reference kernels: csrc/multi_tensor_{adam,sgd,lamb,novograd,adagrad}.cu
(SURVEY.md §2.4).  TPU-first note: the reference's "multi tensor" design
amortizes CUDA launch overhead by fusing thousands of small tensors into
one launch.  Under XLA a whole-pytree update traced in ONE jit already
compiles to a handful of fused elementwise loops, so the canonical path
here is per-leaf jnp math (bandwidth-bound, fully fused); the Pallas
flat-buffer kernels in apex_tpu.ops.multi_tensor remain available via
``fused=True`` on the facades for extreme leaf counts.

All math accumulates in f32 regardless of storage dtype; master-weight
handling keeps f32 params alongside bf16 model params (reference O2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

tree_map = jax.tree_util.tree_map


def _f32(x):
    return x.astype(jnp.float32)


def global_grad_norm(grads) -> jax.Array:
    """Global L2 norm across a pytree (reference: multi_tensor_l2norm)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(_f32(g) ** 2) for g in leaves))


def adam_step(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
              adam_w_mode=True, bias_correction=True, grad_scale=1.0):
    """One Adam/AdamW leaf update. Returns (p, m, v)."""
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    if not adam_w_mode:
        gf = gf + wd * pf
    m = b1 * m + (1 - b1) * gf
    v = b2 * v + (1 - b2) * gf * gf
    if bias_correction:
        t = jnp.asarray(step, jnp.float32)
        c1r = 1.0 / (1.0 - b1 ** t)
        c2r = 1.0 / (1.0 - b2 ** t)
    else:
        c1r = c2r = jnp.float32(1.0)
    update = (m * c1r) / (jnp.sqrt(v * c2r) + jnp.asarray(eps, jnp.float32))
    if adam_w_mode:
        update = update + wd * pf
    return (pf - jnp.asarray(lr, jnp.float32) * update).astype(p.dtype), m, v


def sgd_step(p, g, buf, *, lr, momentum=0.0, dampening=0.0,
             weight_decay=0.0, nesterov=False, first_run=False,
             grad_scale=1.0):
    """One SGD leaf update (torch.optim.SGD semantics). Returns (p, buf)."""
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    gf = gf + jnp.asarray(weight_decay, jnp.float32) * pf
    if momentum != 0.0:
        mom = jnp.asarray(momentum, jnp.float32)
        # first_run may be a traced bool: select instead of branching
        buf = jnp.where(
            first_run, gf,
            mom * buf + (1 - jnp.asarray(dampening, jnp.float32)) * gf)
        d = gf + mom * buf if nesterov else buf
    else:
        d = gf
    return (pf - jnp.asarray(lr, jnp.float32) * d).astype(p.dtype), buf


def lamb_step(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
              bias_correction=True, grad_scale=1.0, clip_coeff=1.0,
              use_nvlamb=False):
    """One LAMB leaf update (reference: multi_tensor_lamb stage1+stage2).

    ``clip_coeff`` is the precomputed global-grad-norm clip factor
    (stage-1 side input in the reference).  Trust ratio is per tensor:
    ||p|| / ||update||, guarded to 1 when either norm is 0.
    """
    pf = _f32(p)
    gf = _f32(g) * (jnp.asarray(clip_coeff, jnp.float32) /
                    jnp.asarray(grad_scale, jnp.float32))
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    m = b1 * m + (1 - b1) * gf
    v = b2 * v + (1 - b2) * gf * gf
    if bias_correction:
        t = jnp.asarray(step, jnp.float32)
        c1r = 1.0 / (1.0 - b1 ** t)
        c2r = 1.0 / (1.0 - b2 ** t)
    else:
        c1r = c2r = jnp.float32(1.0)
    update = (m * c1r) / (jnp.sqrt(v * c2r) + jnp.asarray(eps, jnp.float32))
    update = update + wd * pf
    p_norm = jnp.sqrt(jnp.sum(pf * pf))
    u_norm = jnp.sqrt(jnp.sum(update * update))
    trust = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0)
    if not use_nvlamb:
        # standard LAMB exempts decay-free tensors from layer adaptation;
        # NVLAMB (use_nvlamb=True) applies the trust ratio to every layer
        trust = jnp.where(wd == 0.0, jnp.float32(1.0), trust)
    return (pf - jnp.asarray(lr, jnp.float32) * trust * update
            ).astype(p.dtype), m, v


def novograd_step(p, g, m, v_scalar, *, lr, beta1, beta2, eps,
                  weight_decay, first_run=False, grad_averaging=True,
                  grad_scale=1.0, init_zero=False,
                  reg_inside_moment=False):
    """One NovoGrad leaf update (reference: multi_tensor_novograd.cu).

    ``v_scalar`` is the per-TENSOR second moment (a scalar).
    ``init_zero``: start v at 0 (first step uses (1-b2)*||g||^2) instead
    of seeding with the first gradient norm.  ``reg_inside_moment``:
    fold weight decay into the normalized gradient before the
    first-moment EMA; otherwise decay is applied outside the moment."""
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    g_norm_sq = jnp.sum(gf * gf)
    if init_zero:
        v_scalar = jnp.where(first_run, (1 - b2) * g_norm_sq,
                             b2 * v_scalar + (1 - b2) * g_norm_sq)
    else:
        v_scalar = jnp.where(first_run, g_norm_sq,
                             b2 * v_scalar + (1 - b2) * g_norm_sq)
    denom = jnp.sqrt(v_scalar) + jnp.asarray(eps, jnp.float32)
    gn = gf / denom
    if reg_inside_moment:
        gn = gn + wd * pf
    coeff = (1 - b1) if grad_averaging else jnp.float32(1.0)
    m = jnp.where(first_run, gn, b1 * m + coeff * gn)
    update = m if reg_inside_moment else m + wd * pf
    return (pf - jnp.asarray(lr, jnp.float32) * update
            ).astype(p.dtype), m, v_scalar


def adagrad_step(p, g, h, *, lr, eps, weight_decay, grad_scale=1.0):
    """One Adagrad leaf update (reference: multi_tensor_adagrad.cu)."""
    pf = _f32(p)
    gf = _f32(g) / jnp.asarray(grad_scale, jnp.float32)
    gf = gf + jnp.asarray(weight_decay, jnp.float32) * pf
    h = h + gf * gf
    return (pf - jnp.asarray(lr, jnp.float32) * gf /
            (jnp.sqrt(h) + jnp.asarray(eps, jnp.float32))).astype(p.dtype), h
