"""FusedAdam (reference: apex/optimizers/fused_adam.py).

Adam/AdamW with the whole-pytree update traced into one jitted program
(XLA fuses it the way multi_tensor_adam.cu fused CUDA launches,
SURVEY.md §3.3).  ``adam_w_mode=True`` (default, as in the reference)
gives AdamW decoupled decay; ``capturable`` is accepted for parity and
ignored (every step is a compiled graph on TPU).

Flat AMP pipeline: ``step()`` accepts the bucket plan's per-bucket flat
gradient buffers (or an ``amp.FlatGrads`` bundle) plus a traced
``clip_coef`` — the clip folds into ``flat_adam``'s in-kernel
``inv_scale`` multiply, so a clipped step reads the gradients exactly
once (see optimizers/_base._fold_clip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import _functional as F
from apex_tpu.optimizers._base import FusedOptimizerBase, tree_map, unzip_tree


class FusedAdam(FusedOptimizerBase):
    defaults = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                    weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                    amsgrad=False, capturable=False, set_grad_none=True)

    def __init__(self, params, betas=None, **kw):
        if betas is not None:
            kw["beta1"], kw["beta2"] = betas
        if kw.pop("amsgrad", False):
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")  # reference raises identically
        super().__init__(params, **kw)

    def init_state(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {"exp_avg": tree_map(zeros, params),
                "exp_avg_sq": tree_map(zeros, params)}

    def _step_math(self, params, grads, opt_state, step, grad_scale, hypers):
        h = self._merge_hypers(hypers)

        def leaf(p, g, m, v):
            return F.adam_step(
                p, g, m, v, lr=h["lr"], beta1=h["beta1"], beta2=h["beta2"],
                eps=h["eps"], weight_decay=h["weight_decay"], step=step,
                adam_w_mode=self.hypers["adam_w_mode"],
                bias_correction=self.hypers["bias_correction"],
                grad_scale=grad_scale)

        out = tree_map(leaf, params, grads, opt_state["exp_avg"],
                       opt_state["exp_avg_sq"])
        new_p, new_m, new_v = unzip_tree(params, out, 3)
        return new_p, {"exp_avg": new_m, "exp_avg_sq": new_v}

    def _flat_bucket_step(self, bucket_index, p, g, state, step, grad_scale,
                          hypers, extra):
        h = self._merge_hypers(hypers)
        po, mo, vo = mt.flat_adam(
            p, g, state["exp_avg"], state["exp_avg_sq"],
            lr=h["lr"], beta1=h["beta1"], beta2=h["beta2"], eps=h["eps"],
            weight_decay=h["weight_decay"], step=step,
            adam_w_mode=self.hypers["adam_w_mode"],
            bias_correction=self.hypers["bias_correction"],
            grad_scale=grad_scale)
        return po, {"exp_avg": mo, "exp_avg_sq": vo}
