"""apex_tpu.models — model zoo backing the BASELINE configs.

The reference ships no models (it accelerates torchvision/Megatron
models); these TPU-first implementations exist so every BASELINE config
trains end-to-end inside this framework.
"""

from apex_tpu.models.resnet import (BasicBlock, Bottleneck, ResNet,
                                    resnet18, resnet34, resnet50,
                                    resnet101, resnet152)
from apex_tpu.models.gpt import GPTLayer, GPTModel, GPTStage
from apex_tpu.models.bert import (BertLayer, BertModel, bert_base,
                                  bert_large)

__all__ = [
    "BasicBlock", "Bottleneck", "ResNet",
    "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "GPTLayer", "GPTModel", "GPTStage",
    "BertLayer", "BertModel", "bert_base", "bert_large",
]
