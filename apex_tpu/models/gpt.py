"""GPT / Megatron-style causal transformer — the flagship model family
(reference context: BASELINE config 4 "GPT-2 block: contrib.multihead_attn
+ FusedAdam"; the reference ships no models, apex_tpu does so the configs
run end-to-end).

Megatron anatomy on the TPU mesh:
  - QKV/out-proj and MLP as Column/RowParallelLinear over the "model"
    axis (apex/transformer/tensor_parallel/layers.py semantics)
  - optional sequence parallelism: activations sharded on the seq dim
    between TP regions (all_gather into the col-linear, reduce_scatter
    out of the row-linear)
  - causal attention through the fused flash kernel
    (apex_tpu.ops.attention), RoPE optional
  - FusedLayerNorm in f32, residuals in compute dtype
  - vocab-parallel embedding + tied LM head + vocab-parallel CE

Layout is Megatron's (s, b, h) between layers; attention transposes to
(b, heads, s, d) for the kernel.  Works at tp=1 anywhere, tp>1 inside
shard_map.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from apex_tpu import comm
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.attention import (flash_attention,
                                    packed_segment_ids, ring_attention,
                                    ulysses_attention)
from apex_tpu.ops.rope import fused_apply_rotary_pos_emb
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.tensor_parallel import mappings


class GPTLayer(nn.Module):
    hidden_size: int
    num_heads: int
    ffn_hidden_size: Optional[int] = None
    sequence_parallel: bool = False
    use_rope: bool = False
    context_parallel: bool = False     # attention over the "ctx" axis
    cp_strategy: str = "ring"          # "ring" (ppermute) | "ulysses"
                                       # (all_to_all; local_heads % cp == 0)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, segment_ids=None, positions=None):
        """x: (s[, /tp if SP], b, h) -> same shape.

        segment_ids (b, s) / positions (b, s): packed-batch form
        (apex_tpu.data.pack_sequences) — attention masks across
        segments (disjoint padding ids per side, so padding rows
        output zeros) and RoPE rotates by within-sequence positions.
        BOTH or NEITHER: one-sided packing silently corrupts the
        other half (unmasked cross-segment attention, or every
        non-first segment rotated by its row offset).  Unsupported
        together with context_parallel (a packed row's segments would
        straddle ctx shards)."""
        if (segment_ids is None) != (positions is None):
            raise ValueError(
                "packed batches need BOTH segment_ids and positions "
                "(apex_tpu.data.pack_sequences emits both)")
        if segment_ids is not None and self.context_parallel:
            raise NotImplementedError(
                "packed segment_ids with context_parallel: split "
                "sequences across rows instead of packing, or drop cp")
        h = self.hidden_size
        ffn = self.ffn_hidden_size or 4 * h
        tp_size = comm.model_parallel_size()
        local_heads = self.num_heads // max(tp_size, 1)
        head_dim = h // self.num_heads

        ln1 = FusedLayerNorm(normalized_shape=h, name="input_layernorm",
                             sequence_parallel=self.sequence_parallel)
        qkv = tp.ColumnParallelLinear(
            h, 3 * h, gather_output=False,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="attn_qkv")
        proj = tp.RowParallelLinear(
            h, h, input_is_parallel=True,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="attn_proj")
        ln2 = FusedLayerNorm(normalized_shape=h,
                             name="post_attn_layernorm",
                             sequence_parallel=self.sequence_parallel)
        fc1 = tp.ColumnParallelLinear(
            h, ffn, gather_output=False,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="mlp_fc1")
        fc2 = tp.RowParallelLinear(
            ffn, h, input_is_parallel=True,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="mlp_fc2")

        # --- attention block ---
        y = ln1(x).astype(self.dtype)
        y = qkv(y)                                   # (s_full, b, 3h/tp)
        s_full, b = y.shape[0], y.shape[1]
        y = y.reshape(s_full, b, local_heads, 3 * head_dim)
        q, k, v = jnp.split(y, 3, axis=-1)

        def to_bhsd(t):
            return jnp.transpose(t, (1, 2, 0, 3))    # (b, lh, s, d)

        q, k, v = to_bhsd(q), to_bhsd(k), to_bhsd(v)
        if self.use_rope:
            inv = 1.0 / (10000.0 ** (
                jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
            if positions is not None:
                # packed: within-sequence positions, per row ->
                # freqs (s, b, 1, d) broadcasting over heads
                pos = jnp.transpose(positions, (1, 0)).astype(
                    jnp.float32)                        # (s, b)
                freqs = jnp.einsum("sb,d->sbd", pos, inv)
                freqs = jnp.concatenate([freqs, freqs], axis=-1)
                freqs = freqs[:, :, None, :]
            else:
                pos = jnp.arange(s_full, dtype=jnp.float32)
                if self.context_parallel:
                    # positions are GLOBAL: offset by this ctx
                    # shard's start (mirrors ring_attention's qpos)
                    pos = pos + (jax.lax.axis_index(comm.AXIS_CTX)
                                 * s_full).astype(jnp.float32)
                freqs = jnp.einsum("s,d->sd", pos, inv)
                freqs = jnp.concatenate([freqs, freqs], axis=-1)
                freqs = freqs[:, None, None, :]
            # rope expects (s, b, heads, d)
            def rope(t):
                t_sbhd = jnp.transpose(t, (2, 0, 1, 3))
                t_sbhd = fused_apply_rotary_pos_emb(t_sbhd, freqs)
                return jnp.transpose(t_sbhd, (1, 2, 0, 3))
            q, k = rope(q), rope(k)
        if self.context_parallel:
            if self.cp_strategy == "ulysses":
                attn = ulysses_attention(q, k, v, causal=True)
            elif self.cp_strategy == "ring":
                attn = ring_attention(q, k, v, causal=True)
            else:
                raise ValueError(
                    f"cp_strategy must be 'ring' or 'ulysses', got "
                    f"{self.cp_strategy!r}")
        elif segment_ids is not None:
            # disjoint pad ids per side (-1/-2): pad rows attend
            # nowhere and output exact zeros — convention single-
            # sourced in ops.attention.packed_segment_ids
            attn = flash_attention(q, k, v, causal=True,
                                   segment_ids=packed_segment_ids(
                                       segment_ids))
        else:
            attn = flash_attention(q, k, v, causal=True)
        attn = jnp.transpose(attn, (2, 0, 1, 3)).reshape(
            s_full, b, local_heads * head_dim)
        # offload tags (no-ops outside remat): the two largest
        # activations, usable with apex_tpu.offload.offload_checkpoint
        attn = checkpoint_name(attn, "attn_out")
        x = x + proj(attn).astype(x.dtype)

        # --- mlp block ---
        y = ln2(x).astype(self.dtype)
        y = checkpoint_name(jax.nn.gelu(fc1(y), approximate=True),
                            "ffn_hidden")
        x = x + fc2(y).astype(x.dtype)
        return x


class GPTStage(nn.Module):
    """A pipeline stage: k consecutive GPT layers (the stage_fn body for
    apex_tpu.transformer.pipeline_parallel.spmd)."""
    hidden_size: int
    num_heads: int
    num_layers: int
    ffn_hidden_size: Optional[int] = None
    sequence_parallel: bool = False
    use_rope: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, segment_ids=None, positions=None):
        for i in range(self.num_layers):
            x = GPTLayer(self.hidden_size, self.num_heads,
                         self.ffn_hidden_size,
                         sequence_parallel=self.sequence_parallel,
                         use_rope=self.use_rope, dtype=self.dtype,
                         name=f"layer_{i}")(x, segment_ids=segment_ids,
                                            positions=positions)
        return x


class GPTModel(nn.Module):
    """Full single-pipeline-stage GPT: embed -> layers -> ln -> tied head.

    __call__(tokens (b, s)) -> vocab-parallel logits (s, b, V/tp).
    ``loss(variables, tokens, labels)`` gives mean CE via the
    vocab-parallel loss.
    """
    vocab_size: int
    hidden_size: int
    num_heads: int
    num_layers: int
    max_seq_len: int = 2048
    ffn_hidden_size: Optional[int] = None
    sequence_parallel: bool = False
    use_rope: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, segment_ids=None, positions=None):
        """tokens (b, s) -> vocab-parallel logits (s, b, V/tp).

        segment_ids / positions (both (b, s)): packed-batch training
        (apex_tpu.data.pack_sequences) — position lookups use the
        within-sequence positions and attention is segment-masked;
        pad rows (segment 0) produce garbage logits to be masked in
        the loss (e.g. padding_idx labels)."""
        b, s = tokens.shape
        if positions is not None and s > self.max_seq_len:
            # the unpacked path fails loudly via broadcast shape
            # mismatch; the gather path would silently CLAMP
            # out-of-range positions to the table's last row
            raise ValueError(
                f"packed rows of length {s} exceed max_seq_len="
                f"{self.max_seq_len}; pack at max_len <= max_seq_len")
        embed = tp.VocabParallelEmbedding(self.vocab_size,
                                          self.hidden_size, name="embed")
        x = embed(tokens)                              # (b, s, h)
        if not self.use_rope:
            pos = self.param("pos_embedding",
                             nn.initializers.normal(0.02),
                             (self.max_seq_len, self.hidden_size),
                             jnp.float32)
            x = x + (pos[positions] if positions is not None
                     else pos[:s][None, :, :])
        x = jnp.transpose(x, (1, 0, 2))                # (s, b, h)
        if self.sequence_parallel:
            x = mappings.scatter_to_sequence_parallel_region(x)
        x = x.astype(self.dtype)
        for i in range(self.num_layers):
            x = GPTLayer(self.hidden_size, self.num_heads,
                         self.ffn_hidden_size,
                         sequence_parallel=self.sequence_parallel,
                         use_rope=self.use_rope, dtype=self.dtype,
                         name=f"layer_{i}")(x, segment_ids=segment_ids,
                                            positions=positions)
        # The head's d/dx from the LOCAL vocab shard is a partial sum
        # over tp ranks; exactly ONE f-mapping must sync it (Megatron's
        # parallel_lm_logits layout).  Under SP that role is played by
        # the sequence-region exit gather (bwd = reduce-scatter), with
        # the final LN INSIDE the region (its param grads synced by its
        # sequence_parallel flag); without SP it is an explicit copy_to
        # (fwd identity / bwd psum).
        x = FusedLayerNorm(normalized_shape=self.hidden_size,
                           name="final_layernorm",
                           sequence_parallel=self.sequence_parallel)(x)
        if self.sequence_parallel:
            x = mappings.gather_from_sequence_parallel_region(x)
        elif comm.model_parallel_size() > 1:
            x = mappings.copy_to_tensor_model_parallel_region(x)
        w = self.get_variable("params", "embed")["weight"]
        logits = jnp.dot(x.astype(self.dtype),
                         jnp.transpose(w).astype(self.dtype),
                         preferred_element_type=jnp.float32)
        return logits                                  # (s, b, V/tp) f32

    def loss(self, variables, tokens, labels, segment_ids=None,
             positions=None):
        """Mean CE; with packed inputs, two position classes are
        excluded from the mean: padding (segment 0), whose logits are
        garbage by contract, and each segment's FINAL position — with
        the documented shift-by-one label construction (labels[i] =
        tokens[i+1], docs/transformer.md) a packed segment's last
        token would otherwise train against the NEXT segment's first
        token.  Callers that already set an ignore label there lose
        nothing; callers that shifted naively are silently correct."""
        logits = self.apply(variables, tokens,
                            segment_ids=segment_ids,
                            positions=positions)       # (s, b, V/tp)
        labels_sb = jnp.transpose(labels, (1, 0))      # (s, b)
        per_tok = tp.vocab_parallel_cross_entropy(logits, labels_sb)
        if segment_ids is None:
            return jnp.mean(per_tok)
        seg_sb = jnp.transpose(segment_ids, (1, 0))    # (s, b)
        next_seg = jnp.concatenate(
            [seg_sb[1:], jnp.zeros_like(seg_sb[:1])], axis=0)
        keep = (seg_sb > 0) & (next_seg == seg_sb)
        return (jnp.sum(per_tok * keep)
                / jnp.maximum(jnp.sum(keep), 1))
