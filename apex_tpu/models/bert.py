"""BERT encoder family (reference context: BASELINE config 3 "BERT-Large
pretraining: FusedLAMB + FusedLayerNorm + contrib.xentropy"; the
reference ships no models — this exists so the config runs end-to-end).

Same TPU-first anatomy as GPT (tensor/sequence-parallel linears, fused
flash attention, f32 FusedLayerNorm) but bidirectional with a padding
mask, post-LN residuals (BERT convention), learned position + segment
embeddings, and an MLM head whose loss is the fused softmax-xentropy
(apex_tpu.contrib.xentropy).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import comm
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.attention import (attention_ref, flash_attention,
                                    packed_segment_ids)
from apex_tpu.transformer import tensor_parallel as tp


class BertLayer(nn.Module):
    hidden_size: int
    num_heads: int
    ffn_hidden_size: Optional[int] = None
    sequence_parallel: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, attn_mask=None, segment_ids=None):
        """x: (s, b, h); attn_mask: additive (b, 1, s, s) or None;
        segment_ids: (b, s) packed-batch form
        (apex_tpu.data.pack_sequences) routed through the flash
        kernel's segment masking — mutually exclusive with
        attn_mask."""
        if attn_mask is not None and segment_ids is not None:
            raise ValueError(
                "pass attn_mask OR segment_ids, not both (packed "
                "batches carry their mask in the segment ids)")
        h = self.hidden_size
        ffn = self.ffn_hidden_size or 4 * h
        tp_size = comm.model_parallel_size()
        local_heads = self.num_heads // max(tp_size, 1)
        head_dim = h // self.num_heads

        qkv = tp.ColumnParallelLinear(
            h, 3 * h, gather_output=False,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="attn_qkv")
        proj = tp.RowParallelLinear(
            h, h, input_is_parallel=True,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="attn_proj")
        ln1 = FusedLayerNorm(normalized_shape=h, name="attn_layernorm",
                             sequence_parallel=self.sequence_parallel)
        fc1 = tp.ColumnParallelLinear(
            h, ffn, gather_output=False,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="mlp_fc1")
        fc2 = tp.RowParallelLinear(
            ffn, h, input_is_parallel=True,
            sequence_parallel_enabled=self.sequence_parallel,
            compute_dtype=self.dtype, name="mlp_fc2")
        ln2 = FusedLayerNorm(normalized_shape=h, name="mlp_layernorm",
                             sequence_parallel=self.sequence_parallel)

        y = qkv(x.astype(self.dtype))
        s_full, b = y.shape[0], y.shape[1]
        y = y.reshape(s_full, b, local_heads, 3 * head_dim)
        q, k, v = jnp.split(y, 3, axis=-1)
        q, k, v = (jnp.transpose(t, (1, 2, 0, 3)) for t in (q, k, v))
        if segment_ids is not None:
            attn = flash_attention(q, k, v, False,
                                   segment_ids=packed_segment_ids(
                                       segment_ids))
        elif attn_mask is None:
            attn = flash_attention(q, k, v, False)
        else:
            attn = attention_ref(q, k, v, mask=attn_mask)
        attn = jnp.transpose(attn, (2, 0, 1, 3)).reshape(
            s_full, b, local_heads * head_dim)
        x = ln1(x + proj(attn).astype(x.dtype))
        y = jax.nn.gelu(fc1(x.astype(self.dtype)), approximate=True)
        x = ln2(x + fc2(y).astype(x.dtype))
        return x


class BertModel(nn.Module):
    vocab_size: int
    hidden_size: int
    num_heads: int
    num_layers: int
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dtype: jnp.dtype = jnp.float32
    sequence_parallel: bool = False

    @nn.compact
    def __call__(self, tokens, token_type_ids=None, attention_mask=None,
                 segment_ids=None, positions=None):
        """tokens: (b, s) -> sequence output (s, b, h).

        segment_ids / positions (both (b, s)): packed-batch form
        (apex_tpu.data.pack_sequences) — BOTH or NEITHER; position
        lookups use within-sequence positions and attention is
        segment-masked (pad rows garbage, mask downstream via
        segment_ids == 0).  Mutually exclusive with attention_mask.
        NOTE: BERT "token type" (sentence A/B) ids remain
        token_type_ids — unrelated to packing segment ids."""
        if (segment_ids is None) != (positions is None):
            raise ValueError(
                "packed batches need BOTH segment_ids and positions "
                "(apex_tpu.data.pack_sequences emits both)")
        if segment_ids is not None and attention_mask is not None:
            raise ValueError(
                "pass attention_mask OR segment_ids, not both")
        b, s = tokens.shape
        if positions is not None and s > self.max_seq_len:
            raise ValueError(
                f"packed rows of length {s} exceed max_seq_len="
                f"{self.max_seq_len}; pack at max_len <= max_seq_len")
        embed = tp.VocabParallelEmbedding(self.vocab_size,
                                          self.hidden_size, name="embed")
        x = embed(tokens)
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (self.max_seq_len, self.hidden_size), jnp.float32)
        x = x + (pos[positions] if positions is not None
                 else pos[:s][None, :, :])
        if token_type_ids is not None:
            seg = self.param("segment_embedding",
                             nn.initializers.normal(0.02),
                             (self.type_vocab_size, self.hidden_size),
                             jnp.float32)
            x = x + jnp.take(seg, token_type_ids, axis=0)
        x = FusedLayerNorm(normalized_shape=self.hidden_size,
                           name="embed_layernorm")(x)
        x = jnp.transpose(x, (1, 0, 2)).astype(self.dtype)   # (s, b, h)
        if self.sequence_parallel:
            x = tp.scatter_to_sequence_parallel_region(x)
        mask = None
        if attention_mask is not None:
            # (b, s) 1=keep -> additive (b, 1, 1, s)
            mask = (1.0 - attention_mask[:, None, None, :].astype(
                jnp.float32)) * -10000.0
        for i in range(self.num_layers):
            x = BertLayer(self.hidden_size, self.num_heads,
                          sequence_parallel=self.sequence_parallel,
                          dtype=self.dtype, name=f"layer_{i}")(
                x, mask, segment_ids=segment_ids)
        if self.sequence_parallel:
            x = tp.gather_from_sequence_parallel_region(x)
        return x

    def mlm_logits(self, variables, tokens, **kw):
        x = self.apply(variables, tokens, **kw)        # (s, b, h)
        # see GPTModel's head: exactly ONE f-mapping syncs d/dx of
        # the vocab-sharded head — under SP the encoder's exit gather
        # already is it (bwd reduce-scatter); without SP, copy_to
        if (comm.model_parallel_size() > 1
                and not self.sequence_parallel):
            x = tp.copy_to_tensor_model_parallel_region(x)
        w = variables["params"]["embed"]["weight"]
        return jnp.dot(x.astype(self.dtype),
                       jnp.transpose(w).astype(self.dtype),
                       preferred_element_type=jnp.float32)


def bert_large(**kw) -> BertModel:
    return BertModel(vocab_size=kw.pop("vocab_size", 30528),
                     hidden_size=1024, num_heads=16, num_layers=24, **kw)


def bert_base(**kw) -> BertModel:
    return BertModel(vocab_size=kw.pop("vocab_size", 30528),
                     hidden_size=768, num_heads=12, num_layers=12, **kw)
