"""ResNet family, TPU-first (reference model zoo context:
examples/imagenet/main_amp.py drives torchvision resnet18/50/101 — the
reference itself ships no models; these exist so the BASELINE configs
run end-to-end).

TPU-native choices: NHWC layout (XLA's preferred conv layout on TPU),
bf16 compute with f32 BatchNorm statistics (amp O2's keep_batchnorm_fp32
semantics), injectable norm_cls so convert-to-SyncBatchNorm is a
constructor argument rather than a tree rewrite.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Type

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    norm: ModuleDef = None
    dtype: jnp.dtype = jnp.float32
    expansion = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(x)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides))(x)
            residual = self.norm()(residual, use_running_average=not train)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """v1.5 bottleneck: stride on the 3x3 (torchvision semantics, which
    the reference's imagenet example trains)."""
    filters: int
    strides: int = 1
    norm: ModuleDef = None
    dtype: jnp.dtype = jnp.float32
    expansion = 4

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = self.norm()(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides))(x)
            residual = self.norm()(residual, use_running_average=not train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Type[nn.Module]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.float32
    norm_cls: Optional[Callable] = None   # e.g. parallel.SyncBatchNorm

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.norm_cls is not None:
            norm = self.norm_cls
        else:
            norm = functools.partial(nn.BatchNorm, momentum=0.9,
                                     epsilon=1e-5, dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), (2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = norm()(x, use_running_average=not train)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.width * 2 ** i, strides,
                                   norm=norm, dtype=self.dtype)(
                    x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def resnet18(**kw) -> ResNet:
    return ResNet([2, 2, 2, 2], BasicBlock, **kw)


def resnet34(**kw) -> ResNet:
    return ResNet([3, 4, 6, 3], BasicBlock, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet([3, 4, 6, 3], Bottleneck, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet([3, 4, 23, 3], Bottleneck, **kw)


def resnet152(**kw) -> ResNet:
    return ResNet([3, 8, 36, 3], Bottleneck, **kw)
