"""ResNet family, TPU-first (reference model zoo context:
examples/imagenet/main_amp.py drives torchvision resnet18/50/101 — the
reference itself ships no models; these exist so the BASELINE configs
run end-to-end).

TPU-native choices: NHWC layout (XLA's preferred conv layout on TPU),
bf16 compute with f32 BatchNorm statistics (amp O2's keep_batchnorm_fp32
semantics), injectable norm_cls so convert-to-SyncBatchNorm is a
constructor argument rather than a tree rewrite.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Type

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """(N, H, W, C) -> (N, H/b, W/b, b*b*C); channel order
    (row-parity, col-parity, C) row-major — the kernel fold in
    ResNet's space-to-depth stem depends on exactly this order."""
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"space_to_depth: spatial dims must be multiples of "
            f"{block}, got {h}x{w} — the default 7x7 stem "
            f"(stem_space_to_depth=False) accepts any size")
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def fold_stem_kernel(w7: jax.Array) -> jax.Array:
    """Fold a (7, 7, C, F) stride-2 stem kernel into the (4, 4, 4*C, F)
    stride-1 kernel that acts on space-to-depth(2) input.

    The MLPerf TPU ResNet transform: the 7x7 stride-2 conv wastes the
    128-lane MXU on C=3 inputs (~2% utilization); zero-pad the kernel
    to 8x8 (one leading row/col — that tap only ever reads the extra
    pad column, contributing zero) and fold 2x2 spatial parity into
    channels.  With input padding (2, 1) per spatial dim the result is
    exactly the original convolution (tested to numerical equality in
    tests/test_models.py)."""
    w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    k, _, c, f = 4, 4, w7.shape[2], w7.shape[3]
    w4 = w8.reshape(k, 2, k, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return w4.reshape(k, k, 4 * c, f)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    norm: ModuleDef = None
    dtype: jnp.dtype = jnp.float32
    expansion = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(x)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides))(x)
            residual = self.norm()(residual, use_running_average=not train)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """v1.5 bottleneck: stride on the 3x3 (torchvision semantics, which
    the reference's imagenet example trains)."""
    filters: int
    strides: int = 1
    norm: ModuleDef = None
    dtype: jnp.dtype = jnp.float32
    expansion = 4

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = self.norm()(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides))(x)
            residual = self.norm()(residual, use_running_average=not train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Type[nn.Module]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.float32
    norm_cls: Optional[Callable] = None   # e.g. parallel.SyncBatchNorm
    # MXU-efficient stem (MLPerf space-to-depth transform): same
    # function as the 7x7/s2 conv, computed as a 4x4/s1 conv over
    # space-to-depth(2) input so the MXU sees 12 input channels
    # instead of 3.  Opt-in: the param tree differs from the default
    # stem (stem_conv vs Conv_0).
    stem_space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.norm_cls is not None:
            norm = self.norm_cls
        else:
            norm = functools.partial(nn.BatchNorm, momentum=0.9,
                                     epsilon=1e-5, dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        if self.stem_space_to_depth:
            w7 = self.param("stem_conv",
                            nn.initializers.lecun_normal(),
                            (7, 7, x.shape[-1], self.width),
                            jnp.float32)
            x = jax.lax.conv_general_dilated(
                space_to_depth(x, 2),
                fold_stem_kernel(w7).astype(self.dtype),
                window_strides=(1, 1), padding=[(2, 1), (2, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        else:
            x = nn.Conv(self.width, (7, 7), (2, 2),
                        padding=[(3, 3), (3, 3)], use_bias=False,
                        dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = norm()(x, use_running_average=not train)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.width * 2 ** i, strides,
                                   norm=norm, dtype=self.dtype)(
                    x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def resnet18(**kw) -> ResNet:
    return ResNet([2, 2, 2, 2], BasicBlock, **kw)


def resnet34(**kw) -> ResNet:
    return ResNet([3, 4, 6, 3], BasicBlock, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet([3, 4, 6, 3], Bottleneck, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet([3, 4, 23, 3], Bottleneck, **kw)


def resnet152(**kw) -> ResNet:
    return ResNet([3, 8, 36, 3], Bottleneck, **kw)
