"""Host→device input pipeline (reference: the ``data_prefetcher`` class
in examples/imagenet/main_amp.py, which overlaps H2D copies with compute
on a side CUDA stream; SURVEY.md §1 L6).

TPU-native design: there are no user-managed streams — ``jax.device_put``
is asynchronous and XLA overlaps transfers with running computations by
itself.  What the prefetcher must supply is *pipelining depth*: issue the
next batch's transfer while the current step runs.  ``DevicePrefetcher``
keeps a ring of ``depth`` in-flight device batches fed from a background
host thread (so host-side batch construction — augmentation, decode,
numpy collation — also overlaps), which is the same two-deep pipeline the
reference builds with `stream.wait_stream` + `record_stream`.

Works with any iterator of pytrees (numpy or jax arrays).  When a
``sharding`` is given, batches land already laid out for the mesh
(`jax.device_put` with a NamedSharding performs the host-split +
multi-device transfer in one call).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

import jax

_SENTINEL = object()


class DevicePrefetcher:
    """Iterate device-resident batches, ``depth`` transfers ahead.

    >>> with DevicePrefetcher(loader, depth=2) as pf:
    ...     for batch in pf:
    ...         state = step(state, batch)   # next H2D already in flight

    The reference's loop idiom ``input, target = prefetcher.next()``
    (returning None at exhaustion — repeatedly, like the apex example's
    data_prefetcher) is also supported for drop-in ports.  ``close()``
    (or the context manager) releases the feeder thread and its in-flight
    device batches on early exit.
    """

    def __init__(self, it: Iterable[Any], depth: int = 2,
                 sharding: Optional[Any] = None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._src = iter(it)
        self._sharding = sharding
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(target=self._feed, daemon=True)
        self._thread.start()

    def _put_device(self, batch):
        if self._sharding is not None:
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self._sharding), batch)
        return jax.tree_util.tree_map(jax.device_put, batch)

    def _put_or_stop(self, item) -> bool:
        """Bounded put that aborts when close() is signalled; returns
        False if the prefetcher is shutting down."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _feed(self):
        try:
            for batch in self._src:
                if self._stop.is_set():
                    return
                if not self._put_or_stop(self._put_device(batch)):
                    return
        except BaseException as e:  # surfaced on the consumer side
            # the sentinel put below is the release barrier: __next__
            # reads _err only AFTER q.get() returns the sentinel, and
            # queue.Queue's internal lock orders the two
            self._err = e   # apexlint: disable=APX1001
        finally:
            self._put_or_stop(_SENTINEL)

    def __iter__(self) -> Iterator[Any]:
        return self

    def _publish_sentinel(self):
        """Best-effort sentinel publish so consumers blocked in q.get()
        wake; combined with __next__'s post-get _done check, a dropped
        publish (queue momentarily full) is still safe."""
        try:
            self._q.put_nowait(_SENTINEL)
        except queue.Full:
            pass

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if self._done and item is not _SENTINEL:
            # close() ran while we were blocked in get(): `item` is a
            # stale batch that slipped in after close()'s drain (the
            # feeder may have had one put in flight).  Shut down — and
            # re-publish so every other blocked consumer wakes too.
            self._publish_sentinel()
            raise StopIteration
        if item is _SENTINEL:
            self._done = True
            # re-publish for any OTHER consumer blocked in q.get() —
            # one sentinel must wake every waiter, not just the first
            self._publish_sentinel()
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item

    def next(self):
        """Reference-idiom alias: returns None at (and after) exhaustion
        instead of raising (matches data_prefetcher.next() in the apex
        example)."""
        try:
            return self.__next__()
        except StopIteration:
            return None

    def close(self):
        """Stop the feeder thread and drop queued device batches.  Safe
        to call more than once; called automatically by the context
        manager and on garbage collection."""
        self._done = True
        self._stop.set()
        while True:             # unblock a feeder stuck in put()
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        # re-publish the sentinel: a consumer already blocked in
        # __next__'s q.get() when close() ran would otherwise hang
        # forever (the drain above may have eaten the feeder's sentinel)
        self._publish_sentinel()
        self._thread.join(timeout=1.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort: don't leak the feeder thread
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(it: Iterable[Any], depth: int = 2,
                       sharding: Optional[Any] = None):
    """Functional spelling of DevicePrefetcher (flax-utils-style name)."""
    return DevicePrefetcher(it, depth=depth, sharding=sharding)


def pack_sequences(sequences, max_len: int, pad_id: int = 0):
    """Pack variable-length token sequences into fixed (B, max_len)
    rows for segment-masked attention (the reference's fmha packed
    varlen contract — apex/contrib/fmha in SURVEY.md §2.3; here the
    flash kernel's ``segment_ids`` routing does the masking).

    First-fit-decreasing bin packing on the host (numpy).  Returns a
    dict of (B, max_len) int32 arrays:

    - ``tokens``: packed ids, ``pad_id`` in the tail of each row
    - ``segment_ids``: 1, 2, ... per packed sequence, 0 on padding —
      the unpacking key (and the downstream padding mask)
    - ``q_segment_ids`` / ``kv_segment_ids``: the attention form —
      pass ``(q_segment_ids, kv_segment_ids)`` to ``flash_attention``.
      Padding carries DISJOINT ids per side (-1 vs -2, the
      contrib.fmha convention), so pad rows are fully masked and
      output exact zeros; real segments never see padding or each
      other
    - ``positions``: 0-based position WITHIN each sequence (for RoPE /
      learned position lookups), 0 on padding

    Sequences longer than ``max_len`` raise — truncation policy is the
    caller's decision, not a packer default.
    """
    import numpy as np

    seqs = [np.asarray(s, dtype=np.int32).reshape(-1) for s in sequences]
    too_long = [i for i, s in enumerate(seqs) if len(s) > max_len]
    if too_long:
        raise ValueError(
            f"pack_sequences: sequence(s) {too_long[:5]} longer than "
            f"max_len={max_len}; truncate or split before packing")
    empty = [i for i, s in enumerate(seqs) if len(s) == 0]
    if empty:
        # an empty sequence would silently vanish from the packed
        # output and desync any caller zipping labels by input index
        raise ValueError(
            f"pack_sequences: sequence(s) {empty[:5]} are empty; "
            f"filter them out (and their labels) before packing")

    order = sorted(range(len(seqs)), key=lambda i: -len(seqs[i]))
    bins = []          # list of (free, [seq_idx, ...])
    for i in order:
        need = len(seqs[i])
        for b in bins:
            if b[0] >= need:
                b[0] -= need
                b[1].append(i)
                break
        else:
            bins.append([max_len - need, [i]])

    B = len(bins)
    tokens = np.full((B, max_len), pad_id, dtype=np.int32)
    segment_ids = np.zeros((B, max_len), dtype=np.int32)
    positions = np.zeros((B, max_len), dtype=np.int32)
    for r, (_, members) in enumerate(bins):
        off = 0
        for seg, i in enumerate(members, start=1):
            n = len(seqs[i])
            tokens[r, off:off + n] = seqs[i]
            segment_ids[r, off:off + n] = seg
            positions[r, off:off + n] = np.arange(n)
            off += n
    from apex_tpu.ops.attention import packed_segment_ids
    q_ids, kv_ids = packed_segment_ids(segment_ids, xp=np)
    return {"tokens": tokens, "segment_ids": segment_ids,
            "positions": positions,
            "q_segment_ids": q_ids, "kv_segment_ids": kv_ids}


def pack_dataset(sequences, max_len: int, rows_per_batch: int,
                 pad_id: int = 0, buffer_batches: int = 8):
    """Stream packed batches from an iterable of token sequences.

    Buffers ``rows_per_batch * buffer_batches`` sequences, packs the
    buffer with :func:`pack_sequences` (FFD packs best with many
    candidates), and yields dicts shaped exactly like its output but
    with EXACTLY ``rows_per_batch`` rows per batch — fixed shapes, so
    one jit compilation serves the whole stream and the result feeds
    :class:`DevicePrefetcher` directly::

        batches = pack_dataset(corpus_iter, max_len=2048,
                               rows_per_batch=8)
        for batch in prefetch_to_device(batches, depth=2):
            step(params, batch["tokens"], batch["segment_ids"], ...)

    Rows left over when a buffer doesn't fill a whole batch are
    unpacked back into the carry (no mid-stream padding waste); only
    the stream's FINAL partial batch is padded with all-padding rows
    (segment 0 everywhere — downstream loss masking by
    ``segment_ids == 0`` already ignores them).  Sequences longer than
    ``max_len`` or empty raise, as in pack_sequences.
    """
    import numpy as np

    from apex_tpu.ops.attention import packed_segment_ids

    # pad-row fills: segment 0 + the q/kv ids the single-home helper
    # assigns to padding (never hardcode the -1/-2 convention here)
    _qpad, _kvpad = packed_segment_ids(np.zeros((), np.int32), xp=np)
    pad_fill = {"tokens": pad_id, "segment_ids": 0, "positions": 0,
                "q_segment_ids": int(_qpad), "kv_segment_ids": int(_kvpad)}

    def chunks(buf, final):
        """Yield full batches; return leftover sequences (or pad out
        the last batch when final)."""
        packed = pack_sequences(buf, max_len, pad_id=pad_id)
        rows = packed["tokens"].shape[0]
        full = rows - rows % rows_per_batch
        for start in range(0, full, rows_per_batch):
            yield {k: v[start:start + rows_per_batch]
                   for k, v in packed.items()}
        leftover = []
        if rows != full:
            tail = {k: v[full:] for k, v in packed.items()}
            if final:
                short = rows_per_batch - tail["tokens"].shape[0]
                yield {k: np.concatenate(
                    [v, np.full((short, max_len), pad_fill[k],
                                dtype=v.dtype)], axis=0)
                    for k, v in tail.items()}
            else:
                segs, toks = tail["segment_ids"], tail["tokens"]
                for r in range(toks.shape[0]):
                    for seg in range(1, int(segs[r].max()) + 1):
                        leftover.append(toks[r][segs[r] == seg])
        return leftover

    # flush by TOKEN count, not sequence count: tokens >= threshold
    # guarantees >= rows_per_batch * buffer_batches bins, so at least
    # one FULL batch is emitted per flush and the carry always shrinks
    # below a batch's worth (sequence-count flushing degraded to a
    # full repack per input sequence for short sequences)
    buf, toks = [], 0
    threshold = rows_per_batch * buffer_batches * max_len
    for s in sequences:
        buf.append(s)
        toks += len(s)
        if toks >= threshold:
            buf = yield from chunks(buf, final=False)
            toks = sum(len(x) for x in buf)
    if buf:
        yield from chunks(buf, final=True)
