"""FP16_Optimizer (reference: apex/fp16_utils/fp16_optimizer.py — the
legacy master-weights wrapper predating amp).

Reference flow per step: scale loss -> backward -> copy model grads to
f32 master grads -> check overflow -> (skip | master step -> copy
masters back to model params) -> update scale.  Functionally here:

    opt  = FusedSGD(half_params, lr=...)
    fopt = FP16_Optimizer(opt, dynamic_loss_scale=True)
    loss, grads = value_and_grad(lambda p: fopt.scale(loss_fn(p)))(params)
    params = fopt.step(grads)          # grads of the SCALED loss

The wrapped optimizer's own master handling is reused (FusedOptimizerBase
keeps f32 masters for half params, apex O2 contract); this wrapper adds
the legacy scaling/overflow-skip surface around it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.contrib.clip_grad import clip_grad_norm
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=False):
        self.optimizer = init_optimizer
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False
        self.verbose = verbose

    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale

    @property
    def params(self):
        return self.optimizer.params

    def scale(self, loss):
        """Multiply the loss by the current scale (use inside your loss fn;
        replaces the reference's fp16_optimizer.backward(loss))."""
        return loss * self.loss_scaler.loss_scale

    # reference name for the same operation
    scale_loss = scale

    def step(self, scaled_grads, grad_scale_extra=1.0):
        """Unscale, overflow-check, conditionally step; returns params."""
        grads = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32)
                       / (self.loss_scaler.loss_scale * grad_scale_extra)),
            scaled_grads)
        self.overflow = self.loss_scaler.has_overflow(grads)
        if not self.overflow:
            self.optimizer.step(grads)
        elif self.verbose:
            print(f"OVERFLOW! Skipping step, scale {self.loss_scale}")
        self.loss_scaler.update_scale(self.overflow)
        return self.optimizer.params

    def clip_master_grads(self, grads, max_norm, norm_type=2.0):
        """Clip (already-unscaled) f32 grads; returns (clipped, norm)."""
        return clip_grad_norm(grads, max_norm, norm_type)

    def zero_grad(self):
        self.optimizer.zero_grad()

    def state_dict(self):
        sd = {
            "optimizer": self.optimizer.state_dict(),
            "cur_scale": self.loss_scaler.cur_scale,
            "dynamic": isinstance(self.loss_scaler, DynamicLossScaler),
        }
        if sd["dynamic"]:
            # growth-window clock (the reference checkpoints these too)
            sd["cur_iter"] = self.loss_scaler.cur_iter
            sd["last_overflow_iter"] = self.loss_scaler.last_overflow_iter
        return sd

    def load_state_dict(self, sd):
        self.optimizer.load_state_dict(sd["optimizer"])
        if sd.get("dynamic") and not isinstance(self.loss_scaler,
                                                DynamicLossScaler):
            self.loss_scaler = DynamicLossScaler(sd["cur_scale"])
        self.loss_scaler.cur_scale = sd["cur_scale"]
        if isinstance(self.loss_scaler, DynamicLossScaler):
            self.loss_scaler.cur_iter = sd.get("cur_iter", 0)
            self.loss_scaler.last_overflow_iter = sd.get(
                "last_overflow_iter", -1)
