from apex_tpu.fp16_utils.fp16util import (  # noqa: F401
    BN_convert_float,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
    tree_to_half,
)
from apex_tpu.fp16_utils.loss_scaler import (  # noqa: F401
    DynamicLossScaler,
    LossScaler,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401

__all__ = [
    "BN_convert_float", "DynamicLossScaler", "FP16_Optimizer",
    "LossScaler", "master_params_to_model_params",
    "model_grads_to_master_grads", "network_to_half", "prep_param_lists",
    "to_python_float", "tree_to_half",
]
