"""Legacy manual mixed-precision helpers (reference:
apex/fp16_utils/fp16util.py, SURVEY.md §2.1 — the pre-amp API:
network_to_half, BN_convert_float, prep_param_lists,
master_params_to_model_params, ...).

The reference operates on nn.Module parameter lists; here the unit of
state is the params PYTREE, so each helper is a tree transform.  "Half"
defaults to bfloat16 — the TPU's native half — with fp16 available via
the dtype argument.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NORM_NAME_HINTS = ("batchnorm", "bn", "layernorm", "ln", "norm",
                    "batch_stats")


def _is_float(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def tree_to_half(params, dtype=jnp.bfloat16):
    """Cast every floating leaf to half precision."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float(x) else x, params)


def network_to_half(params, dtype=jnp.bfloat16):
    """Reference parity: convert a model to half but keep normalization
    layers in f32 (the reference wraps BN in tofp32 shims).  Norm leaves
    are identified by path-name hints (flax module names)."""
    half = tree_to_half(params, dtype)
    return BN_convert_float(half)


def BN_convert_float(params):
    """Cast normalization-layer params back to f32 (reference contract:
    BN statistics/affine math must stay f32 under fp16 training)."""
    def fix(path, x):
        names = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                         for k in path).lower()
        if _is_float(x) and any(h in names for h in _NORM_NAME_HINTS):
            return x.astype(jnp.float32)
        return x
    return jax.tree_util.tree_map_with_path(fix, params)


def prep_param_lists(params, flat_master: bool = False):
    """(model_params, master_params): f32 master copies of the model tree.

    flat_master=True additionally fuses masters into ONE flat f32 buffer
    (the reference's single-tensor master option); returned as
    (params, (flat_buffer, unravel_fn))."""
    masters = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32) if _is_float(x) else x, params)
    if flat_master:
        from jax.flatten_util import ravel_pytree
        flat, unravel = ravel_pytree(masters)
        return params, (flat, unravel)
    return params, masters


def master_params_to_model_params(model_params, master_params):
    """Write master values back into model dtypes (returns new tree)."""
    return jax.tree_util.tree_map(
        lambda p, m: m.astype(p.dtype) if _is_float(p) else m,
        model_params, master_params)


def model_grads_to_master_grads(model_grads):
    """Promote model-dtype grads to f32 for the master step."""
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32) if _is_float(g) else g, model_grads)


def to_python_float(t):
    """Reference helper: pull a scalar to host."""
    return float(jnp.asarray(t).reshape(()))
