"""Legacy loss scalers (reference: apex/fp16_utils/loss_scaler.py —
`LossScaler` (static) and `DynamicLossScaler`, the pre-amp API).

Same math as apex_tpu.amp.scaler (the modern path); these classes keep
the legacy surface: has_overflow(grads), update_scale(overflow),
scale_gradient semantics via unscale()."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _has_overflow(grads) -> bool:
    leaves = [g for g in jax.tree_util.tree_leaves(grads)
              if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)]
    if not leaves:
        return False
    # one device reduction + ONE host sync (not one per leaf)
    ok = jnp.stack([jnp.all(jnp.isfinite(g.astype(jnp.float32)))
                    for g in leaves])
    return not bool(jnp.all(ok))


class LossScaler:
    """Static scale.  has_overflow always False (reference behavior)."""

    def __init__(self, scale=1.0):
        self.cur_scale = float(scale)

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_loss(self, loss):
        return loss * self.cur_scale

    def unscale(self, grads):
        inv = 1.0 / self.cur_scale
        return jax.tree_util.tree_map(lambda g: g * inv, grads)

    def has_overflow(self, grads):
        return False

    def update_scale(self, overflow):
        pass


class DynamicLossScaler(LossScaler):
    """Grow x2 after scale_window clean steps, back off x0.5 on overflow
    (reference defaults: init 2**32 clipped here to 2**16 for bf16-era
    sanity is NOT done — parity keeps the reference's 2**32)."""

    def __init__(self, init_scale=2.0 ** 32, scale_factor=2.0,
                 scale_window=1000):
        super().__init__(init_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.last_overflow_iter = -1
        self.cur_iter = 0

    def has_overflow(self, grads):
        return _has_overflow(grads)

    def update_scale(self, overflow):
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % \
                self.scale_window == 0:
            # reference grows whenever the window condition holds — with
            # scale_window=1 that includes the very first clean step
            # (ADVICE r1 parity fix)
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1
