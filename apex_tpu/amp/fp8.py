"""fp8 training: delayed-scaling policy + packed per-bucket state.

The reference apex stops at fp16/bf16; fp8-capable TPUs run
e4m3/e5m2 matmuls at roughly 2x the bf16 MXU rate, and the flat AMP
pipeline already owns everything delayed scaling needs: per-bucket
flat buffers, sorted-segment per-tensor reduces, the loss scaler's
growth/backoff discipline and the watchdog's rollback safety net.

Design (the transformer-engine recipe, bucketized):

- **Formats**: e4m3 forward (max 448 — precision over range),
  e5m2 backward (max 57344 — gradients need range).  Where the
  backend has no fp8 matmul the COMPUTE falls back to bf16 while the
  quantization (convert to the fp8 storage dtype) still runs, so the
  scaling discipline — and every bit of the amax/scale bookkeeping —
  is identical on CPU tier-1 and on hardware ("bf16-compute oracle").
- **Delayed scaling**: tensors are quantized with the PREVIOUS steps'
  scale while the current step only records amax; the scale is
  recomputed from a rolling per-tensor amax history
  (``fp8_max / (2**margin * max(history))``).  No dependency of this
  step's quantization on this step's values = no extra serialization.
- **Packed state**: the per-tensor amax history and scale live packed
  in the :class:`~apex_tpu.multi_tensor_apply.packer.BucketPlan`
  layout — one ``(n_leaves, H)`` history matrix and one
  ``(n_leaves,)`` scale vector per bucket — updated by ONE flat pass
  per bucket (``ops.multi_tensor.flat_amax_scale_update``: sorted-
  segment amax + history roll + scale recompute + per-tensor overflow
  backoff), never a per-leaf tree_map.  As optimizer slots
  (``FusedOptimizerBase.enable_fp8``) the state is donated, offloaded,
  checkpointed and re-chunked like every other slot.
- **Overflow**: a non-finite amax latches ``found_inf`` — the step is
  skipped branch-free and the step clock holds, exactly like a loss-
  scale overflow — while the affected tensor's scale backs off by
  ``backoff_factor`` (the scaler's hysteresis, layered per bucket).
  A scale pinned at its floor is the fp8 collapse signature the
  watchdog's :class:`~apex_tpu.resilience.watchdog.
  Fp8ScaleCollapseDetector` watches (``fp8/scale_min``).

See docs/amp.md "fp8 training" for the state layout and the fallback
matrix.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply.packer import BucketPlan
from apex_tpu.ops import multi_tensor as mt
from apex_tpu.telemetry import _tape

Pytree = Any

#: fp8 format maxima (jnp.finfo where the dtypes exist; these are the
#: IEEE-P3109/OCP values and never change).
E4M3_MAX = 448.0
E5M2_MAX = 57344.0

_DTYPES = {"e4m3": ("float8_e4m3fn", E4M3_MAX),
           "e5m2": ("float8_e5m2", E5M2_MAX)}


def fp8_dtype(which: str):
    """The jnp fp8 dtype for ``which`` ("e4m3"/"e5m2"), or None where
    this jax build lacks it (the storage-level availability gate)."""
    name, _ = _DTYPES[which]
    return getattr(jnp, name, None)


def fp8_max(which: str) -> float:
    return _DTYPES[which][1]


@functools.lru_cache(maxsize=None)
def fp8_matmul_available() -> bool:
    """True iff the default backend can COMPILE every fp8 dot the
    training path emits: e4m3 x e4m3 (forward) AND the mixed
    e5m2 x e4m3 / e4m3 x e5m2 combinations the backward's shared
    cotangent produces — a backend that accepts the forward but
    rejects the mixed backward dots must fall back as a whole, or the
    first ``jax.grad`` would fail at compile time.

    Probed once with a tiny lowering+compile; failure (old chip
    generations, jax builds without fp8) routes ``fp8_matmul``'s
    compute to the bf16 fallback while the quantization and scale
    bookkeeping run unchanged."""
    e4 = fp8_dtype("e4m3")
    e5 = fp8_dtype("e5m2")
    if e4 is None or e5 is None:
        return False
    try:
        a4 = jax.ShapeDtypeStruct((8, 8), e4)
        a5 = jax.ShapeDtypeStruct((8, 8), e5)

        def probe(x4, g5):
            dot = functools.partial(
                jax.lax.dot_general,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dot(x4, x4), dot(g5, x4), dot(x4, g5)

        jax.jit(probe).lower(a4, a5).compile()
        return True
    except Exception:
        return False


@dataclasses.dataclass(frozen=True)
class Fp8Policy:
    """Static fp8 training configuration (hashable — safe to close
    over in jitted code and to use as a custom_vjp nondiff arg).

    ``fwd_format``/``bwd_format``: fp8 formats for forward operands
    (activations/weights) and backward cotangents.  ``amax_history_len``
    and ``interval`` are the delayed-scaling cadence knobs the
    autotuner sweeps (``tools/autotune.py``; build with
    :func:`tuned_policy` to pick up the measured per-topology values).
    ``margin``: extra headroom exponent in the scale formula.
    ``compute``: "auto" uses real fp8 matmuls where the backend
    compiles them, else the bf16-compute oracle; "fp8"/"bf16" force
    either side (tests pin "bf16" to assert the bookkeeping is
    bit-identical across compute paths).
    """
    fwd_format: str = "e4m3"
    bwd_format: str = "e5m2"
    amax_history_len: int = 16
    interval: int = 1
    margin: float = 0.0
    backoff_factor: float = 0.5
    compute: str = "auto"

    def __post_init__(self):
        for f in (self.fwd_format, self.bwd_format):
            if f not in _DTYPES:
                raise ValueError(f"unknown fp8 format {f!r}; one of "
                                 f"{sorted(_DTYPES)}")
        if self.amax_history_len < 1:
            raise ValueError("amax_history_len must be >= 1")
        if self.interval < 1:
            raise ValueError("interval must be >= 1")
        if self.compute not in ("auto", "fp8", "bf16"):
            raise ValueError(f"unknown compute {self.compute!r}")

    def fwd_dtype(self):
        return fp8_dtype(self.fwd_format)

    def bwd_dtype(self):
        return fp8_dtype(self.bwd_format)

    def fwd_max(self) -> float:
        return fp8_max(self.fwd_format)

    def bwd_max(self) -> float:
        return fp8_max(self.bwd_format)

    def uses_fp8_compute(self) -> bool:
        """Whether matmuls run on fp8 operands (vs the bf16-compute
        oracle).  Requires the storage dtypes to exist either way."""
        if self.fwd_dtype() is None or self.bwd_dtype() is None:
            return False
        if self.compute == "fp8":
            return True
        if self.compute == "bf16":
            return False
        return fp8_matmul_available()


def tuned_policy(**overrides) -> Fp8Policy:
    """An :class:`Fp8Policy` with the autotuner's measured per-topology
    scaling cadence applied (``fp8.amax_history_len`` /
    ``fp8.interval`` from the dispatch prefs table — the design
    defaults where no sweep recorded one).  Explicit ``overrides``
    always win."""
    from apex_tpu.ops import _dispatch
    kw = {}
    h = _dispatch.fp8_pref("amax_history_len")
    if h is not None:
        kw["amax_history_len"] = int(h)
    n = _dispatch.fp8_pref("interval")
    if n is not None:
        kw["interval"] = int(n)
    kw.update(overrides)
    return Fp8Policy(**kw)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Fp8State:
    """Packed delayed-scaling state over one BucketPlan (a pytree).

    ``amax_history``: per bucket, (n_leaves, H) f32 — row per tensor,
    column 0 newest.  ``scale``: per bucket, (n_leaves,) f32 — the
    CURRENT quantization scales (``value * scale`` fills the fp8
    range; dequantize multiplies by ``1/scale``).  ``step``: i32
    update counter driving the scale-update-interval cadence.
    """
    amax_history: List[jax.Array]
    scale: List[jax.Array]
    step: jax.Array


def init_state(plan: BucketPlan, policy: Fp8Policy) -> Fp8State:
    """Fresh state: zero history, unit scales."""
    h = policy.amax_history_len
    return Fp8State(
        amax_history=[jnp.zeros((len(b.leaves), h), jnp.float32)
                      for b in plan.buckets],
        scale=[jnp.ones((len(b.leaves),), jnp.float32)
               for b in plan.buckets],
        step=jnp.int32(0))


def update_state(state: Fp8State, bufs: Sequence[jax.Array],
                 plan: BucketPlan, policy: Fp8Policy, *,
                 fp8_max_value: Optional[float] = None,
                 skip=None, telemetry_prefix: str = "fp8"
                 ) -> Tuple[Fp8State, jax.Array]:
    """Roll this step's per-tensor amax into the packed state: ONE
    flat pass per bucket (``mt.flat_amax_scale_update``).  Returns
    ``(new_state, found_inf)`` — found_inf flags any non-finite amax
    and must be OR'd into the step's skip flag (the fp8 analog of the
    loss scaler's overflow latch; the step clock holds with it).

    ``skip`` (traced bool/i32 ok): an externally-skipped step — the
    CLEAN transition holds (no history roll, no scale recompute),
    mirroring ``amp.update_state(skipped=)``; the scale-update-
    interval cadence (``policy.interval``) composes the same way, and
    amax from a gated step is simply not recorded (delayed scaling
    tolerates sparse histories by construction).  A tensor whose amax
    OVERFLOWED still backs off on a gated step — overflow response
    must not wait for the cadence, exactly like the loss scaler backs
    off on the steps it skips — and is transient by construction: the
    next clean update RECOMPUTES the scale from the (unpoisoned)
    history rather than incrementally recovering it.
    """
    do = jnp.equal(state.step % jnp.int32(policy.interval), 0)
    if skip is not None:
        do = jnp.logical_and(do,
                             jnp.asarray(skip, jnp.int32) == 0)
    new_hist, new_scale, found_inf = update_packed(
        state.amax_history, state.scale, bufs, plan, policy,
        fp8_max_value=fp8_max_value, update=do,
        scale_min_metric=f"{telemetry_prefix}/scale_min",
        amax_max_metric=f"{telemetry_prefix}/amax_max")
    return Fp8State(amax_history=new_hist, scale=new_scale,
                    step=state.step + 1), found_inf


def update_packed(amax_history: Sequence[jax.Array],
                  scale: Sequence[jax.Array],
                  bufs: Sequence[jax.Array], plan: BucketPlan,
                  policy: Fp8Policy, *,
                  fp8_max_value: Optional[float] = None, update,
                  scale_min_metric: Optional[str] = None,
                  amax_max_metric: Optional[str] = None):
    """THE packed per-bucket transition (one
    ``mt.flat_amax_scale_update`` pass per bucket + the telemetry
    reduce) — shared by :func:`update_state` (gradient-side
    ``Fp8State``, cadence from ``state.step``) and the optimizer's
    weight-scale slots (``FusedOptimizerBase._fp8_slot_update``,
    cadence from the step clock), so the two carriers can never
    drift.  ``update`` is the caller's already-resolved gate.
    Returns ``(new_histories, new_scales, found_inf)``."""
    if len(bufs) != len(plan.buckets):
        raise ValueError(
            f"fp8 state covers {len(plan.buckets)} bucket(s), got "
            f"{len(bufs)} buffer(s)")
    # fp8_max_value is static config (a Python float), never traced
    fmax = (policy.fwd_max() if fp8_max_value is None
            else fp8_max_value)
    new_hist, new_scale, flags = [], [], []
    for bi, buf in enumerate(bufs):
        h, s, f = mt.flat_amax_scale_update(
            buf, plan.segment_ids(bi), plan.num_segments(bi),
            amax_history[bi], scale[bi],
            fp8_max=fmax, margin=policy.margin,
            backoff_factor=policy.backoff_factor, update=update)
        new_hist.append(h)
        new_scale.append(s)
        flags.append(f)
    found_inf = functools.reduce(jnp.maximum, flags)
    # telemetry producers (no-ops without an active tape): a collapsed
    # fp8 scale is THE signature the watchdog's
    # Fp8ScaleCollapseDetector consumes
    if scale_min_metric is not None:
        _tape.emit(scale_min_metric, functools.reduce(
            jnp.minimum, [jnp.min(s) for s in new_scale]))
    if amax_max_metric is not None:
        _tape.emit(amax_max_metric, functools.reduce(
            jnp.maximum, [jnp.max(h[:, 0]) for h in new_hist]),
            reduce="max")
    _tape.emit("fp8/found_inf", found_inf, reduce="max")
    return new_hist, new_scale, found_inf


def update_state_ref(state: Fp8State, tree: Pytree, plan: BucketPlan,
                     policy: Fp8Policy, *,
                     fp8_max_value: Optional[float] = None,
                     skip=None) -> Tuple[Fp8State, jax.Array]:
    """Per-leaf oracle of :func:`update_state`: amax per LEAF via a
    tree walk, the identical transition math per tensor — the
    bit-exactness bar tests hold the packed path to."""
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != plan.n_leaves:
        raise ValueError("tree does not mirror the plan")
    fmax = (policy.fwd_max() if fp8_max_value is None
            else fp8_max_value)
    do = jnp.equal(state.step % jnp.int32(policy.interval), 0)
    if skip is not None:
        do = jnp.logical_and(do, jnp.asarray(skip, jnp.int32) == 0)
    new_hist, new_scale, flags = [], [], []
    for bi, b in enumerate(plan.buckets):
        amax = jnp.stack([
            jnp.max(jnp.abs(leaves[s.index].astype(jnp.float32)))
            for s in b.leaves])
        h, s, f = mt._amax_scale_math(
            amax, state.amax_history[bi], state.scale[bi], fmax,
            policy.margin, policy.backoff_factor, 2.0 ** 24,
            2.0 ** -24, do)
        new_hist.append(h)
        new_scale.append(s)
        flags.append(f)
    return Fp8State(amax_history=new_hist, scale=new_scale,
                    step=state.step + 1), \
        functools.reduce(jnp.maximum, flags)


def scales_tree(plan: BucketPlan, state: Fp8State) -> Pytree:
    """The per-leaf pytree view of the packed scales (scalar per
    leaf) — the wiring surface for module-level fp8 matmuls
    (``FusedDense(fp8=...)`` weights take their delayed scale from
    here).  Scalar slices fuse into the caller's jit; the hot loop
    never materializes a per-leaf copy of the state."""
    leaves: List[Any] = [None] * plan.n_leaves
    for bi, b in enumerate(plan.buckets):
        for j, s in enumerate(b.leaves):
            leaves[s.index] = state.scale[bi][j]
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def quantize(x: jax.Array, scale, which_or_dtype) -> jax.Array:
    """``x * scale`` saturated into the fp8 format — THE quantize op
    (exactly one convert per call; apexverify spec ``amp.fp8_step``
    pins the program-wide count so casts cannot silently multiply).
    Where the dtype is unavailable the value path saturates the same
    way but stays bf16 (scale bookkeeping unchanged)."""
    if isinstance(which_or_dtype, str):
        dt = fp8_dtype(which_or_dtype)
        fmax = fp8_max(which_or_dtype)
    else:
        dt = which_or_dtype
        fmax = float(jnp.finfo(dt).max)
    y = jnp.clip(x.astype(jnp.float32)
                 * jnp.asarray(scale, jnp.float32), -fmax, fmax)
    return y.astype(dt if dt is not None else jnp.bfloat16)


def dynamic_scale(x: jax.Array, fmax: float) -> jax.Array:
    """Just-in-time (current) scaling for tensors with no delayed
    state — activations and cotangents: ``fmax / amax`` clipped, amax
    zero/non-finite degrading to scale 1 (the overflow then saturates
    and the unscale stays exact)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    ok = (amax > 0) & (jnp.abs(amax) < jnp.float32(jnp.inf))
    return jnp.where(
        ok, jnp.clip(jnp.asarray(fmax, jnp.float32) / amax,
                     2.0 ** -24, 2.0 ** 24), jnp.float32(1.0))
