"""Flat end-to-end AMP gradient pipeline: pack once, fuse everything.

The per-leaf amp surface walks the gradient pytree three to four times
per step — ``unscale_grads``, ``check_finite``, ``clip_grad_norm`` each
sweep every leaf (clip_grad even ravels its own throwaway flat buffer),
and the bucketed optimizer then re-packs the grads inside ``step()``.
That is exactly the per-tensor-launch overhead upstream apex's ``amp_C``
multi-tensor pipeline exists to kill (SURVEY.md §2.3).

This module makes gradients live FLAT from loss to update:

    scaled_value_and_grad          (grads w.r.t. model params)
        └─ pack_grads              ONE concatenate per dtype bucket
            └─ all-reduce          one psum per BUCKET, not per leaf
                └─ flat_unscale_norm   unscale + non-finite + Σg² in
                                       ONE HBM read per bucket
                    └─ tiny combine    global norm, found_inf, clip_coef
                        └─ optimizer.step(FlatGrads)
                                       clip folds into the flat kernels'
                                       grad scaling; grads never unpack

The per-leaf path (amp.scaler + contrib.clip_grad) stays as the oracle
and the fallback for trees the packer declines.

Two schedule refinements ride the same pipeline (ISSUE 10):

* **Interleaved collectives** (``interleave=True`` + a chunked plan):
  each bucket's data-parallel reduce is emitted INSIDE the backward by
  a custom-vjp seam wrapped around that bucket's param leaves, so the
  collective's dependency cone is exactly its own leaves' cotangents —
  never the whole backward.  With buckets chunked
  (``max_bucket_bytes``), bucket k's psum is schedulable while bucket
  k-1's backward compute still runs; XLA's latency-hiding scheduler
  (platform.enable_latency_hiding_scheduler) turns that freedom into
  hidden collective time (docs/perf.md "Overlap schedule").
* **Flat accumulation** (``accumulate()``/``finalize()`` or
  ``microbatches=N``): microbatch gradients add into persistent f32
  accumulator buckets via ONE fused read-modify-write per bucket
  (ops.multi_tensor.flat_accumulate, donated/aliased accumulators),
  found_inf latching across microbatches; the final
  unscale+clip+reduce rides the existing per-bucket kernels, so the
  accumulation loop never materializes a per-leaf gradient tree.
"""

from __future__ import annotations

import functools
from typing import Any, List, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import (LossScaleState, scale_loss,
                                 split_microbatch_args)
from apex_tpu.multi_tensor_apply.packer import BucketPlan, cached_plan
from apex_tpu.ops import _dispatch
from apex_tpu.ops import multi_tensor as mt
from apex_tpu.telemetry import _tape

Pytree = Any


class FlatGrads(NamedTuple):
    """The flat pipeline's gradient bundle (a pytree — jit-safe).

    ``bufs``: unscaled per-bucket flat gradient buffers in the plan's
    layout.  ``grad_norm``: PRE-clip global L2 norm of the unscaled
    gradients (f32; NaN when non-finite — see found_inf).  ``found_inf``:
    i32 overflow flag (any non-finite unscaled element).  ``clip_coef``:
    f32 global-norm clip coefficient in (0, 1], exactly 1.0 when no
    clipping applies; fold it into the optimizer step, never into the
    buffers (``FusedOptimizerBase.step`` does this for you).
    """
    bufs: List[jax.Array]
    grad_norm: jax.Array
    found_inf: jax.Array
    clip_coef: jax.Array


class GradAccum(NamedTuple):
    """Persistent microbatch gradient-accumulation state (a pytree).

    ``bufs``: per-bucket f32 accumulator buffers in the plan's layout
    (SCALED gradients accumulate; unscale happens once at finalize).
    ``found_inf``: i32 latch — set by ANY microbatch whose gradients
    (or their running sum) went non-finite, so one bad microbatch
    skips the whole committed step, branch-free.  ``count``: i32
    number of microbatches accumulated (finalize's averaging divisor).

    Donate the buffers to the jitted accumulation step
    (``flat_accumulate`` aliases its accumulator input to its output)
    — the add is then in place, one HBM read-modify-write per bucket.
    """
    bufs: List[jax.Array]
    found_inf: jax.Array
    count: jax.Array

    @staticmethod
    def zeros(plan: BucketPlan) -> "GradAccum":
        return GradAccum(
            bufs=[jnp.zeros((b.size,), jnp.float32)
                  for b in plan.buckets],
            found_inf=jnp.int32(0), count=jnp.int32(0))


def _scaler_state(state) -> LossScaleState:
    """Accept a LossScaleState or anything carrying one (AmpState)."""
    return getattr(state, "scaler", state)


class FlatGradPipeline:
    """Pack-once gradient pipeline over a :class:`BucketPlan`.

    Construct from a bucketed fused optimizer (reuses its plan — the
    buffers then feed ``optimizer.step`` with ZERO re-packing) or from
    a params/grads pytree (a standalone cached plan is built).

    ``max_grad_norm > 0`` enables fused global-norm clipping: the norm
    falls out of the unscale kernel for free and the clip coefficient
    rides the optimizer kernels' existing grad scaling.  ``axis_name``
    enables bucket-granular data-parallel all-reduce (one collective
    per flat bucket) between pack and unscale, mirroring the reference
    DDP's reduce-then-unscale ordering.

    ``interleave=True`` moves each bucket's reduce INTO the backward
    (custom-vjp seam per bucket): the collective depends only on its
    own leaves' cotangents, so with a chunked plan
    (``max_bucket_bytes``, or the optimizer's own) the scheduler can
    hide bucket k's collective under bucket k-1's backward compute.
    Numerically identical to the trailing schedule (same f32 psum per
    bucket, same ordering of adds); a no-op when ``axis_name`` is None
    or unbound.  ``reduce_decompose="reduce_scatter"`` lowers each
    bucket's sum as psum_scatter + all_gather (async-friendlier halves
    — see parallel.distributed).
    """

    def __init__(self, optimizer=None, plan: Optional[BucketPlan] = None,
                 params: Optional[Pytree] = None,
                 max_grad_norm: float = 0.0,
                 axis_name: Optional[str] = None,
                 average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 eps: float = 1e-6,
                 defer_plan: bool = False,
                 interleave: bool = False,
                 reduce_decompose: str = "psum",
                 max_bucket_bytes=None,
                 fp8=None):
        if reduce_decompose == "auto":
            # measured per-topology preference (tools/autotune.py);
            # absent entry = the design default
            reduce_decompose = _dispatch.pipeline_pref(
                "reduce_decompose", "psum")
        if max_bucket_bytes == "auto":
            supplied = plan if plan is not None \
                else getattr(optimizer, "_plan", None)
            if supplied is not None:
                # a supplied plan owns its chunking: "auto" asks the
                # measured table only when THIS pipeline derives the
                # plan (chunk at the source, e.g. FusedAdam(...,
                # max_bucket_bytes=...), to steer a shared plan)
                max_bucket_bytes = getattr(supplied,
                                           "max_bucket_bytes", None)
            else:
                max_bucket_bytes = _dispatch.pipeline_pref(
                    "max_bucket_bytes", None)
        if plan is None and optimizer is not None:
            plan = getattr(optimizer, "_plan", None)
            if plan is None:
                raise ValueError(
                    "optimizer has no bucket plan (fuse_buckets=False or "
                    "the packer declined its tree) — the flat pipeline "
                    "needs the bucketed path; use the per-leaf amp "
                    "surface instead")
        if plan is not None and max_bucket_bytes is not None \
                and getattr(plan, "max_bucket_bytes",
                            None) != max_bucket_bytes:
            # a supplied plan (optimizer=/plan=) wins over any later
            # derivation, so a mismatching chunking request would be
            # SILENTLY ignored — and with interleave=True the overlap
            # schedule would silently degrade to the plan's (possibly
            # monolithic, trailing-equivalent) layout
            raise ValueError(
                "max_bucket_bytes conflicts with the supplied plan "
                f"(built with max_bucket_bytes="
                f"{getattr(plan, 'max_bucket_bytes', None)}) — chunk "
                "at the source instead, e.g. FusedAdam(..., "
                "max_bucket_bytes=N), or omit it here")
        if plan is None and params is not None:
            plan = cached_plan(params, max_bucket_bytes=max_bucket_bytes)
        if plan is None and not defer_plan:
            raise ValueError("need one of optimizer=, plan= or params= "
                             "(or defer_plan=True to derive the plan "
                             "from the first gradient tree packed)")
        if reduce_decompose not in ("psum", "reduce_scatter"):
            raise ValueError(
                f"unknown reduce_decompose {reduce_decompose!r}")
        self.plan = plan
        self.optimizer = optimizer
        self.max_grad_norm = float(max_grad_norm)
        self.axis_name = axis_name
        self.average = average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.eps = float(eps)
        self.interleave = bool(interleave)
        self.reduce_decompose = reduce_decompose
        self.max_bucket_bytes = max_bucket_bytes
        # fp8 delayed scaling for the GRADIENT side: e5m2 per-tensor
        # scale state packed in this plan's layout (docs/amp.md "fp8
        # training") — fp8=True resolves the autotuned policy
        if fp8 is True:
            from apex_tpu.amp.fp8 import tuned_policy
            fp8 = tuned_policy()
        self.fp8 = fp8
        self._seams: dict = {}

    # ---- stages ----------------------------------------------------------
    def pack(self, grads: Pytree) -> List[jax.Array]:
        """Pytree -> per-bucket flat buffers (the ONE gradient pack);
        already-packed input passes through untouched."""
        if self.plan is None:   # defer_plan: derive from the first tree
            self.plan = cached_plan(
                grads, max_bucket_bytes=self.max_bucket_bytes)
            if self.plan is None:
                raise ValueError(
                    "flat pipeline: the packer declined this gradient "
                    "tree (non-float or multi-device leaves) — use the "
                    "per-leaf amp surface")
        if self.plan.is_packed(grads):
            return list(grads)
        return self.plan.pack_grads(grads)

    def reduce(self, bufs: List[jax.Array]) -> List[jax.Array]:
        """Bucket-granular data-parallel all-reduce (no-op without
        ``axis_name`` or outside shard_map/pmap)."""
        if self.axis_name is None:
            return bufs
        from apex_tpu.parallel.distributed import all_reduce_flat_buffers
        return all_reduce_flat_buffers(
            bufs, self.axis_name, average=self.average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            decompose=self.reduce_decompose)

    # ---- interleaved collectives (reduce-in-backward seam) ---------------
    def _bucket_seam(self, bucket_index: int):
        """Custom-vjp identity over one bucket's param leaves whose
        backward packs that bucket's cotangents and reduces them over
        the data axis RIGHT THERE — the collective's dependency cone is
        exactly this bucket's cotangent subgraph, never the rest of the
        backward, so the lowered schedule is free to overlap it with
        the remaining bucket's compute.  The slices it returns fold
        with the pipeline's later re-pack (slice-of-concat /
        concat-of-slices cancel in XLA's simplifier), so the seam adds
        no extra gradient copy."""
        b = self.plan.buckets[bucket_index]
        axis = self.axis_name
        avg, pre = self.average, self.gradient_predivide_factor
        dec = self.reduce_decompose

        @jax.custom_vjp
        def seam(leaves):
            return leaves

        def fwd(leaves):
            return leaves, None

        def bwd(_, cts):
            from apex_tpu.parallel.distributed import \
                all_reduce_flat_buffers
            parts = [jnp.ravel(c) for c in cts]
            buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            buf = all_reduce_flat_buffers(
                [buf], axis, average=avg,
                gradient_predivide_factor=pre, decompose=dec)[0]
            return (tuple(
                jax.lax.slice(buf, (s.offset,),
                              (s.offset + s.size,)).reshape(s.shape)
                for s in b.leaves),)

        seam.defvjp(fwd, bwd)
        return seam

    def _interleave_params(self, params: Pytree) -> Pytree:
        """Thread every bucket's leaves through its reduce-in-backward
        seam (forward: identity)."""
        if self.plan is None:
            self.plan = cached_plan(
                params, max_bucket_bytes=self.max_bucket_bytes)
            if self.plan is None:
                raise ValueError(
                    "interleave: the packer declined the params tree")
        leaves, treedef = jax.tree_util.tree_flatten(params)
        if len(leaves) != self.plan.n_leaves:
            raise ValueError(
                "interleave: params tree does not match the bucket plan")
        for bi, b in enumerate(self.plan.buckets):
            seam = self._seams.get(bi)
            if seam is None:
                seam = self._seams[bi] = self._bucket_seam(bi)
            outs = seam(tuple(leaves[s.index] for s in b.leaves))
            for s, o in zip(b.leaves, outs):
                leaves[s.index] = o
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def unscale_and_norm(self, bufs: List[jax.Array],
                         state=None, inv_scale=None) -> FlatGrads:
        """One ``flat_unscale_norm`` kernel per bucket + tiny combine.

        Pass either a scaler ``state`` (LossScaleState/AmpState) or an
        explicit ``inv_scale``; omit both for already-unscaled grads
        (inv_scale=1 — the kernel still yields norm + found_inf)."""
        if inv_scale is None:
            inv_scale = (1.0 / _scaler_state(state).loss_scale
                         if state is not None else jnp.float32(1.0))
        outs, norm_sqs, flags = [], [], []
        for buf in bufs:
            o, nsq, flag = mt.flat_unscale_norm(buf, inv_scale)
            outs.append(o)
            norm_sqs.append(nsq)
            flags.append(flag)
        found_inf = functools.reduce(jnp.maximum, flags)
        norm = jnp.sqrt(sum(norm_sqs, jnp.float32(0.0)))
        maxn = jnp.asarray(self.max_grad_norm, jnp.float32)
        clip = jnp.where((maxn > 0) & (norm > maxn),
                         maxn / (norm + self.eps), jnp.float32(1.0))
        # overflow (inf/NaN norm): the step is skipped via found_inf
        # regardless, so pin clip_coef to the neutral 1.0 — deterministic
        # whether the norm overflowed to inf (clip would be 0) or NaN
        # (comparison False); no 0-or-NaN coefficient ever leaks out
        clip = jnp.where(found_inf > 0, jnp.float32(1.0), clip)
        # telemetry producers (trace-time no-ops without an active
        # tape): the signals below already exist on device — reporting
        # them costs nothing and syncs nothing
        _tape.emit("amp/grad_norm", norm)
        _tape.emit("amp/found_inf", found_inf, reduce="max")
        _tape.emit("amp/clip_coef", clip)
        return FlatGrads(bufs=outs, grad_norm=norm,
                         found_inf=found_inf, clip_coef=clip)

    # ---- fp8 delayed scaling (gradient side) -----------------------------
    def fp8_init(self):
        """Fresh packed :class:`~apex_tpu.amp.fp8.Fp8State` over this
        plan — per-tensor amax history + e5m2 scales for the gradient
        buffers.  Thread it through the jitted step next to the loss
        scaler and feed the step's flag to the optimizer's
        ``found_inf=``."""
        from apex_tpu.amp import fp8 as _fp8
        if self.fp8 is None:
            raise ValueError("pipeline was built without fp8= policy")
        if self.plan is None:
            raise ValueError("fp8_init needs a resolved plan "
                             "(construct with optimizer=/plan=/params=)")
        return _fp8.init_state(self.plan, self.fp8)

    def fp8_update(self, fp8_state, flat: FlatGrads):
        """Roll the unscaled gradient buffers' per-tensor amax into
        the delayed-scaling state (ONE flat pass per bucket) and
        latch any fp8 overflow into the bundle's ``found_inf`` — a
        poisoned scale state skips the step and holds the step clock
        exactly like a loss-scale overflow.  A step already skipped
        (``flat.found_inf``) holds the fp8 history too — garbage amax
        must never enter the window — while an overflowed tensor's
        scale still backs off (the loss scaler's own skip-and-back-off
        shape; see ``amp.fp8.update_state``).  Returns
        ``(flat', new_state)``.
        """
        from apex_tpu.amp import fp8 as _fp8
        if self.fp8 is None:
            raise ValueError("pipeline was built without fp8= policy")
        new_state, f8_inf = _fp8.update_state(
            fp8_state, flat.bufs, self.plan, self.fp8,
            fp8_max_value=self.fp8.bwd_max(), skip=flat.found_inf)
        return (flat._replace(
            found_inf=jnp.maximum(flat.found_inf, f8_inf)), new_state)

    # ---- microbatch accumulation -----------------------------------------
    def init_accum(self) -> GradAccum:
        """Fresh zeroed accumulator state in the plan's layout."""
        if self.plan is None:
            raise ValueError("init_accum needs a resolved plan "
                             "(construct with optimizer=/plan=/params=)")
        return GradAccum.zeros(self.plan)

    def accumulate(self, acc: GradAccum, grads: Pytree) -> GradAccum:
        """Add one microbatch's (still-scaled) gradients into the
        accumulator: pack if needed (already-packed buffers pass
        through), then ONE fused read-modify-write per bucket.  The
        overflow flag latches — a single bad microbatch marks the
        whole accumulation window."""
        bufs = self.pack(grads)
        new, flags = [], [acc.found_inf]
        for a, g in zip(acc.bufs, bufs):
            o, f = mt.flat_accumulate(a, g)
            new.append(o)
            flags.append(f)
        return GradAccum(bufs=new,
                         found_inf=functools.reduce(jnp.maximum, flags),
                         count=acc.count + 1)

    def finalize(self, acc: GradAccum, state=None, inv_scale=None,
                 average: bool = True, fp8_state=None):
        """Accumulator -> FlatGrads: ONE data-parallel reduce per
        bucket (grad accumulation reduces once per committed step, not
        per microbatch), then the fused unscale+norm+clip epilogue
        with the loss scale and the microbatch count folded into a
        single ``inv_scale`` (``average=True`` divides by ``count`` —
        the mean-over-global-batch convention).  The latched
        ``found_inf`` ORs into the epilogue's own detection.

        ``fp8_state``: delayed-scaling gradient state — its amax
        update rides the finalized (unscaled) buffers and the return
        becomes ``(flat, new_fp8_state)``."""
        bufs = self.reduce(acc.bufs)
        if inv_scale is None:
            inv_scale = (1.0 / _scaler_state(state).loss_scale
                         if state is not None else jnp.float32(1.0))
        inv_scale = jnp.asarray(inv_scale, jnp.float32)
        if average:
            inv_scale = inv_scale / jnp.maximum(
                acc.count, 1).astype(jnp.float32)
        flat = self.unscale_and_norm(bufs, inv_scale=inv_scale)
        flat = flat._replace(
            found_inf=jnp.maximum(flat.found_inf, acc.found_inf))
        if fp8_state is not None:
            return self.fp8_update(fp8_state, flat)
        return flat

    def reset_accum(self, acc: GradAccum) -> GradAccum:
        """Zeroed accumulator for the next step, reusing the buffer
        shapes (trace-safe; under donation XLA reuses the storage)."""
        return GradAccum(bufs=[jnp.zeros_like(b) for b in acc.bufs],
                         found_inf=jnp.int32(0), count=jnp.int32(0))

    # ---- end-to-end ------------------------------------------------------
    def scaled_value_and_grad(self, loss_fn, state, *args,
                              has_aux: bool = False,
                              microbatches: int = 1,
                              fp8_state=None, **kwargs):
        """value_and_grad of the LOSS-SCALED objective, gradients flat.

        The flat analog of ``amp.scaled_value_and_grad``: returns
        ``((loss, aux?), FlatGrads)`` where the FlatGrads buffers are
        unscaled, reduced (when ``axis_name``), and carry the global
        norm, overflow flag and clip coefficient — ready for
        ``optimizer.step(flat_grads)``.

        With ``interleave=True`` each bucket's reduce runs inside the
        backward (see class docstring) and the trailing reduce stage
        is skipped.

        ``microbatches=N`` (N > 1) splits every batch argument
        (``args[1:]``) along its leading axis into N microbatches and
        accumulates gradients FLAT across a ``lax.scan``: one pack +
        one fused ``flat_accumulate`` per bucket per microbatch, zero
        per-leaf unpacking, found_inf latched across microbatches,
        data-parallel reduce deferred to the single finalize.  The
        returned loss is the mean over microbatches (== the mean over
        the full batch for a mean-over-examples loss); with
        ``has_aux`` the aux comes back stacked along a leading
        microbatch axis.

        ``fp8_state``: packed delayed-scaling gradient state
        (``fp8_init()``) — the amax/scale update rides the unscaled
        buffers (one flat pass per bucket) and the return grows a
        trailing ``new_fp8_state``, with any fp8 overflow latched
        into ``flat.found_inf``.
        """
        sstate = _scaler_state(state)
        if microbatches > 1:
            return self._microbatched(loss_fn, sstate, args,
                                      has_aux, int(microbatches),
                                      kwargs, fp8_state)
        interleaved = self.interleave and self.axis_name is not None

        def scaled_fn(*a, **kw):
            if interleaved:
                a = (self._interleave_params(a[0]),) + tuple(a[1:])
            out = loss_fn(*a, **kw)
            if has_aux:
                loss, aux = out
                return scale_loss(loss, sstate), aux
            return scale_loss(out, sstate)

        if has_aux:
            (scaled, aux), grads = jax.value_and_grad(
                scaled_fn, has_aux=True)(*args, **kwargs)
        else:
            scaled, grads = jax.value_and_grad(scaled_fn)(*args, **kwargs)
            aux = None
        bufs = self.pack(grads)
        if not interleaved:      # seam already reduced in the backward
            bufs = self.reduce(bufs)
        flat = self.unscale_and_norm(bufs, sstate)
        loss = scaled / sstate.loss_scale
        _tape.emit("amp/loss_scale", sstate.loss_scale)
        _tape.emit("loss", loss)
        if fp8_state is not None:
            flat, fp8_state = self.fp8_update(fp8_state, flat)
            if has_aux:
                return (loss, aux), flat, fp8_state
            return loss, flat, fp8_state
        if has_aux:
            return (loss, aux), flat
        return loss, flat

    def _microbatched(self, loss_fn, sstate, args, has_aux, n, kwargs,
                      fp8_state=None):
        """The ``microbatches=N`` body: scan over leading-axis splits,
        accumulating packed gradients (never a per-leaf tree)."""
        params, xs = split_microbatch_args(args, n)
        if self.plan is None:
            # resolve the plan from the params tree (same structure,
            # shapes and dtypes as the gradients) so init_accum can
            # size the buffers before the first backward
            self.plan = cached_plan(
                params, max_bucket_bytes=self.max_bucket_bytes)
            if self.plan is None:
                raise ValueError(
                    "microbatches: the packer declined the params tree")

        def scaled_fn(p, *b):
            out = loss_fn(p, *b, **kwargs)
            if has_aux:
                loss, aux = out
                return scale_loss(loss, sstate), aux
            return scale_loss(out, sstate), None

        def body(carry, micro):
            acc, scaled_sum = carry
            (scaled, aux), grads = jax.value_and_grad(
                scaled_fn, has_aux=True)(params, *micro)
            acc = self.accumulate(acc, grads)
            return (acc, scaled_sum + scaled), aux

        (acc, scaled_sum), auxes = jax.lax.scan(
            body, (self.init_accum(), jnp.float32(0.0)), xs)
        out = self.finalize(acc, sstate, average=True,
                            fp8_state=fp8_state)
        flat, new_fp8 = out if fp8_state is not None else (out, None)
        loss = scaled_sum / (jnp.float32(n) * sstate.loss_scale)
        _tape.emit("amp/loss_scale", sstate.loss_scale)
        _tape.emit("loss", loss)
        if fp8_state is not None:
            if has_aux:
                return (loss, auxes), flat, new_fp8
            return loss, flat, new_fp8
        if has_aux:
            return (loss, auxes), flat
        return loss, flat

    def step(self, flat: FlatGrads, grad_scale=1.0) -> Pytree:
        """``optimizer.step`` on the packed buffers — found_inf drives
        the branch-free skip, clip_coef folds into the kernels."""
        if self.optimizer is None:
            raise ValueError("pipeline was built without an optimizer")
        return self.optimizer.step(flat, grad_scale=grad_scale)

    def grads_tree(self, flat: FlatGrads) -> Pytree:
        """Unpack the buffers to a pytree (inspection/tests only — the
        hot loop never needs this)."""
        return self.plan.unpack_grads(flat.bufs)
