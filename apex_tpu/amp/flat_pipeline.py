"""Flat end-to-end AMP gradient pipeline: pack once, fuse everything.

The per-leaf amp surface walks the gradient pytree three to four times
per step — ``unscale_grads``, ``check_finite``, ``clip_grad_norm`` each
sweep every leaf (clip_grad even ravels its own throwaway flat buffer),
and the bucketed optimizer then re-packs the grads inside ``step()``.
That is exactly the per-tensor-launch overhead upstream apex's ``amp_C``
multi-tensor pipeline exists to kill (SURVEY.md §2.3).

This module makes gradients live FLAT from loss to update:

    scaled_value_and_grad          (grads w.r.t. model params)
        └─ pack_grads              ONE concatenate per dtype bucket
            └─ all-reduce          one psum per BUCKET, not per leaf
                └─ flat_unscale_norm   unscale + non-finite + Σg² in
                                       ONE HBM read per bucket
                    └─ tiny combine    global norm, found_inf, clip_coef
                        └─ optimizer.step(FlatGrads)
                                       clip folds into the flat kernels'
                                       grad scaling; grads never unpack

The per-leaf path (amp.scaler + contrib.clip_grad) stays as the oracle
and the fallback for trees the packer declines.
"""

from __future__ import annotations

import functools
from typing import Any, List, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaleState, scale_loss
from apex_tpu.multi_tensor_apply.packer import BucketPlan, cached_plan
from apex_tpu.ops import multi_tensor as mt
from apex_tpu.telemetry import _tape

Pytree = Any


class FlatGrads(NamedTuple):
    """The flat pipeline's gradient bundle (a pytree — jit-safe).

    ``bufs``: unscaled per-bucket flat gradient buffers in the plan's
    layout.  ``grad_norm``: PRE-clip global L2 norm of the unscaled
    gradients (f32; NaN when non-finite — see found_inf).  ``found_inf``:
    i32 overflow flag (any non-finite unscaled element).  ``clip_coef``:
    f32 global-norm clip coefficient in (0, 1], exactly 1.0 when no
    clipping applies; fold it into the optimizer step, never into the
    buffers (``FusedOptimizerBase.step`` does this for you).
    """
    bufs: List[jax.Array]
    grad_norm: jax.Array
    found_inf: jax.Array
    clip_coef: jax.Array


def _scaler_state(state) -> LossScaleState:
    """Accept a LossScaleState or anything carrying one (AmpState)."""
    return getattr(state, "scaler", state)


class FlatGradPipeline:
    """Pack-once gradient pipeline over a :class:`BucketPlan`.

    Construct from a bucketed fused optimizer (reuses its plan — the
    buffers then feed ``optimizer.step`` with ZERO re-packing) or from
    a params/grads pytree (a standalone cached plan is built).

    ``max_grad_norm > 0`` enables fused global-norm clipping: the norm
    falls out of the unscale kernel for free and the clip coefficient
    rides the optimizer kernels' existing grad scaling.  ``axis_name``
    enables bucket-granular data-parallel all-reduce (one collective
    per flat bucket) between pack and unscale, mirroring the reference
    DDP's reduce-then-unscale ordering.
    """

    def __init__(self, optimizer=None, plan: Optional[BucketPlan] = None,
                 params: Optional[Pytree] = None,
                 max_grad_norm: float = 0.0,
                 axis_name: Optional[str] = None,
                 average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 eps: float = 1e-6,
                 defer_plan: bool = False):
        if plan is None and optimizer is not None:
            plan = getattr(optimizer, "_plan", None)
            if plan is None:
                raise ValueError(
                    "optimizer has no bucket plan (fuse_buckets=False or "
                    "the packer declined its tree) — the flat pipeline "
                    "needs the bucketed path; use the per-leaf amp "
                    "surface instead")
        if plan is None and params is not None:
            plan = cached_plan(params)
        if plan is None and not defer_plan:
            raise ValueError("need one of optimizer=, plan= or params= "
                             "(or defer_plan=True to derive the plan "
                             "from the first gradient tree packed)")
        self.plan = plan
        self.optimizer = optimizer
        self.max_grad_norm = float(max_grad_norm)
        self.axis_name = axis_name
        self.average = average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.eps = float(eps)

    # ---- stages ----------------------------------------------------------
    def pack(self, grads: Pytree) -> List[jax.Array]:
        """Pytree -> per-bucket flat buffers (the ONE gradient pack);
        already-packed input passes through untouched."""
        if self.plan is None:   # defer_plan: derive from the first tree
            self.plan = cached_plan(grads)
            if self.plan is None:
                raise ValueError(
                    "flat pipeline: the packer declined this gradient "
                    "tree (non-float or multi-device leaves) — use the "
                    "per-leaf amp surface")
        if self.plan.is_packed(grads):
            return list(grads)
        return self.plan.pack_grads(grads)

    def reduce(self, bufs: List[jax.Array]) -> List[jax.Array]:
        """Bucket-granular data-parallel all-reduce (no-op without
        ``axis_name`` or outside shard_map/pmap)."""
        if self.axis_name is None:
            return bufs
        from apex_tpu.parallel.distributed import all_reduce_flat_buffers
        return all_reduce_flat_buffers(
            bufs, self.axis_name, average=self.average,
            gradient_predivide_factor=self.gradient_predivide_factor)

    def unscale_and_norm(self, bufs: List[jax.Array],
                         state=None, inv_scale=None) -> FlatGrads:
        """One ``flat_unscale_norm`` kernel per bucket + tiny combine.

        Pass either a scaler ``state`` (LossScaleState/AmpState) or an
        explicit ``inv_scale``; omit both for already-unscaled grads
        (inv_scale=1 — the kernel still yields norm + found_inf)."""
        if inv_scale is None:
            inv_scale = (1.0 / _scaler_state(state).loss_scale
                         if state is not None else jnp.float32(1.0))
        outs, norm_sqs, flags = [], [], []
        for buf in bufs:
            o, nsq, flag = mt.flat_unscale_norm(buf, inv_scale)
            outs.append(o)
            norm_sqs.append(nsq)
            flags.append(flag)
        found_inf = functools.reduce(jnp.maximum, flags)
        norm = jnp.sqrt(sum(norm_sqs, jnp.float32(0.0)))
        maxn = jnp.asarray(self.max_grad_norm, jnp.float32)
        clip = jnp.where((maxn > 0) & (norm > maxn),
                         maxn / (norm + self.eps), jnp.float32(1.0))
        # overflow (inf/NaN norm): the step is skipped via found_inf
        # regardless, so pin clip_coef to the neutral 1.0 — deterministic
        # whether the norm overflowed to inf (clip would be 0) or NaN
        # (comparison False); no 0-or-NaN coefficient ever leaks out
        clip = jnp.where(found_inf > 0, jnp.float32(1.0), clip)
        # telemetry producers (trace-time no-ops without an active
        # tape): the signals below already exist on device — reporting
        # them costs nothing and syncs nothing
        _tape.emit("amp/grad_norm", norm)
        _tape.emit("amp/found_inf", found_inf, reduce="max")
        _tape.emit("amp/clip_coef", clip)
        return FlatGrads(bufs=outs, grad_norm=norm,
                         found_inf=found_inf, clip_coef=clip)

    # ---- end-to-end ------------------------------------------------------
    def scaled_value_and_grad(self, loss_fn, state, *args,
                              has_aux: bool = False, **kwargs):
        """value_and_grad of the LOSS-SCALED objective, gradients flat.

        The flat analog of ``amp.scaled_value_and_grad``: returns
        ``((loss, aux?), FlatGrads)`` where the FlatGrads buffers are
        unscaled, reduced (when ``axis_name``), and carry the global
        norm, overflow flag and clip coefficient — ready for
        ``optimizer.step(flat_grads)``.
        """
        sstate = _scaler_state(state)

        def scaled_fn(*a, **kw):
            out = loss_fn(*a, **kw)
            if has_aux:
                loss, aux = out
                return scale_loss(loss, sstate), aux
            return scale_loss(out, sstate)

        if has_aux:
            (scaled, aux), grads = jax.value_and_grad(
                scaled_fn, has_aux=True)(*args, **kwargs)
        else:
            scaled, grads = jax.value_and_grad(scaled_fn)(*args, **kwargs)
            aux = None
        flat = self.unscale_and_norm(self.reduce(self.pack(grads)), sstate)
        loss = scaled / sstate.loss_scale
        _tape.emit("amp/loss_scale", sstate.loss_scale)
        _tape.emit("loss", loss)
        if has_aux:
            return (loss, aux), flat
        return loss, flat

    def step(self, flat: FlatGrads, grad_scale=1.0) -> Pytree:
        """``optimizer.step`` on the packed buffers — found_inf drives
        the branch-free skip, clip_coef folds into the kernels."""
        if self.optimizer is None:
            raise ValueError("pipeline was built without an optimizer")
        return self.optimizer.step(flat, grad_scale=grad_scale)

    def grads_tree(self, flat: FlatGrads) -> Pytree:
        """Unpack the buffers to a pytree (inspection/tests only — the
        hot loop never needs this)."""
        return self.plan.unpack_grads(flat.bufs)
