"""fp8 micro-benchmarks: the quantized matmul vs the bf16 baseline,
and the fused packed scale update vs the per-leaf oracle.

Shared by tools/kernel_bench.py (JSON rows ``fp8_matmul`` /
``fp8_scale_update``), bench.py (the ``fp8_matmul_speedup`` TPU
extra that grounds the ``extra.fp8_matmul_speedup`` perf-budget row)
and the tier-1 smoke test (tiny shapes on CPU: proves the harness,
not performance — fp8 wins only where the MXU has fp8 units).
"""

from __future__ import annotations


def bench_fp8_matmul(m: int = 4096, k: int = 4096, n: int = 4096,
                     iters: int = 10, reps: int = 3):
    """fp8 vs bf16 fused_dense forward+backward at one GEMM shape.

    "kernel" = ``fp8_matmul`` (e4m3 fwd / e5m2 bwd, delayed-style
    explicit scales so the quantize path is the packed-state shape),
    "oracle" = the plain bf16 ``fused_dense_function`` dot.  On
    fp8-capable TPUs the floor is 1.5x (tools/perf_budget.json
    ``extra.fp8_matmul_speedup``); elsewhere the ratio only proves
    the harness runs.
    """
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp.fp8 import Fp8Policy
    from apex_tpu.benchlib import timeit
    from apex_tpu.fused_dense import fp8_matmul

    policy = Fp8Policy()
    x = jax.random.normal(jax.random.key(0), (m, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (k, n),
                          jnp.bfloat16) * 0.02
    sx = jnp.float32(8.0)
    sw = jnp.float32(64.0)

    def fp8_fwdbwd(x, w):
        return jax.grad(
            lambda x, w: jnp.sum(fp8_matmul(
                x, w, policy=policy, x_scale=sx, w_scale=sw
            ).astype(jnp.float32) ** 2), argnums=(0, 1))(x, w)

    def bf16_fwdbwd(x, w):
        return jax.grad(
            lambda x, w: jnp.sum(jnp.dot(
                x, w, preferred_element_type=jnp.float32) ** 2),
            argnums=(0, 1))(x, w)

    fp8_ms = timeit(jax.jit(fp8_fwdbwd), x, w, iters=iters, reps=reps,
                    adaptive=True)
    bf16_ms = timeit(jax.jit(bf16_fwdbwd), x, w, iters=iters,
                     reps=reps, adaptive=True)
    return {
        "fp8_matmul_shape": f"{m}x{k}x{n}",
        "fp8_compute": policy.uses_fp8_compute(),
        "fp8_matmul_ms": round(fp8_ms, 4),
        "bf16_matmul_ms": round(bf16_ms, 4),
        "fp8_matmul_speedup": (round(bf16_ms / fp8_ms, 3)
                               if fp8_ms else None),
    }


def bench_fp8_scale_update(layers: int = 48, hidden: int = 256,
                           amax_history_len: int = 16,
                           iters: int = 10, reps: int = 3):
    """Fused packed fp8 scale update (ONE flat segment-reduce pass per
    bucket) vs the per-leaf oracle (amax per leaf via a tree walk) on
    the same many-leaf pytree — the dispatch-amortization win the
    packed state exists for, measured exactly like the other
    bucketing benches."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp import fp8
    from apex_tpu.benchlib import timeit
    from apex_tpu.multi_tensor_apply.packer import cached_plan
    from apex_tpu.optimizers.bucketing_bench import many_leaf_params

    policy = fp8.Fp8Policy(amax_history_len=amax_history_len)
    params = many_leaf_params(jax, jnp, layers, hidden)
    plan = cached_plan(params)
    bufs = plan.pack_grads(params)
    state = fp8.init_state(plan, policy)

    def fused(state, bufs):
        new, _ = fp8.update_state(state, bufs, plan, policy)
        return new

    def per_leaf(state, tree):
        new, _ = fp8.update_state_ref(state, tree, plan, policy)
        return new

    fused_ms = timeit(jax.jit(fused), state, bufs, iters=iters,
                      reps=reps, adaptive=True)
    leaf_ms = timeit(jax.jit(per_leaf), state, params, iters=iters,
                     reps=reps, adaptive=True)
    return {
        "fp8_scale_leaves": plan.n_leaves,
        "fp8_scale_history": amax_history_len,
        "fp8_scale_fused_ms": round(fused_ms, 4),
        "fp8_scale_per_leaf_ms": round(leaf_ms, 4),
        "fp8_scale_update_speedup": (round(leaf_ms / fused_ms, 3)
                                     if fused_ms else None),
    }
