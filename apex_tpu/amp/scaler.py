"""Dynamic loss scaling as carried pytree state.

Reference: apex/amp/scaler.py + csrc/update_scale_hysteresis.cu
(SURVEY.md §2.1, §3.2).  Semantics preserved: scale the loss before
backward; unscale grads; if any grad is non-finite, skip the step and
multiply the scale by ``backoff_factor`` (0.5); after ``growth_interval``
(2000) consecutive clean steps multiply by ``growth_factor`` (2.0).

TPU redesign: the reference reads the overflow flag on the host every step
(a device sync).  Here the flag, the skip decision (lax.cond) and the
scale update are all traced into the jitted train step; the scaler state
is a pytree the caller threads through.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.telemetry import _tape

Pytree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LossScaleState:
    """Carried state of one dynamic loss scaler (a pytree)."""
    loss_scale: jax.Array      # f32 scalar
    growth_tracker: jax.Array  # i32 scalar: consecutive clean steps
    found_inf: jax.Array       # i32 scalar: last step's overflow flag

    @staticmethod
    def create(init_scale: float = 2.0 ** 16) -> "LossScaleState":
        return LossScaleState(
            loss_scale=jnp.float32(init_scale),
            growth_tracker=jnp.int32(0),
            found_inf=jnp.int32(0),
        )


@dataclasses.dataclass(frozen=True)
class LossScaleConfig:
    init_scale: float = 2.0 ** 16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    min_loss_scale: float = 1.0
    max_loss_scale: float = 2.0 ** 24
    dynamic: bool = True


def scale_loss(loss: jax.Array, state: LossScaleState) -> jax.Array:
    return loss * state.loss_scale.astype(loss.dtype)


def unscale_grads(grads: Pytree, state: LossScaleState) -> Pytree:
    inv = 1.0 / state.loss_scale
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)


def check_finite(grads: Pytree) -> jax.Array:
    """i32 flag: 1 iff any grad element is non-finite.  Stays on device."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.int32(0)
    bad = jnp.stack([
        jnp.logical_not(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
        for g in leaves])
    return jnp.any(bad).astype(jnp.int32)


def update_state(state: LossScaleState, found_inf: jax.Array,
                 config: LossScaleConfig = LossScaleConfig(),
                 skipped=None) -> LossScaleState:
    """update_scale_hysteresis semantics, branch-free on device.

    ``skipped`` (optional i32/bool, traced or concrete): the step was
    skipped EXTERNALLY — a watchdog quarantine, a pipeline bubble —
    rather than by the scaler's own overflow logic.  Such a step is
    neither a clean step nor an overflow: the growth tracker must not
    advance toward the growth interval (it did not observe a clean
    optimizer update) and the scale must not move.  Without the flag a
    quarantined window would count toward ``growth_interval`` and the
    scale could grow across a window where nothing was learned.
    """
    if not config.dynamic:
        _tape.emit("amp/found_inf", found_inf, reduce="max")
        return dataclasses.replace(state, found_inf=found_inf)
    overflowed = found_inf > 0
    tracker = jnp.where(overflowed, 0, state.growth_tracker + 1)
    grow = tracker >= config.growth_interval
    new_scale = jnp.where(
        overflowed,
        jnp.maximum(state.loss_scale * config.backoff_factor,
                    config.min_loss_scale),
        jnp.where(grow,
                  jnp.minimum(state.loss_scale * config.growth_factor,
                              config.max_loss_scale),
                  state.loss_scale),
    )
    tracker = jnp.where(grow, 0, tracker)
    if skipped is not None:
        ext = jnp.asarray(skipped, jnp.int32) > 0
        new_scale = jnp.where(ext, state.loss_scale, new_scale)
        tracker = jnp.where(ext, state.growth_tracker, tracker)
    # telemetry (no-ops without an active tape): a collapsing loss
    # scale is THE amp failure signature worth watching live
    _tape.emit("amp/loss_scale", new_scale)
    _tape.emit("amp/growth_tracker", tracker)
    _tape.emit("amp/found_inf", found_inf, reduce="max")
    return LossScaleState(
        loss_scale=new_scale,
        growth_tracker=tracker,
        found_inf=found_inf,
    )


def re_anchor(state: LossScaleState,
              config: LossScaleConfig = LossScaleConfig(),
              scale=None) -> LossScaleState:
    """Reset the scaler to a known-safe operating point — the
    watchdog's quarantine action.

    After a detected training anomaly (NaN storm that outlasted the
    backoff, loss-scale collapse) the scaler's carried state is part of
    the damage: the scale may be pinned at the floor and the growth
    tracker mid-count.  ``re_anchor`` returns a fresh state at
    ``scale`` (default: the config's init scale), tracker zeroed,
    overflow flag cleared — so recovery restarts from the configured
    operating point instead of crawling back up by growth intervals.
    """
    if scale is None:
        scale = config.init_scale
    return LossScaleState(
        loss_scale=jnp.float32(scale),
        growth_tracker=jnp.int32(0),
        found_inf=jnp.int32(0),
    )


def scaled_value_and_grad(loss_fn, state: LossScaleState, *args,
                          has_aux: bool = False, grads_layout: str = "tree",
                          plan=None, microbatches: int = 1, **kwargs):
    """value_and_grad of a LOSS-SCALED objective, then unscale.

    The canonical TPU replacement for the reference's
    ``with amp.scale_loss(loss, optimizer) as scaled: scaled.backward()``
    idiom (apex/amp/handle.py): grads come back already unscaled plus the
    on-device found_inf flag for the conditional optimizer step.

    Returns ((loss, aux?), grads, found_inf).

    ``grads_layout="flat"`` switches the gradient side to the flat
    pipeline: grads come back as an ``amp.FlatGrads`` bundle — packed
    ONCE into per-bucket flat buffers (``plan``: a BucketPlan, a
    bucketed fused optimizer, or None to derive a cached plan from the
    grads), unscaled by one fused kernel per bucket that also yields
    the global norm and the overflow flag.  The per-leaf ``"tree"``
    layout stays the oracle.

    ``microbatches=N`` (N > 1) splits every batch argument
    (``args[1:]``) along its leading axis and accumulates gradients
    across a scan before unscaling ONCE by ``1/(loss_scale * N)`` (the
    mean-over-global-batch convention), with the overflow flag latched
    across microbatches.  On the flat layout the accumulation is the
    fused per-bucket ``flat_accumulate`` path (zero per-leaf work —
    docs/amp.md "Gradient accumulation"); on the tree layout it is the
    per-leaf f32 oracle of the same schedule.  With ``has_aux`` the
    aux comes back stacked along a leading microbatch axis.
    """
    if grads_layout not in ("tree", "flat"):
        raise ValueError(f"unknown grads_layout {grads_layout!r}")
    if grads_layout == "flat":
        # layering: flat_pipeline imports this module; import lazily
        from apex_tpu.amp.flat_pipeline import FlatGradPipeline
        if plan is not None and not hasattr(plan, "pack_grads"):
            pipe = FlatGradPipeline(optimizer=plan)   # a fused optimizer
        else:
            # plan=None: the pipeline derives a cached plan from the
            # gradient tree at first pack
            pipe = FlatGradPipeline(plan=plan, defer_plan=plan is None)
        out, flat = pipe.scaled_value_and_grad(
            loss_fn, state, *args, has_aux=has_aux,
            microbatches=microbatches, **kwargs)
        return out, flat, flat.found_inf

    def scaled_fn(*a, **kw):
        out = loss_fn(*a, **kw)
        if has_aux:
            loss, aux = out
            return scale_loss(loss, state), aux
        return scale_loss(out, state)

    if microbatches > 1:
        return _microbatched_tree(scaled_fn, state, args, has_aux,
                                  int(microbatches), kwargs)

    if has_aux:
        (scaled, aux), grads = jax.value_and_grad(
            scaled_fn, has_aux=True)(*args, **kwargs)
    else:
        scaled, grads = jax.value_and_grad(scaled_fn)(*args, **kwargs)
        aux = None
    found_inf = check_finite(grads)
    grads = unscale_grads(grads, state)
    loss = scaled / state.loss_scale
    _tape.emit("amp/found_inf", found_inf, reduce="max")
    _tape.emit("amp/loss_scale", state.loss_scale)
    _tape.emit("loss", loss)
    if has_aux:
        return (loss, aux), grads, found_inf
    return loss, grads, found_inf


def split_microbatch_args(args, n: int):
    """``(params, stacked-batch)`` from a microbatched call's args:
    every argument after the params (args[0]) splits ``(n, lead/n,
    ...)`` along its leading axis — the ONE splitting contract shared
    by the per-leaf oracle below and FlatGradPipeline's fused path."""
    if len(args) < 2:
        raise ValueError(
            "microbatches=N needs batch arguments after the params "
            "(they are split along their leading axis)")
    params, *batch = args
    leads = {tuple(getattr(a, "shape", ()))[:1]
             for a in jax.tree_util.tree_leaves(tuple(batch))}
    if () in leads or len(leads) != 1:
        # a 0-d arg (step scalar, key) or mismatched leading dims
        # would silently mis-split into wrong per-microbatch slices —
        # every split arg must share ONE batch axis
        raise ValueError(
            "microbatches=N splits every argument after the params "
            "along a shared leading batch axis, but the batch "
            f"arguments have leading dims {sorted(leads)} — close "
            "over non-batch values instead of passing them "
            "positionally")

    def split(a):
        if a.shape[0] % n:
            raise ValueError(
                f"microbatches={n} does not divide the leading batch "
                f"axis of shape {a.shape}")
        return a.reshape((n, a.shape[0] // n) + tuple(a.shape[1:]))

    return params, jax.tree_util.tree_map(split, tuple(batch))


def _microbatched_tree(scaled_fn, state, args, has_aux, n, kwargs):
    """Per-leaf microbatch accumulation (the tree-layout oracle of
    FlatGradPipeline's fused path): scan over leading-axis splits,
    accumulate SCALED grads in f32 per leaf, unscale once by
    ``1/(loss_scale * n)``, latch found_inf across microbatches."""
    params, xs = split_microbatch_args(args, n)

    def wrapped(p, *b):
        out = scaled_fn(p, *b, **kwargs)
        return out if has_aux else (out, None)

    def body(carry, micro):
        acc, scaled_sum, bad = carry
        (scaled, aux), grads = jax.value_and_grad(
            wrapped, has_aux=True)(params, *micro)
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), acc, grads)
        bad = jnp.maximum(bad, check_finite(acc))
        return (acc, scaled_sum + scaled, bad), aux

    acc0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (acc, scaled_sum, found_inf), auxes = jax.lax.scan(
        body, (acc0, jnp.float32(0.0), jnp.int32(0)), xs)
    inv = 1.0 / (state.loss_scale * jnp.float32(n))
    grads = jax.tree_util.tree_map(
        lambda a, p: (a * inv).astype(p.dtype), acc, params)
    loss = scaled_sum / (jnp.float32(n) * state.loss_scale)
    _tape.emit("amp/found_inf", found_inf, reduce="max")
    _tape.emit("amp/loss_scale", state.loss_scale)
    _tape.emit("loss", loss)
    if has_aux:
        return (loss, auxes), grads, found_inf
    return loss, grads, found_inf


def conditional_step(state: LossScaleState, found_inf: jax.Array,
                     step_fn, params: Pytree, opt_state: Pytree,
                     config: LossScaleConfig = LossScaleConfig()
                     ) -> Tuple[Pytree, Pytree, LossScaleState]:
    """Apply ``step_fn(params, opt_state) -> (params, opt_state)`` only when
    grads were finite; always update scaler state.  The skip is a
    lax.cond — no host sync (contrast: reference optimizer.step patching in
    apex/amp/_process_optimizer.py reads the flag on host)."""
    def do_step(operand):
        p, s = operand
        return step_fn(p, s)

    def skip(operand):
        return operand

    params, opt_state = jax.lax.cond(
        found_inf == 0, do_step, skip, (params, opt_state))
    return params, opt_state, update_state(state, found_inf, config)


class LossScaler:
    """Reference-shaped stateful facade over the functional core
    (apex/amp/scaler.py::LossScaler).  Host-side convenience only; jitted
    code should use the functional API above."""

    def __init__(self, loss_scale="dynamic", init_scale=2.0 ** 16,
                 scale_factor=2.0, scale_window=2000,
                 min_loss_scale=None, max_loss_scale=2.0 ** 24):
        self._dynamic = loss_scale == "dynamic"
        init = init_scale if self._dynamic else float(loss_scale)
        self.config = LossScaleConfig(
            init_scale=init,
            growth_factor=scale_factor,
            backoff_factor=1.0 / scale_factor,
            growth_interval=scale_window,
            min_loss_scale=min_loss_scale if min_loss_scale is not None else 1.0,
            max_loss_scale=max_loss_scale,
            dynamic=self._dynamic,
        )
        self.state = LossScaleState.create(init)

    def loss_scale(self):
        return float(self.state.loss_scale)

    def scale(self, loss):
        return scale_loss(loss, self.state)

    def unscale(self, grads):
        return unscale_grads(grads, self.state)

    def update_scale(self, found_inf):
        self.state = update_state(self.state,
                                  jnp.asarray(found_inf, jnp.int32),
                                  self.config)

    # apex serialization contract (amp.state_dict round-trips scaler state)
    def state_dict(self):
        return {
            "loss_scale": float(self.state.loss_scale),
            "unskipped": int(self.state.growth_tracker),
        }

    def load_state_dict(self, sd):
        self.state = LossScaleState(
            loss_scale=jnp.float32(sd["loss_scale"]),
            growth_tracker=jnp.int32(sd.get("unskipped", 0)),
            found_inf=jnp.int32(0),
        )
