"""amp.initialize and the amp serialization contract, JAX-native.

Reference: apex/amp/frontend.py + _initialize.py + _amp_state.py
(SURVEY.md §3.1).  The reference mutates torch models/optimizers in place
(weight casts, forward patching, optimizer.step patching).  The JAX
contract is functional: ``initialize`` takes a params pytree, returns the
cast params plus an ``AmpState`` carrying the policy, optional f32
masters, and the loss-scaler state; train steps thread AmpState through.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp.policies import (Policy, Properties, opt_level_properties)
from apex_tpu.amp.scaler import (LossScaleConfig, LossScaleState,
                                 re_anchor, update_state)
from apex_tpu.amp.wrap import auto_cast, cast_inputs

Pytree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AmpState:
    """Carried amp state (a pytree; static config in `properties`)."""
    master_params: Optional[Pytree]
    scaler: LossScaleState
    properties: Properties = dataclasses.field(
        metadata=dict(static=True), default_factory=Properties)
    scaler_config: LossScaleConfig = dataclasses.field(
        metadata=dict(static=True), default_factory=LossScaleConfig)

    @property
    def policy(self) -> Policy:
        return self.properties.policy(self._half_dtype())

    def wrap_forward(self, fn, cast_argnums=None):
        """Apply this opt level's casting mechanism to an UNMODIFIED
        forward function — the reference's model-patching step
        (apex/amp/_initialize.py) as a functional wrapper.

        O1 (patch_torch_functions): the trace-time op-list rewriter.
        O2/O3 (cast_model_type set): cast floating inputs (restricted to
        ``cast_argnums`` positions if given — the data args) to the
        model half dtype.  O0 / disabled: identity.
        """
        props = self.properties
        if not props.enabled:
            return fn
        if props.patch_torch_functions:
            return auto_cast(fn, self.policy)
        if props.cast_model_type is not None:
            return cast_inputs(fn, props.cast_model_type, cast_argnums)
        return fn

    def _half_dtype(self):
        cast = self.properties.cast_model_type
        return cast if cast is not None else jnp.bfloat16

    @property
    def fp8_policy(self):
        """The armed :class:`apex_tpu.amp.fp8.Fp8Policy` (None when
        this state was initialized without ``fp8=``) — hand it to
        fp8-capable modules (``FusedDense(fp8=state.fp8_policy)``,
        the tensor-parallel linears)."""
        return getattr(self.properties, "fp8", None)

    def flat_pipeline(self, optimizer=None, plan=None,
                      max_grad_norm: float = 0.0, axis_name=None,
                      **kw):
        """A :class:`~apex_tpu.amp.flat_pipeline.FlatGradPipeline` for
        this amp state — the pack-once gradient path (one fused
        unscale+norm+clip kernel per bucket, bucket-granular
        all-reduce) feeding a bucketed fused optimizer.  Call its
        ``scaled_value_and_grad(loss_fn, amp_state_or_scaler, ...)``
        with this state's ``scaler`` threaded through the train step.
        """
        from apex_tpu.amp.flat_pipeline import FlatGradPipeline
        kw.setdefault("fp8", self.fp8_policy)
        return FlatGradPipeline(optimizer=optimizer, plan=plan,
                                max_grad_norm=max_grad_norm,
                                axis_name=axis_name, **kw)

    def re_anchor(self, scale=None) -> "AmpState":
        """This state with its scaler reset to a known-safe operating
        point (:func:`apex_tpu.amp.scaler.re_anchor`) — the watchdog's
        quarantine action after a NaN storm or scale collapse."""
        return dataclasses.replace(
            self, scaler=re_anchor(self.scaler, self.scaler_config,
                                   scale))

    def telemetry_values(self) -> dict:
        """This state's scaler scalars under their standard telemetry
        names (still on device — no sync), ready for
        ``Telemetry.record`` in eager train loops; jitted steps get
        the same names for free via the producers inside
        ``scaled_value_and_grad``/``update_state``."""
        return {"amp/loss_scale": self.scaler.loss_scale,
                "amp/growth_tracker": self.scaler.growth_tracker,
                "amp/found_inf": self.scaler.found_inf}

    # --- apex serialization contract: amp.state_dict() round-trips the
    # loss scaler (scale + unskipped count), frontend.py parity ---
    def state_dict(self):
        return {
            "loss_scaler0": {
                "loss_scale": float(self.scaler.loss_scale),
                "unskipped": int(self.scaler.growth_tracker),
            }
        }

    def load_state_dict(self, sd):
        entry = sd.get("loss_scaler0", {})
        return dataclasses.replace(
            self,
            scaler=LossScaleState(
                loss_scale=jnp.float32(entry.get("loss_scale",
                                                 self.scaler_config.init_scale)),
                growth_tracker=jnp.int32(entry.get("unskipped", 0)),
                found_inf=jnp.int32(0),
            ))


def initialize(params: Pytree,
               opt_level: str = "O1",
               half_dtype=jnp.bfloat16,
               cast_model_type=None,
               keep_batchnorm_fp32=None,
               master_weights=None,
               loss_scale: Union[str, float, None] = None,
               enabled: bool = True,
               fp8=None,
               ) -> Tuple[Pytree, AmpState]:
    """Resolve an opt level to a precision configuration and cast params.

    Mirrors apex.amp.initialize's signature shape (model, optimizers →
    params pytree here); per-kwarg overrides beat the table defaults, as in
    the reference.  Returns (cast_params, amp_state).

    ``fp8`` (beyond-reference): an ``amp.fp8.Fp8Policy`` (or ``True``
    for the autotuned defaults) arms the fp8 training path on top of
    the opt level — matmul-shaped modules built with
    ``fp8=state.fp8_policy`` quantize to e4m3 forward / e5m2 backward
    under delayed scaling, and ``state.flat_pipeline()`` threads the
    packed per-bucket scale state (docs/amp.md "fp8 training").
    Params still cast per the opt level (fp8 is a COMPUTE format, not
    a storage format — weights stay bf16/f16 masters-backed).
    """
    props = opt_level_properties(opt_level, half_dtype)
    if cast_model_type is not None:
        props.cast_model_type = cast_model_type
    if keep_batchnorm_fp32 is not None:
        props.keep_batchnorm_fp32 = keep_batchnorm_fp32
    if master_weights is not None:
        props.master_weights = master_weights
    if loss_scale is not None:
        props.loss_scale = loss_scale
    props.enabled = enabled
    if fp8 is not None and fp8 is not False:
        from apex_tpu.amp.fp8 import Fp8Policy, tuned_policy
        if fp8 is True:
            fp8 = tuned_policy()
        if not isinstance(fp8, Fp8Policy):
            raise TypeError(
                f"fp8= expects an amp.fp8.Fp8Policy or True, got "
                f"{type(fp8).__name__}")
        props.fp8 = fp8
    if not enabled:
        return params, AmpState(master_params=None,
                                scaler=LossScaleState.create(1.0),
                                properties=props,
                                scaler_config=LossScaleConfig(dynamic=False))

    masters = None
    cast_params = params
    if props.cast_model_type is not None:
        cast_params = jax.tree_util.tree_map(
            lambda x: x.astype(props.cast_model_type)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        if props.master_weights:
            masters = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.float32)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)

    dynamic = props.loss_scale == "dynamic"
    init_scale = 2.0 ** 16 if dynamic else float(props.loss_scale)
    cfg = LossScaleConfig(init_scale=init_scale, dynamic=dynamic)
    scaler = LossScaleState.create(init_scale)
    return cast_params, AmpState(master_params=masters, scaler=scaler,
                                 properties=props, scaler_config=cfg)


def master_params_to_model_params(model_params: Pytree,
                                  master_params: Pytree) -> Pytree:
    """Copy f32 masters back into the model-dtype params (O2 step tail).

    Reference: apex/amp/_process_optimizer.py::_master_params_to_model_params.
    """
    return jax.tree_util.tree_map(
        lambda mp, m: m.astype(mp.dtype), model_params, master_params)


def update_scaler(state: AmpState, found_inf, skipped=None) -> AmpState:
    """``skipped``: the step was skipped externally (watchdog
    quarantine) — the growth tracker holds instead of counting the
    window as clean (:func:`apex_tpu.amp.scaler.update_state`)."""
    return dataclasses.replace(
        state, scaler=update_state(state.scaler,
                                   jnp.asarray(found_inf, jnp.int32),
                                   state.scaler_config,
                                   skipped=skipped))


def state_dict(*states: AmpState) -> dict:
    """Serialize N amp states as the reference's multi-scaler layout.

    apex's ``amp.initialize(..., num_losses=N)`` keeps N scalers and
    ``amp.state_dict()`` emits ``{'loss_scaler0': ..., 'loss_scalerN':
    ...}`` (frontend.py).  The functional analog of num_losses is one
    AmpState per loss (see examples/dcgan); this helper merges them into
    the same reference-shaped dict so checkpoints port unchanged.
    """
    out = {}
    for i, s in enumerate(states):
        out[f"loss_scaler{i}"] = s.state_dict()["loss_scaler0"]
    return out


def load_state_dict(sd: dict, *states: AmpState):
    """Inverse of ``state_dict(*states)``: returns the restored states
    (a single AmpState when one was passed, else a tuple in order).
    Warns on a scaler-count mismatch (reference behavior) — missing
    entries leave that state's scaler at its config default."""
    import warnings
    saved = sum(1 for k in sd if k.startswith("loss_scaler"))
    if saved != len(states):
        warnings.warn(
            f"amp.load_state_dict: checkpoint has {saved} loss scaler(s) "
            f"but {len(states)} AmpState(s) were passed; unmatched "
            "states keep their initial scale", stacklevel=2)
    restored = tuple(
        s.load_state_dict({"loss_scaler0": sd.get(f"loss_scaler{i}", {})})
        for i, s in enumerate(states))
    return restored[0] if len(restored) == 1 else restored
