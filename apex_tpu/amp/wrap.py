"""The O1 casting engine: a trace-time precision rewriter.

Reference: apex/amp/wrap.py + utils.py + opt.py (~900 LoC, SURVEY.md
§2.1): the reference monkey-patches every listed torch function with a
wrapper that casts inputs per the FP16/FP32 lists and caches parameter
casts.  Monkey-patching has no JAX analog — but it doesn't need one:
under `jit` every op is already visible at trace time.  ``auto_cast``
traces the UNMODIFIED function to a jaxpr, then re-evaluates it with
per-primitive dtype rules from apex_tpu.amp.lists:

- HALF_PRIMS (GEMM/conv)        -> operands cast to compute_dtype
- FP32_PRIMS (exp/log/sums/...) -> operands cast to f32
- everything else               -> mixed float operands promote to the
                                   widest dtype present (reference CASTS)

so ``amp.initialize(..., "O1")`` changes an arbitrary model's precision
with zero edits to the model.  The rewrite composes with jit/grad/vmap
(it is itself a tracing transform), and the reference's "cast cache"
falls out for free: a param cast appearing once in the jaxpr is one op
in the compiled program, CSE'd and fused by XLA.

Call-like primitives are recursed into (pjit/remat/custom_jvp), and so
is structured control flow: ``scan`` / ``while`` / ``cond`` bodies are
re-traced with the same per-primitive rules, with loop state cast back
to its traced dtype at every iteration boundary so the loop stays
well-typed (the reference reaches ops inside RNN loops the same way,
via rnn_compat).  Only genuinely dtype-bound opaque primitives
(custom_vjp, pallas_call — e.g. this package's own kernels, which
already manage precision internally) run unmodified at their traced
dtypes.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp import lists
from apex_tpu.amp.policies import Policy

# jax.extend.core is the supported home for jaxpr types in newer jax
try:
    from jax.extend.core import ClosedJaxpr, Literal
except ImportError:  # pragma: no cover - older jax
    from jax.core import ClosedJaxpr, Literal


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.floating)


def _cast_floats(vals, dtype):
    return [v.astype(dtype) if _is_float(v)
            and jnp.result_type(v) != dtype else v for v in vals]


def _promote_floats(vals):
    """Reference CASTS semantics: widen mixed float operands."""
    fdts = {jnp.result_type(v) for v in vals if _is_float(v)}
    if len(fdts) <= 1:
        return vals
    widest = functools.reduce(jnp.promote_types, fdts)
    return _cast_floats(vals, widest)


def _restore_dtypes(vals, invars):
    """Cast drifted operands back to the dtypes the eqn was traced at
    (used for opaque primitives whose sub-jaxprs are dtype-bound)."""
    out = []
    for v, var in zip(vals, invars):
        aval = var.aval
        if (_is_float(v) and hasattr(aval, "dtype")
                and jnp.result_type(v) != aval.dtype):
            v = v.astype(aval.dtype)
        out.append(v)
    return out


def _half_params(params, half):
    """For HALF prims: drop a traced f32 accumulation hint so the output
    comes back in compute dtype (XLA still accumulates bf16 dots in f32
    on the MXU)."""
    if params.get("preferred_element_type") is not None:
        p = dict(params)
        if jnp.issubdtype(p["preferred_element_type"], jnp.floating):
            p["preferred_element_type"] = jnp.dtype(half)
        return p
    return params


def _cast_to_dtypes(vals, dtypes):
    """Cast each float val back to its traced dtype (None = leave)."""
    return [v.astype(d) if d is not None and _is_float(v)
            and jnp.result_type(v) != d else v
            for v, d in zip(vals, dtypes)]


def _aval_dtypes(vars_):
    return [v.aval.dtype for v in vars_]


def _rewrite_scan(vals, params, half):
    """Re-issue a scan with its body O1-rewritten.  The carry is cast
    back to its traced dtype each iteration (dtype-coherent boundary);
    ops INSIDE the body follow the normal HALF/FP32/promote rules."""
    body = params["jaxpr"]                      # ClosedJaxpr
    C, K = params["num_consts"], params["num_carry"]
    consts, init, xs = vals[:C], vals[C:C + K], vals[C + K:]
    carry_dts = _aval_dtypes(body.jaxpr.invars[C:C + K])

    def new_body(carry, x):
        ins = list(consts) + list(carry) + list(x)
        outs = _eval_jaxpr(body.jaxpr, body.consts, ins, half)
        return (tuple(_cast_to_dtypes(outs[:K], carry_dts)),
                tuple(outs[K:]))

    carry_out, ys = jax.lax.scan(
        new_body, tuple(init), tuple(xs), length=params.get("length"),
        reverse=params.get("reverse", False),
        unroll=params.get("unroll", 1))
    return list(carry_out) + list(ys)


def _rewrite_while(vals, params, half):
    """Re-issue a while_loop with cond/body O1-rewritten; loop state is
    cast back to its traced dtype after every body application."""
    cj, bj = params["cond_jaxpr"], params["body_jaxpr"]
    cn, bn = params["cond_nconsts"], params["body_nconsts"]
    cc, bc, init = vals[:cn], vals[cn:cn + bn], vals[cn + bn:]
    carry_dts = _aval_dtypes(bj.jaxpr.invars[bn:])

    def cond_fn(carry):
        return _eval_jaxpr(cj.jaxpr, cj.consts,
                           list(cc) + list(carry), half)[0]

    def body_fn(carry):
        outs = _eval_jaxpr(bj.jaxpr, bj.consts,
                           list(bc) + list(carry), half)
        return tuple(_cast_to_dtypes(outs, carry_dts))

    return list(jax.lax.while_loop(cond_fn, body_fn, tuple(init)))


def _rewrite_cond(vals, params, outvars, half):
    """Re-issue a cond/switch with every branch O1-rewritten.  Branch
    outputs are cast back to the traced output dtypes — the branches
    must agree on out avals, and after an asymmetric rewrite (a GEMM in
    one branch, a pass-through in the other) they wouldn't."""
    out_dts = [getattr(v.aval, "dtype", None) for v in outvars]
    idx, ops = jnp.asarray(vals[0]), vals[1:]
    if idx.dtype == jnp.bool_:
        idx = idx.astype(jnp.int32)

    def mk(br):
        def f(*ops_):
            outs = _eval_jaxpr(br.jaxpr, br.consts, list(ops_), half)
            return tuple(_cast_to_dtypes(outs, out_dts))
        return f

    return list(jax.lax.switch(idx, [mk(b) for b in params["branches"]],
                               *ops))


def _iter_sub_jaxprs(params):
    """Yield every (Closed)Jaxpr reachable from an eqn's params —
    wherever the primitive stashed it (jaxpr/call_jaxpr/branches/
    cond_jaxpr/...), including inside lists/tuples.  Thunks and other
    callables are not forced."""
    stack = list(params.values())
    while stack:
        v = stack.pop()
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            stack.extend(v)
        elif hasattr(v, "eqns") and hasattr(v, "invars"):  # raw Jaxpr
            yield v


def _contains_half_prims(jaxpr) -> bool:
    """Does this sub-jaxpr reach any HALF-list op (GEMM/conv) that O1
    would have rewritten?  ``pallas_call`` interiors don't count: a
    kernel body's dtypes are chosen explicitly by its author (this
    package's kernels manage precision internally), so dots inside one
    are not missed casts."""
    for eqn in jaxpr.eqns:
        nm = eqn.primitive.name
        if nm == "pallas_call":
            continue
        if nm in lists.HALF_PRIMS:
            return True
        for sub in _iter_sub_jaxprs(eqn.params):
            if _contains_half_prims(sub):
                return True
    return False


_OPAQUE_WARNED: set = set()


def _invar_sig(invars):
    return tuple((getattr(v.aval, "shape", None),
                  str(getattr(v.aval, "dtype", None))) for v in invars)


def _body_sig(params, cap=64):
    """Light content fingerprint of an opaque primitive's body: the
    primitive-name sequence of its sub-jaxprs (capped).  Distinguishes
    two different user ops that happen to share operand shapes; two
    ops identical in BOTH operands and op sequence would produce the
    same warning text anyway."""
    names = []
    for sub in _iter_sub_jaxprs(params):
        for eqn in sub.eqns:
            names.append(eqn.primitive.name)
            if len(names) >= cap:
                return tuple(names)
    return tuple(names)


def _warn_opaque(name: str, params, invars) -> None:
    """Honesty warning (VERDICT r3 #4): an opaque primitive whose body
    contains listed GEMMs runs UNREWRITTEN under O1 — the user should
    hear that, not discover it in a profile.  Deduped per (primitive,
    operand signature, body fingerprint) so DISTINCT skipped ops each
    warn once (every user custom_vjp shares one primitive name).  A
    direct pallas_call is itself a kernel body — precision-explicit by
    design, never warned about."""
    if name == "pallas_call":
        return
    key = (name, _invar_sig(invars), _body_sig(params))
    if key in _OPAQUE_WARNED:
        return
    if any(_contains_half_prims(s) for s in _iter_sub_jaxprs(params)):
        _OPAQUE_WARNED.add(key)
        warnings.warn(
            f"amp O1: primitive '{name}' (operands "
            f"{[s for s, _ in key[1]]}) is opaque to the casting "
            "engine but its body contains matmul/conv ops that would "
            "otherwise run in the compute dtype; they will run at "
            "their traced (likely f32) precision. Cast its inputs "
            "explicitly, or apply apex_tpu.amp.auto_cast inside the "
            "custom function, to opt those ops into mixed precision.",
            stacklevel=2)


def _bind(prim, vals, params):
    """Re-issue an eqn the way core.eval_jaxpr does: get_bind_params
    recovers callable sub-arguments (custom_vjp's fun/fwd/bwd, ...)
    that live in eqn.params but bind positionally."""
    subfuns, bind_params = prim.get_bind_params(params)
    ans = prim.bind(*subfuns, *vals, **bind_params)
    return ans if prim.multiple_results else [ans]


def _eval_jaxpr(jaxpr, consts, args, half):
    env = {}

    def read(a):
        return a.val if isinstance(a, Literal) else env[a]

    for v, c in zip(jaxpr.constvars, consts):
        env[v] = c
    for v, a in zip(jaxpr.invars, args):
        env[v] = a

    for eqn in jaxpr.eqns:
        prim = eqn.primitive
        name = prim.name
        vals = [read(x) for x in eqn.invars]
        params = eqn.params

        if name in lists.RECURSE_PRIMS:
            sub = params.get("jaxpr") or params.get("call_jaxpr")
            if sub is not None:
                if isinstance(sub, ClosedJaxpr):
                    ans = _eval_jaxpr(sub.jaxpr, sub.consts, vals, half)
                else:
                    ans = _eval_jaxpr(sub, (), vals, half)
            else:  # unexpected shape: run opaque
                ans = _bind(prim, _restore_dtypes(vals, eqn.invars),
                            params)
        elif name in lists.HALF_PRIMS:
            ans = _bind(prim, _cast_floats(vals, half),
                        _half_params(params, half))
        elif name in lists.FP32_PRIMS:
            ans = _bind(prim, _cast_floats(vals, jnp.float32), params)
        elif name == "scan" and "jaxpr" in params:
            ans = _rewrite_scan(_restore_dtypes(vals, eqn.invars),
                                params, half)
        elif name == "while" and "body_jaxpr" in params:
            ans = _rewrite_while(_restore_dtypes(vals, eqn.invars),
                                 params, half)
        elif name == "cond" and "branches" in params:
            ans = _rewrite_cond(_restore_dtypes(vals, eqn.invars),
                                params, eqn.outvars, half)
        elif "jaxpr" in params or "call_jaxpr" in params or \
                "branches" in params or "cond_jaxpr" in params or \
                "fwd_jaxpr_thunk" in params or "num_consts" in params:
            # opaque (custom_vjp, pallas_call, ...): dtype-bound bodies
            _warn_opaque(name, params, eqn.invars)
            ans = _bind(prim, _restore_dtypes(vals, eqn.invars), params)
        else:
            ans = _bind(prim, _promote_floats(vals), params)

        for v, a in zip(eqn.outvars, ans):
            env[v] = a

    return [read(v) for v in jaxpr.outvars]


def auto_cast(fn: Callable, policy: Optional[Policy] = None,
              compute_dtype: Any = None) -> Callable:
    """Wrap ``fn`` so listed ops run at the policy's precision.

    The O1 engine: ``fn`` is any jax-traceable callable (a flax
    ``model.apply``, a bare function, a whole train-step body).  Returns
    a callable computing the same function with GEMMs/convs in
    ``compute_dtype`` and fragile ops in f32, per apex_tpu.amp.lists.

    No-op (returns ``fn`` unchanged) when the compute dtype is f32.
    """
    half = jnp.dtype(compute_dtype if compute_dtype is not None
                     else (policy.compute_dtype if policy is not None
                           else jnp.bfloat16))
    if half == jnp.dtype(jnp.float32):
        return fn

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        flat, in_tree = jax.tree_util.tree_flatten((args, kwargs))

        def flat_fn(*xs):
            a, kw = jax.tree_util.tree_unflatten(in_tree, xs)
            return fn(*a, **kw)

        closed, out_shape = jax.make_jaxpr(
            flat_fn, return_shape=True)(*flat)
        out_tree = jax.tree_util.tree_structure(out_shape)
        outs = _eval_jaxpr(closed.jaxpr, closed.consts, flat, half)
        return jax.tree_util.tree_unflatten(out_tree, outs)

    return wrapped


def cast_inputs(fn: Callable, dtype, argnums=None) -> Callable:
    """O2/O3 forward patch: cast floating inputs to the model dtype.

    Reference: apex/amp/_initialize.py patches ``model.forward`` to cast
    ``*args`` to the cast_model_type; this is the functional analog.
    ``argnums`` restricts casting to those positional args — functional
    code passes params/state as arguments too, and only the DATA inputs
    play the role of the reference's forward(*args).
    """
    dtype = jnp.dtype(dtype)

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        cast = lambda x: (x.astype(dtype)
                          if hasattr(x, "dtype") and _is_float(x) else x)
        if argnums is None:
            args = jax.tree_util.tree_map(cast, args)
            kwargs = jax.tree_util.tree_map(cast, kwargs)
        else:
            args = tuple(jax.tree_util.tree_map(cast, a)
                         if i in argnums else a
                         for i, a in enumerate(args))
        return fn(*args, **kwargs)

    return wrapped
