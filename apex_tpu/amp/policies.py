"""Precision policies: the O0-O3 opt-level tables.

Reference: apex/amp/frontend.py (Properties + O0..O3 option bundles,
SURVEY.md §3.1).  The reference implements O1 by monkey-patching torch
functions per whitelist/blacklist; on TPU the same contract becomes a
tracing-time dtype policy consulted by modules: matmul/conv-shaped ops run
in ``compute_dtype`` (bf16 → MXU), reductions/norms/losses in f32, params
stored in ``param_dtype`` with optional f32 masters.

bf16 replaces fp16 as the half type: same MXU throughput, fp32-range
exponent, so O2's *dynamic* loss scaling degenerates to static scale 1.0
by default (the scaler API is kept — fp16 is still selectable).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

Dtype = Any


@dataclasses.dataclass(frozen=True)
class Policy:
    """jmp-style dtype policy applied at trace time."""
    param_dtype: Dtype = jnp.float32
    compute_dtype: Dtype = jnp.float32
    output_dtype: Dtype = jnp.float32
    # master_weights: keep an f32 copy updated by the optimizer while the
    # model computes with param_dtype (reference O2 semantics)
    master_weights: bool = False
    # keep norms/statistics in f32 regardless of compute dtype
    keep_norm_fp32: bool = True

    def cast_to_compute(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_to_param(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_to_output(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.output_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


@dataclasses.dataclass
class Properties:
    """Reference-shaped option bundle (apex/amp/frontend.py::Properties).

    ``fp8``: beyond-reference — an :class:`apex_tpu.amp.fp8.Fp8Policy`
    extends the opt level with e4m3/e5m2 matmuls under delayed
    scaling (``amp.initialize(opt_level="O3", fp8=Fp8Policy())``);
    None keeps the bf16/f16 ceiling."""
    opt_level: str = "O0"
    cast_model_type: Optional[Dtype] = None
    patch_torch_functions: bool = False
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Union[str, float] = 1.0
    enabled: bool = True
    fp8: Optional[Any] = None

    def policy(self, half_dtype: Dtype = jnp.bfloat16) -> Policy:
        half = half_dtype
        if self.opt_level == "O0":
            return Policy(jnp.float32, jnp.float32, jnp.float32,
                          master_weights=False)
        if self.opt_level == "O1":
            # params stay f32; selected ops compute in half
            return Policy(jnp.float32, half, jnp.float32,
                          master_weights=False)
        if self.opt_level == "O2":
            return Policy(half, half, jnp.float32, master_weights=True,
                          keep_norm_fp32=bool(self.keep_batchnorm_fp32))
        if self.opt_level == "O3":
            return Policy(half, half, half, master_weights=False,
                          keep_norm_fp32=False)
        raise ValueError(f"unknown opt_level {self.opt_level!r}")


def opt_level_properties(opt_level: str,
                         half_dtype: Dtype = jnp.bfloat16) -> Properties:
    """The reference's O0..O3 defaults (apex/amp/frontend.py tables)."""
    fp16_like = half_dtype == jnp.float16
    default_dynamic = "dynamic" if fp16_like else 1.0
    tables = {
        "O0": Properties("O0", None, False, None, False, 1.0),
        "O1": Properties("O1", None, True, None, None, default_dynamic),
        "O2": Properties("O2", half_dtype, False, True, True,
                         "dynamic" if fp16_like else default_dynamic),
        "O3": Properties("O3", half_dtype, False, False, False, 1.0),
    }
    if opt_level not in tables:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r}; "
            "options are 'O0', 'O1', 'O2', 'O3'.")
    return tables[opt_level]
