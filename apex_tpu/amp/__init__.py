"""apex_tpu.amp — automatic mixed precision (reference: apex/amp).

JAX-native surface:
  initialize(params, opt_level=...)       -> (params, AmpState)
  scaled_value_and_grad(loss_fn, state..) -> loss, unscaled grads, found_inf
  conditional_step / update_state         -> scaler-driven skip logic
  FlatGradPipeline / FlatGrads            -> pack-once flat gradient path
                                             (grads_layout="flat"; one fused
                                             unscale+norm+clip kernel per
                                             bucket, docs/amp.md)
  Policy / Properties / opt_level_properties

The reference's op-classification lists (which torch ops run fp16 vs fp32,
apex/amp/lists/) live in apex_tpu.amp.lists and drive both the torch-CPU
compatibility frontend and the JAX policy's notion of "norm-like" ops.
"""

from apex_tpu.amp.policies import Policy, Properties, opt_level_properties
from apex_tpu.amp.scaler import (
    LossScaler,
    LossScaleConfig,
    LossScaleState,
    check_finite,
    conditional_step,
    re_anchor,
    scale_loss,
    scaled_value_and_grad,
    unscale_grads,
    update_state,
)
from apex_tpu.amp.frontend import (
    AmpState,
    initialize,
    load_state_dict,
    master_params_to_model_params,
    state_dict,
    update_scaler,
)
from apex_tpu.amp.flat_pipeline import FlatGradPipeline, FlatGrads, \
    GradAccum
from apex_tpu.amp.wrap import auto_cast, cast_inputs
from apex_tpu.amp import lists
from apex_tpu.amp import fp8
from apex_tpu.amp.fp8 import Fp8Policy, Fp8State

__all__ = [
    "Policy", "Properties", "opt_level_properties",
    "LossScaler", "LossScaleConfig", "LossScaleState",
    "check_finite", "conditional_step", "re_anchor", "scale_loss",
    "scaled_value_and_grad", "unscale_grads", "update_state",
    "AmpState", "initialize", "master_params_to_model_params",
    "update_scaler", "state_dict", "load_state_dict",
    "FlatGradPipeline", "FlatGrads", "GradAccum",
    "Fp8Policy", "Fp8State", "fp8",
    "auto_cast", "cast_inputs", "lists",
]
