"""amp op-classification lists, as data.

Reference: apex/amp/lists/{functional_overrides,torch_overrides,
tensor_overrides}.py (~400 LoC of torch function names split into
FP16_FUNCS / FP32_FUNCS / CASTS — SURVEY.md §2.1).  The reference
classifies *torch functions* because its engine monkey-patches them; the
TPU engine (apex_tpu.amp.wrap) rewrites *jax primitives* at trace time,
so the lists here classify primitive names.  The function-level names
are kept alongside as documentation of parity with the reference's
tables.

Three classes, same semantics as the reference:

- HALF (reference FP16_FUNCS): tensor-core/MXU-shaped ops — run in the
  policy's compute dtype.  GEMMs and convolutions.
- FP32 (reference FP32_FUNCS): numerically fragile ops — transcendental
  / accumulation-heavy — always run in f32.
- everything else (reference CASTS): type-promote so mixed-precision
  operands widen to the widest floating dtype present.
"""

from __future__ import annotations

# --- primitive-level tables (consumed by apex_tpu.amp.wrap) ---

# MXU ops: run in compute dtype (reference FP16_FUNCS: conv*, linear,
# matmul, mm, bmm, addmm, ...)
HALF_PRIMS = frozenset({
    "dot_general",
    "conv_general_dilated",
    "ragged_dot_general",
})

# fragile ops: pin to f32 (reference FP32_FUNCS: softmax, log_softmax,
# exp, log, pow, norm, cumsum, losses, ...).  Pinning the primitive
# decomposition — exp/log/rsqrt/sums — covers the reference's
# function-level entries (softmax = max/sub/exp/sum/div; layer_norm =
# mean/rsqrt; cross_entropy = log_softmax + gather; norm = square/sum/
# sqrt) without needing to recognize whole functions.
FP32_PRIMS = frozenset({
    "exp", "exp2", "log", "log1p", "expm1",
    "pow", "rsqrt", "sqrt", "cbrt",
    "erf", "erfc", "erf_inv", "lgamma", "digamma",
    "logistic", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "asinh", "acosh", "atanh",
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
})

# call-like primitives the rewriter recurses into (their body is just
# more jaxpr)
RECURSE_PRIMS = frozenset({
    "pjit", "jit", "closed_call", "core_call",
    "remat", "remat2", "checkpoint",   # jax 0.9 names remat 'remat2'
    "custom_jvp_call", "custom_jvp_call_jaxpr",
})

# --- reference-table documentation (function-level names, for parity
# auditing against apex/amp/lists/*.py; not consumed by the engine) ---

FP16_FUNCS = [
    # functional_overrides.FP16_FUNCS / torch_overrides.FP16_FUNCS
    "conv1d", "conv2d", "conv3d", "conv_transpose1d", "conv_transpose2d",
    "conv_transpose3d", "conv_tbc", "linear", "addmm", "addmv", "addr",
    "matmul", "mm", "mv", "bmm", "baddbmm", "addbmm", "prelu",
]

FP32_FUNCS = [
    # functional_overrides.FP32_FUNCS / torch_overrides.FP32_FUNCS
    "acos", "asin", "cosh", "erfinv", "exp", "expm1", "log", "log10",
    "log2", "log1p", "reciprocal", "rsqrt", "sinh", "tan", "pow",
    "softplus", "gelu", "layer_norm", "group_norm", "local_response_norm",
    "normalize", "softmin", "softmax", "log_softmax", "cosine_similarity",
    "poisson_nll_loss", "cosine_embedding_loss", "cross_entropy",
    "hinge_embedding_loss", "kl_div", "l1_loss", "mse_loss",
    "margin_ranking_loss", "multilabel_margin_loss", "soft_margin_loss",
    "triplet_margin_loss", "multi_margin_loss", "nll_loss",
    "binary_cross_entropy_with_logits", "smooth_l1_loss", "cumprod",
    "cumsum", "dist", "norm", "prod", "renorm", "sum",
]

CASTS = [
    # promote-to-widest ops (torch_overrides.CASTS)
    "addcdiv", "addcmul", "atan2", "cross", "bilinear", "dot", "vdot",
    "add", "div", "mul", "sub", "eq", "equal", "ge", "gt", "le", "lt",
    "ne",
]

# banned in fp16 without scaling (reference raises/warns):
# binary_cross_entropy — covered here by the FP32 pin on its log/exp
SEQUENCE_CASTS = ["cat", "stack"]
