"""Elastic resilience: crash-safe checkpoint rotation, preemption-safe
restart, an in-job supervisor loop, and a deterministic fault-injection
harness (SURVEY.md §5: the failure story the reference lacks).

- :mod:`~apex_tpu.resilience.manager` — :class:`CheckpointManager`
  (rotating async checkpoints, bucket-native v2 when the optimizer runs
  bucketed, multi-host lockstep ``restore_latest``);
- :mod:`~apex_tpu.resilience.preemption` — :class:`PreemptionGuard`
  (SIGTERM / ``--preempt-at-step`` -> save-now-then-clean-exit at the
  next step boundary);
- :mod:`~apex_tpu.resilience.elastic` — :func:`run_elastic`, the
  supervisor loop tying restore + cadence saves + bounded
  retry-with-backoff + preemption together;
- :mod:`~apex_tpu.resilience.faults` — :class:`FaultInjector`
  (seeded schedules of torn writes, fsync errors, slow disks,
  preemption signals and crash-before-publish, injected through the
  :class:`apex_tpu.checkpoint.CheckpointIO` seam).
"""

from apex_tpu.resilience.elastic import ElasticResult, run_elastic
from apex_tpu.resilience.manager import CheckpointManager
from apex_tpu.resilience.preemption import PreemptionGuard

__all__ = [
    "CheckpointManager",
    "ElasticResult",
    "PreemptionGuard",
    "run_elastic",
]
