"""Elastic resilience: crash-safe checkpoint rotation, preemption-safe
restart, an in-job supervisor loop, and a deterministic fault-injection
harness (SURVEY.md §5: the failure story the reference lacks).

- :mod:`~apex_tpu.resilience.manager` — :class:`CheckpointManager`
  (rotating async checkpoints, bucket-native v2 when the optimizer runs
  bucketed, multi-host lockstep ``restore_latest``);
- :mod:`~apex_tpu.resilience.preemption` — :class:`PreemptionGuard`
  (SIGTERM / ``--preempt-at-step`` -> save-now-then-clean-exit at the
  next step boundary);
- :mod:`~apex_tpu.resilience.elastic` — :func:`run_elastic`, the
  supervisor loop tying restore + cadence saves + bounded
  retry-with-backoff + preemption together;
- :mod:`~apex_tpu.resilience.watchdog` — :class:`Watchdog`
  (anomaly detectors over the telemetry ring's window flushes, the
  escalation policy quarantine -> rollback-to-last-known-good ->
  abort-with-diagnostics, executed through ``run_elastic``);
- :mod:`~apex_tpu.resilience.retry` — :class:`RetryPolicy`
  (bounded widening backoff, shared by ``run_elastic``'s transient
  retries and the watchdog's rollback budget);
- :mod:`~apex_tpu.resilience.fleet` — :class:`FleetMonitor`
  (out-of-band host liveness beacons classified live/slow/dead with
  sticky-dead keyed on ``(host, incarnation)``, typed
  :class:`HostFailure` events, the barrier-free survivor AND
  admission agreement rounds, and the deadline-armed step machinery —
  :class:`StepDeadlineExceeded` — behind ``run_elastic``'s
  shrink-to-healthy-mesh recovery and its inverse, beacon-admitted
  host rejoin with grow-capable resharding) plus
  :class:`FleetController` (the load-driven fleet autoscaler:
  typed :class:`ScaleDecision` grow/shrink/stay decisions with
  hysteresis, executed through the same machinery);
- :mod:`~apex_tpu.resilience.faults` — :class:`FaultInjector`
  (seeded schedules of torn writes, fsync errors, slow disks, full
  disks, preemption signals, crash-before-publish, the training-state
  faults — NaN grads, loss spikes, scale collapse, straggler stalls —
  and the fleet faults — peer death, peer hang, slow network — that
  prove every detector->action path).
"""

from apex_tpu.resilience.elastic import ElasticResult, run_elastic
from apex_tpu.resilience.fleet import (FleetController, FleetMonitor,
                                       FleetRecoveryFailed, HostFailure,
                                       ScaleDecision,
                                       StepDeadlineExceeded)
from apex_tpu.resilience.manager import CheckpointManager
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.resilience.retry import RetryPolicy
from apex_tpu.resilience.watchdog import (Anomaly, Watchdog,
                                          WatchdogAbort, WatchdogPolicy)

__all__ = [
    "Anomaly",
    "CheckpointManager",
    "ElasticResult",
    "FleetController",
    "FleetMonitor",
    "FleetRecoveryFailed",
    "HostFailure",
    "PreemptionGuard",
    "RetryPolicy",
    "ScaleDecision",
    "StepDeadlineExceeded",
    "Watchdog",
    "WatchdogAbort",
    "WatchdogPolicy",
    "run_elastic",
]
