"""Self-healing training: the anomaly watchdog and its escalation
policy — the closed loop between the telemetry sensors and the
elastic-recovery actuators.

The reference apex's only self-healing behavior is dynamic loss
scaling: skip the step and shrink the scale on overflow
(``apex/amp/scaler.py``).  At fleet scale that is nowhere near enough —
loss spikes, NaN storms that outlive the scaler's backoff, optimizer
divergence and straggling hosts all kill multi-day runs with no
automated response.  This module wires PR 4's device-side MetricRing
(the sensor) to PR 6's bucket-native checkpoints + ``run_elastic``
supervisor (the actuator):

- **Detectors** consume the telemetry session's WINDOW FLUSHES on the
  host — the one ``device_get`` per window the ring already pays — so
  detection adds **zero per-step device syncs** (the apexverify spec
  ``watchdog.instrumented_step`` proves the traced step is unchanged).
  Built in: ``found_inf`` streaks that outlast the scaler
  (:class:`NanStreakDetector`), windowed z-score loss-spike and
  grad-norm-explosion detection (:class:`LossSpikeDetector`,
  :class:`GradNormDetector`), loss-scale collapse storms
  (:class:`ScaleCollapseDetector`), and step-time straggler regression
  from host step-boundary wall times (:class:`StepTimeDetector`).
  Each yields a typed :class:`Anomaly` with severity and evidence.

- The **escalation ladder** (:class:`WatchdogPolicy`) turns anomalies
  into actions executed through ``run_elastic``:

  1. *warn* — emit the anomaly event, change nothing;
  2. *quarantine* — the offending window is written off: the caller's
     ``on_quarantine`` hook re-anchors the loss scale
     (``amp.re_anchor`` / ``AmpState.re_anchor``) and may skip its own
     update (``amp.update_state(..., skipped=...)`` keeps such steps
     out of the growth interval).  Repeated quarantines of the same
     kind escalate to rollback;
  3. *rollback-and-replay* — restore the **last-known-good**
     checkpoint (``CheckpointManager.restore_good``; "good" is stamped
     only after a full clean window ages past a save, and retention
     pinning means rotation never deletes it) and replay.  The budget
     and widening backoff come from a shared
     :class:`~apex_tpu.resilience.retry.RetryPolicy`, so a persistent
     bug can never loop forever;
  4. *abort-with-diagnostics* — write a post-mortem bundle (ring dump,
     anomaly timeline, config/env, retrace counters) and raise
     :class:`WatchdogAbort` so the job exits non-zero with the
     evidence on disk.

Multi-host: the detectors are deterministic functions of the ring
contents, which are computed from replicated on-device values — every
host reaches the SAME verdict at the same step boundary, and the
rollback itself goes through ``restore_latest``'s lockstep agreement,
so all hosts act in the same step boundary or none does.  (Attach a
watchdog on every rank; the telemetry session fetches its local ring
for observers even on non-writer ranks.)
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import sys
import time
from typing import (Any, Callable, Deque, Dict, List, Mapping, NamedTuple,
                    Optional, Sequence, Tuple)

from apex_tpu.resilience.retry import RetryPolicy
from apex_tpu.telemetry.incident import IncidentLog

SEVERITY_WARN = "warn"
SEVERITY_CRITICAL = "critical"

# the escalation ladder, least to most drastic
ACTION_NONE = "none"
ACTION_WARN = "warn"
ACTION_QUARANTINE = "quarantine"
ACTION_ROLLBACK = "rollback"
ACTION_ABORT = "abort"
_LADDER = (ACTION_NONE, ACTION_WARN, ACTION_QUARANTINE,
           ACTION_ROLLBACK, ACTION_ABORT)

DEFAULT_ACTIONS: Mapping[str, str] = {
    "nan_streak": ACTION_ROLLBACK,
    "scale_collapse": ACTION_ROLLBACK,
    "fp8_scale_collapse": ACTION_ROLLBACK,
    "loss_spike": ACTION_QUARANTINE,
    "grad_norm_explosion": ACTION_QUARANTINE,
    "straggler": ACTION_WARN,
}


class WatchdogAbort(RuntimeError):
    """The escalation policy reached abort: recovery is out of budget
    or impossible.  ``.postmortem`` holds the diagnostics bundle path
    (None if writing it failed); the job should exit non-zero."""

    def __init__(self, message: str, postmortem: Optional[str] = None):
        super().__init__(message)
        self.postmortem = postmortem


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One detected training anomaly (typed, JSON-able evidence)."""
    kind: str                   # "nan_streak" | "loss_spike" | ...
    severity: str               # SEVERITY_WARN | SEVERITY_CRITICAL
    step: int                   # newest step of the evidence
    first_step: int             # oldest step of the evidence
    detector: str               # detector instance name
    evidence: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    # the causal-correlation key (telemetry/incident.py): stamped by
    # the watchdog when this anomaly opens — or joins — an incident
    incident_id: Optional[str] = None

    def record(self) -> dict:
        """The typed telemetry event (``kind: "anomaly"``) emitters
        write and ``telemetry summarize`` renders as a timeline row."""
        rec = {"kind": "anomaly", "anomaly": self.kind,
               "severity": self.severity, "step": self.step,
               "first_step": self.first_step,
               "detector": self.detector,
               "evidence": dict(self.evidence)}
        if self.incident_id is not None:
            rec["incident_id"] = self.incident_id
        return rec


class Verdict(NamedTuple):
    """What the escalation policy decided at a step boundary."""
    action: str                     # one of the ACTION_* ladder
    anomaly: Optional[Anomaly]      # the driving anomaly (None: clean)


# ---------------------------------------------------------------------
# Detectors: pure host-side consumers of flushed step records.
# ---------------------------------------------------------------------

class Detector:
    """One anomaly detector over flushed telemetry step records.

    ``observe(records)`` is called once per window flush with the
    decoded step records (ascending by step; missing/non-finite metric
    cells are None) and returns any anomalies found.  Detectors carry
    their own trailing state and must ``reset()`` cleanly after a
    rollback — replayed step numbers would otherwise re-trigger
    against stale history.
    """
    name = "detector"

    def observe(self, records: Sequence[dict]) -> List[Anomaly]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def config(self) -> dict:
        """JSON-able construction parameters (post-mortem bundle)."""
        return {}


class NanStreakDetector(Detector):
    """``found_inf`` streaks that OUTLAST the scaler's own backoff.

    The scaler handles isolated overflows by design: skip + halve the
    scale.  From ``init_scale`` 2^16 that self-heals within ~16
    overflow steps — so a streak longer than ``streak`` consecutive
    overflowed steps means backoff is NOT converging (NaN params, a
    poisoned batch pipeline, broken kernels) and the state itself
    needs treatment."""

    def __init__(self, streak: int = 8, metric: str = "amp/found_inf"):
        if streak < 1:
            raise ValueError(f"streak must be >= 1, got {streak}")
        self.name = "nan_streak"
        self.streak = int(streak)
        self.metric = metric
        self.reset()

    def reset(self) -> None:
        self._run = 0
        self._first: Optional[int] = None
        self._fired = False

    def config(self) -> dict:
        return {"streak": self.streak, "metric": self.metric}

    def observe(self, records: Sequence[dict]) -> List[Anomaly]:
        out: List[Anomaly] = []
        for r in records:
            v = r.get(self.metric)
            if v is None:
                continue                  # metric not recorded this step
            if v > 0:
                if self._run == 0:
                    self._first = r["step"]
                self._run += 1
                if self._run >= self.streak and not self._fired:
                    self._fired = True    # once per streak, not per step
                    out.append(Anomaly(
                        kind="nan_streak", severity=SEVERITY_CRITICAL,
                        step=r["step"], first_step=self._first,
                        detector=self.name,
                        evidence={"consecutive_overflows": self._run}))
            else:
                self.reset()
        return out


class ZScoreDetector(Detector):
    """Windowed z-score spike detection over one metric's trailing
    history.  Anomalous values are EXCLUDED from the history so a
    spike cannot poison its own baseline; non-finite cells are the NaN
    detector's business and are skipped here."""

    kind = "zscore"
    severity = SEVERITY_WARN

    def __init__(self, metric: str, zscore: float = 8.0,
                 min_history: int = 12, history: int = 256,
                 min_rel_std: float = 0.01):
        if min_history < 2:
            raise ValueError("min_history must be >= 2")
        self.name = self.kind
        self.metric = metric
        self.zscore = float(zscore)
        self.min_history = int(min_history)
        self.min_rel_std = float(min_rel_std)
        self._hist: Deque[float] = collections.deque(maxlen=int(history))

    def reset(self) -> None:
        self._hist.clear()

    def config(self) -> dict:
        return {"metric": self.metric, "zscore": self.zscore,
                "min_history": self.min_history,
                "history": self._hist.maxlen,
                "min_rel_std": self.min_rel_std}

    def observe(self, records: Sequence[dict]) -> List[Anomaly]:
        out: List[Anomaly] = []
        for r in records:
            v = r.get(self.metric)
            if v is None or not math.isfinite(v):
                continue
            if len(self._hist) >= self.min_history:
                mean = sum(self._hist) / len(self._hist)
                var = (sum((x - mean) ** 2 for x in self._hist)
                       / (len(self._hist) - 1))
                # a (near-)flat-lined metric has no noise to measure
                # spikes against: floor the std at min_rel_std of the
                # mean's magnitude, so a noiseless baseline still
                # catches a genuine spike without firing on rounding
                std = max(math.sqrt(var),
                          self.min_rel_std * max(abs(mean), 1e-12))
                if (v - mean) / std >= self.zscore:
                    out.append(Anomaly(
                        kind=self.kind, severity=self.severity,
                        step=r["step"], first_step=r["step"],
                        detector=self.name,
                        evidence={"value": v, "mean": mean, "std": std,
                                  "zscore": (v - mean) / std}))
                    continue              # keep the baseline clean
            self._hist.append(float(v))
        return out


class LossSpikeDetector(ZScoreDetector):
    """Loss suddenly far above its trailing distribution — a corrupt
    batch or the onset of divergence."""
    kind = "loss_spike"

    def __init__(self, metric: str = "loss", zscore: float = 8.0,
                 min_history: int = 12, history: int = 256):
        super().__init__(metric, zscore=zscore, min_history=min_history,
                         history=history)


class GradNormDetector(ZScoreDetector):
    """Gradient-norm explosion relative to its trailing distribution
    (pre-clip norm: clipping caps the update, not the signal)."""
    kind = "grad_norm_explosion"

    def __init__(self, metric: str = "amp/grad_norm",
                 zscore: float = 8.0, min_history: int = 12,
                 history: int = 256):
        super().__init__(metric, zscore=zscore, min_history=min_history,
                         history=history)


class ScaleCollapseDetector(Detector):
    """Loss scale pinned at its floor for ``windows`` consecutive
    flushes — the storm signature: intermittent overflows keep beating
    the scale back down faster than growth can recover it, without
    ever forming the contiguous streak :class:`NanStreakDetector`
    requires."""

    kind = "scale_collapse"

    def __init__(self, floor: float = 1.0, windows: int = 2,
                 metric: Optional[str] = None):
        if windows < 1:
            raise ValueError(f"windows must be >= 1, got {windows}")
        self.name = self.kind
        self.floor = float(floor)
        self.windows = int(windows)
        self.metric = metric if metric is not None else "amp/loss_scale"
        self.reset()

    def reset(self) -> None:
        self._consec = 0
        self._first: Optional[int] = None
        self._fired = False

    def config(self) -> dict:
        return {"floor": self.floor, "windows": self.windows,
                "metric": self.metric}

    def observe(self, records: Sequence[dict]) -> List[Anomaly]:
        scales = [(r["step"], r[self.metric]) for r in records
                  if r.get(self.metric) is not None]
        if not scales:
            return []                     # no information this window
        if max(v for _, v in scales) <= self.floor:
            if self._consec == 0:
                self._first = scales[0][0]
            self._consec += 1
            if self._consec >= self.windows and not self._fired:
                self._fired = True
                return [Anomaly(
                    kind=self.kind, severity=SEVERITY_CRITICAL,
                    step=scales[-1][0], first_step=self._first,
                    detector=self.name,
                    evidence={"floor": self.floor, "metric": self.metric,
                              "windows_at_floor": self._consec})]
        else:
            self.reset()
        return []


class Fp8ScaleCollapseDetector(ScaleCollapseDetector):
    """fp8 delayed-scaling collapse: the MINIMUM per-tensor fp8 scale
    (``fp8/scale_min`` from the flat pipeline's gradient state, or
    ``fp8/weight_scale_min`` from the optimizer's packed weight
    slots) pinned at/below ``floor`` for ``windows`` consecutive
    flushes.  A healthy scale is ``fp8_max / amax`` — well above 1
    for sane tensors; a scale stuck at the floor means some tensor's
    amax history is saturated (divergence, a poisoned batch, or an
    overflow storm the per-tensor backoff keeps fighting), the exact
    state-is-the-damage shape rollback exists for.  Same
    quarantine->rollback ladder as the loss-scale collapse
    (DEFAULT_ACTIONS maps ``fp8_scale_collapse`` to rollback).

    The default floor is 2^-8, NOT 1.0: a tensor with no gradient
    signal yet (frozen/unused leaf) keeps its INIT scale of exactly
    1.0 forever, and a floor of 1.0 would read that healthy
    no-information state as a collapse.  Reaching 2^-8 takes eight
    consecutive per-tensor backoffs (or a sustained amax around
    fp8_max * 2^8) — unambiguously a storm."""

    kind = "fp8_scale_collapse"

    def __init__(self, floor: float = 2.0 ** -8, windows: int = 2,
                 metric: Optional[str] = None):
        super().__init__(floor=floor, windows=windows,
                         metric=metric if metric is not None
                         else "fp8/scale_min")


class StepTimeDetector(Detector):
    """Straggler / throughput regression from HOST step-boundary wall
    times.  The watchdog clocks ``check(step)`` calls itself (span-
    style host telemetry — no device traffic) and feeds the deltas
    here; a step slower than ``factor`` x the trailing median fires.
    Outliers are excluded from the history, so a stall does not drag
    the baseline up."""

    def __init__(self, factor: float = 3.0, min_history: int = 12,
                 history: int = 256):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        self.name = "straggler"
        self.factor = float(factor)
        self.min_history = int(min_history)
        self._hist: Deque[float] = collections.deque(maxlen=int(history))
        self._fired = False

    def reset(self) -> None:
        self._hist.clear()
        self._fired = False

    def config(self) -> dict:
        return {"factor": self.factor, "min_history": self.min_history,
                "history": self._hist.maxlen}

    def observe(self, records: Sequence[dict]) -> List[Anomaly]:
        return []                         # fed through observe_time

    def observe_time(self, step: int, dt_s: float) -> Optional[Anomaly]:
        if len(self._hist) >= self.min_history:
            med = sorted(self._hist)[len(self._hist) // 2]
            if med > 0 and dt_s >= self.factor * med:
                # once per slowness EPISODE, not per slow step: a
                # sustained slowdown (or a cadence of naturally-slower
                # save/flush steps) must not flood the timeline
                if self._fired:
                    return None
                self._fired = True
                return Anomaly(
                    kind="straggler", severity=SEVERITY_WARN,
                    step=step, first_step=step, detector=self.name,
                    evidence={"step_time_s": round(dt_s, 6),
                              "median_s": round(med, 6),
                              "slowdown": round(dt_s / med, 2)})
            self._fired = False           # normal step re-arms
        self._hist.append(float(dt_s))
        return None


def default_detectors(scale_floor: float = 1.0) -> List[Detector]:
    """The standard detector suite (``scale_floor`` should match the
    scaler config's ``min_loss_scale``).  The fp8 collapse detector is
    inert in non-fp8 runs (no ``fp8/scale_min`` records = no
    information = never fires)."""
    return [NanStreakDetector(),
            LossSpikeDetector(),
            GradNormDetector(),
            ScaleCollapseDetector(floor=scale_floor),
            Fp8ScaleCollapseDetector(),
            StepTimeDetector()]


# ---------------------------------------------------------------------
# Escalation policy
# ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class WatchdogPolicy:
    """Anomaly kind -> action mapping plus the escalation budgets.

    ``actions``: base action per anomaly kind (unknown kinds warn).
    ``quarantine_budget``: same-kind quarantines tolerated per
    INCIDENT before escalating that kind to rollback; the counts
    clear after a full clean window (or a rollback), so isolated
    spikes days apart never accumulate into a spurious rollback.
    ``rollback``: the rollback budget and widening backoff — a shared
    :class:`RetryPolicy`; once ``rollback.max_retries`` rollbacks have
    been spent, the next rollback-grade anomaly aborts.
    """
    actions: Mapping[str, str] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_ACTIONS))
    quarantine_budget: int = 2
    rollback: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(max_retries=2,
                                            base_delay_s=0.05,
                                            max_delay_s=2.0))

    def __post_init__(self):
        for kind, act in self.actions.items():
            if act not in _LADDER:
                raise ValueError(f"unknown action {act!r} for anomaly "
                                 f"kind {kind!r}; known: {_LADDER}")
        if self.quarantine_budget < 0:
            raise ValueError("quarantine_budget must be >= 0")

    def action_for(self, anomaly: Anomaly) -> str:
        return self.actions.get(anomaly.kind, ACTION_WARN)


# ---------------------------------------------------------------------
# The watchdog
# ---------------------------------------------------------------------

class Watchdog:
    """Anomaly watchdog over a telemetry session's window flushes.

    >>> tel = telemetry.Telemetry(run_dir, window=32)
    >>> wd = Watchdog(telemetry=tel)          # observer auto-attached
    >>> res = run_elastic(step_fn, mgr, opt, total_steps=...,
    ...                   watchdog=wd,
    ...                   on_quarantine=lambda a:
    ...                       box.update(amp=box["amp"].re_anchor()))

    Detection runs inside the session's flush (host side, window
    cadence); decisions surface at step boundaries through
    ``check(step)``, which ``run_elastic`` calls for you.  Without a
    session, call ``observe(records)`` with decoded ring records
    directly (the chaos suite drives it this way).

    LKG stamping: ``run_elastic`` reports cadence saves via
    ``note_save`` and drains ``resolved_saves()`` — a save is stamped
    good only once ``clean_window`` further steps were observed with
    no quarantine-or-worse anomaly; any such anomaly voids every
    still-aging candidate.
    """

    def __init__(self, detectors: Optional[Sequence[Detector]] = None,
                 policy: Optional[WatchdogPolicy] = None,
                 telemetry=None,
                 clean_window: Optional[int] = None,
                 postmortem_dir: Optional[str] = None,
                 incidents: Optional[IncidentLog] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.detectors: List[Detector] = (
            list(detectors) if detectors is not None
            else default_detectors())
        self.policy = policy or WatchdogPolicy()
        # the incident register: quarantine-or-worse anomalies open an
        # incident whose id threads every resulting event record.
        # run_elastic shares the fleet monitor's log when both are
        # attached so the ordinals interleave identically on every host
        self.incidents = incidents if incidents is not None \
            else IncidentLog()
        self._own_iid: Optional[str] = None
        self.telemetry = telemetry
        if clean_window is None:
            clean_window = (telemetry.ring.window
                            if telemetry is not None else 32)
        if clean_window < 1:
            raise ValueError("clean_window must be >= 1")
        self.clean_window = int(clean_window)
        self.postmortem_dir = postmortem_dir or (
            getattr(telemetry, "run_dir", None))
        self._clock = clock
        self._time_det: Optional[StepTimeDetector] = next(
            (d for d in self.detectors
             if isinstance(d, StepTimeDetector)), None)
        self.timeline: List[Anomaly] = []     # full history, in order
        self.events: List[dict] = []          # full action-event history
        self._pending: List[Anomaly] = []     # awaiting a verdict
        self._event_records: List[dict] = []  # queued for the next flush
        self._recent: Deque[dict] = collections.deque(maxlen=1024)
        self._pending_saves: List[int] = []
        self._resolved: List[Tuple[int, bool]] = []
        self._quarantines: Dict[str, int] = {}
        self._last_anomaly_step: Optional[int] = None
        self._rollbacks = 0
        self._last_step_t: Optional[float] = None
        self._attached = False
        if telemetry is not None:
            telemetry.add_observer(self._on_flush)
            self._attached = True

    # ---- lifecycle -------------------------------------------------------
    def close(self) -> None:
        if self._attached and self.telemetry is not None:
            self.telemetry.remove_observer(self._on_flush)
            self._attached = False

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def rollbacks(self) -> int:
        """Rollbacks spent from the policy's budget so far."""
        return self._rollbacks

    def recent_step_times(self) -> List[float]:
        """The straggler detector's trailing step-time samples
        (seconds; empty without a :class:`StepTimeDetector`) — the
        baseline ``run_elastic(step_deadline="auto")`` seeds its
        :class:`~apex_tpu.resilience.fleet.DeadlineCalibrator` from,
        so the deadline is calibrated before the calibrator's own
        history accrues."""
        if self._time_det is None:
            return []
        return list(self._time_det._hist)

    # ---- observation (window-flush cadence, host side) -------------------
    def _on_flush(self, records: Sequence[dict]) -> List[dict]:
        """Telemetry flush observer: detect, then hand the anomaly +
        action event records back for the emitters to write (wall
        stamps ``t`` let the fleet timeline order events across
        hosts)."""
        now = round(time.time(), 3)
        events = [{**a.record(), "t": now}
                  for a in self.observe(records)]
        events += self._event_records
        self._event_records = []
        return events

    def observe(self, records: Sequence[dict]) -> List[Anomaly]:
        """Run every detector over one window's decoded step records;
        returns (and queues for ``check``) the anomalies found."""
        step_records = [r for r in records
                        if r.get("kind", "step") == "step"]
        if not step_records:
            return []
        self._recent.extend(step_records)
        found: List[Anomaly] = []
        for det in self.detectors:
            found.extend(det.observe(step_records))
        found = self._ingest(found)
        newest = step_records[-1]["step"]
        # LKG aging: saves survive once a full clean window passed them
        # (any quarantine-grade anomaly above already voided them all)
        while self._pending_saves and \
                newest >= self._pending_saves[0] + self.clean_window:
            self._resolved.append((self._pending_saves.pop(0), True))
        # incident closure: a full clean window since the last
        # quarantine-or-worse anomaly forgives the quarantine counts
        # (policy docstring); _ingest keeps the watermark fresh while
        # an incident is live, so the age test suffices
        if self._last_anomaly_step is not None and \
                newest >= self._last_anomaly_step + self.clean_window:
            self._quarantines.clear()
            self._last_anomaly_step = None
        # a quarantine-grade incident this watchdog opened closes by
        # surviving its clean window — but NEVER while an anomaly is
        # still pending a verdict: one flush can both detect and span
        # past the clean horizon (a late first detection in a wide
        # window), and the verdict it drives (quarantine/rollback at
        # the next boundary) must still ride the open incident, so the
        # closure test runs on every flush once nothing is pending and
        # the forgiveness watermark has aged out.  Rollback incidents
        # are DISOWNED at rollback time (reset_after_external_rewind)
        # and close via note_replay_complete instead
        if not self._pending and self._last_anomaly_step is None \
                and self._own_iid is not None:
            if self.incidents.close(self._own_iid):
                self._event({"kind": "watchdog",
                             "action": "incident_resolved",
                             "step": int(newest),
                             "incident_id": self._own_iid})
            self._own_iid = None
        return found

    def _ingest(self, found: Sequence[Anomaly]) -> List[Anomaly]:
        """Fold newly-detected anomalies into the incident state;
        returns them stamped with the open incident id (when one is
        open) — callers must use the returned list."""
        found = list(found)
        if not found:
            return found
        # incident state keys on quarantine-or-worse anomalies only: a
        # warn-grade straggler must neither void LKG candidates nor
        # hold the quarantine-forgiveness window open
        serious = [a for a in found
                   if _LADDER.index(self.policy.action_for(a))
                   >= _LADDER.index(ACTION_QUARANTINE)]
        if serious:
            # open (or join — a fleet recovery may already be live on a
            # shared log) the incident; the id threads every record in
            # the causal chain from here on
            if self.incidents.current is None:
                self._own_iid = self.incidents.open(serious[0].kind)
            else:
                self.incidents.open(serious[0].kind)
        if self.incidents.current is not None:
            found = [dataclasses.replace(
                a, incident_id=self.incidents.current) for a in found]
            serious = [a for a in found
                       if _LADDER.index(self.policy.action_for(a))
                       >= _LADDER.index(ACTION_QUARANTINE)]
        self.timeline.extend(found)
        self._pending.extend(found)
        if serious:
            self._last_anomaly_step = max(
                [a.step for a in serious]
                + ([self._last_anomaly_step]
                   if self._last_anomaly_step is not None else []))
            # the open incident voids every still-aging save
            # candidate: none of them has proven a clean window
            for s in self._pending_saves:
                self._resolved.append((s, False))
            self._pending_saves.clear()
        return found

    # ---- supervisor surface (step-boundary cadence) ----------------------
    def open_incident(self, step: int) -> bool:
        """True while an incident is OPEN at ``step``: anomalies are
        awaiting a verdict at this boundary, or ``step`` is within
        ``clean_window`` of the last quarantine-or-worse anomaly.
        This is the exact test :meth:`note_save` applies to LKG
        candidacy — exposed for the fleet's admission gate too: a
        mesh resize mid-incident would reshard (and replicate onto new
        hosts) the very state the watchdog may be about to roll away
        from, so ``run_elastic`` refuses admissions and the
        :class:`~.fleet.FleetController` holds its decisions while
        this is True."""
        return bool(self._pending) or (
            self._last_anomaly_step is not None
            and int(step) <= self._last_anomaly_step + self.clean_window)

    def note_save(self, step: int) -> None:
        """A cadence checkpoint was scheduled at ``step``; it starts
        aging toward last-known-good (pin it in the manager).

        A save taken inside an OPEN incident (:meth:`open_incident`)
        is rejected immediately: it snapshots state that went through
        the anomalous window (the quarantine re-anchor has not even
        run yet), and letting it age into LKG would hand a later
        rollback the very state being rolled away from."""
        step = int(step)
        if self.open_incident(step):
            self._resolved.append((step, False))
            return
        self._pending_saves.append(step)
        self._pending_saves.sort()

    def resolved_saves(self) -> List[Tuple[int, bool]]:
        """Drain (step, became_good) verdicts for previously noted
        saves — ``run_elastic`` marks good / unpins accordingly."""
        out, self._resolved = self._resolved, []
        return out

    def check(self, step: int) -> Verdict:
        """THE step-boundary poll (``run_elastic`` calls it once per
        step): clock the step for the straggler detector, then fold
        every pending anomaly through the escalation policy into one
        verdict.  Pure host logic — no device traffic."""
        now = self._clock()
        if self._last_step_t is not None and self._time_det is not None:
            a = self._time_det.observe_time(step, now - self._last_step_t)
            if a is not None:
                a = self._ingest([a])[0]
                self._event_records.append(
                    {**a.record(), "t": round(time.time(), 3)})
        self._last_step_t = now
        if not self._pending:
            return Verdict(ACTION_NONE, None)
        worst, worst_anomaly = ACTION_NONE, None
        for a in self._pending:
            act = self.policy.action_for(a)
            if act == ACTION_QUARANTINE:
                n = self._quarantines.get(a.kind, 0) + 1
                self._quarantines[a.kind] = n
                if n > self.policy.quarantine_budget:
                    act = ACTION_ROLLBACK    # ladder: repeat offender
            if _LADDER.index(act) > _LADDER.index(worst):
                worst, worst_anomaly = act, a
        self._pending = []
        if worst == ACTION_ROLLBACK:
            if self.policy.rollback.exhausted(self._rollbacks + 1):
                worst = ACTION_ABORT         # budget spent
            else:
                # counted only when the rollback will actually run, so
                # `rollbacks` always reads as rollbacks EXECUTED
                self._rollbacks += 1
        return Verdict(worst, worst_anomaly)

    # ---- actions (called by run_elastic) ---------------------------------
    def _event(self, rec: dict) -> None:
        rec.setdefault("t", round(time.time(), 3))
        self.incidents.tag(rec)
        self.events.append(rec)
        self._event_records.append(rec)

    def note_quarantine(self, step: int, anomaly: Optional[Anomaly]
                        ) -> None:
        self._event({
            "kind": "watchdog", "action": ACTION_QUARANTINE,
            "step": int(step),
            "anomaly": anomaly.kind if anomaly else None})

    def note_rollback(self, restored_step: int, step: int,
                      anomaly: Optional[Anomaly]) -> None:
        """A rollback restored ``restored_step``: rewind telemetry so
        the replayed steps re-record, reset every detector (replayed
        step numbers must not re-trigger on stale history), void the
        aging save candidates, and log the event."""
        self._event({
            "kind": "watchdog", "action": ACTION_ROLLBACK,
            "step": int(step), "to_step": int(restored_step),
            "anomaly": anomaly.kind if anomaly else None,
            "rollbacks": self._rollbacks})
        # disown BEFORE the rewind: rewind() flushes, the flush runs
        # observe(), and an aged-out forgiveness watermark would let
        # the clean-window closure resolve the incident mid-rollback —
        # the replay-complete path owns closing it from here
        self.disown_incident()
        if self.telemetry is not None:
            self.telemetry.rewind(restored_step)
        self.reset_after_external_rewind(restored_step)

    def note_replay_complete(self, step: int,
                             incident_id: Optional[str] = None) -> None:
        """The replay after a rollback caught back up to the failure
        step: the incident's causal chain is over.  Emits the
        ``replay_complete`` event carrying the incident id and closes
        it in the register (``run_elastic`` calls this when the loop
        passes the step the incident opened at)."""
        iid = incident_id if incident_id is not None \
            else self.incidents.current
        rec = {"kind": "watchdog", "action": "replay_complete",
               "step": int(step)}
        if iid is not None:
            rec["incident_id"] = iid
        self._event(rec)
        self.incidents.close(iid)
        if iid == self._own_iid:
            self._own_iid = None

    def disown_incident(self) -> None:
        """Hand the open incident's closure to the replay-complete
        path (rollback / fleet-resize recoveries): the clean-window
        closure must never resolve an incident whose replay is still
        in flight.  Called before any telemetry rewind whose flush
        would run the closure test."""
        self._own_iid = None

    def reset_after_external_rewind(self, restored_step: int) -> None:
        """The run was rewound to ``restored_step`` and the steps
        after it are about to be REPLAYED — by this watchdog's own
        rollback, or by an external recovery (the fleet's
        shrink-to-healthy-mesh) whose telemetry rewind the caller
        already performed.  Reset every detector (replayed step
        numbers must not re-trigger on stale history from the
        abandoned timeline), drop pending anomalies, void the aging
        save candidates, and clear the incident state — the restored
        state predates the incident, so replayed saves are
        trustworthy candidates again.  Touches neither the rollback
        budget nor the event log."""
        for det in self.detectors:
            det.reset()
        self._pending = []
        for s in self._pending_saves:
            self._resolved.append((s, False))
        self._pending_saves.clear()
        self._quarantines.clear()
        self._last_anomaly_step = None
        self._last_step_t = None             # restore time is not a step
        self.disown_incident()   # replay-complete owns closing it now

    # ---- abort diagnostics -----------------------------------------------
    def write_postmortem(self, step: int,
                         anomaly: Optional[Anomaly] = None,
                         directory: Optional[str] = None
                         ) -> Optional[str]:
        """Write the post-mortem bundle; returns its path (None when
        even that failed — aborting must never be blocked on disk).

        Layout: ``postmortem-step<N>/`` with ``anomalies.jsonl`` (the
        full anomaly timeline + action events), ``ring_dump.jsonl``
        (the recent decoded step records), ``config.json`` (policy,
        detector configs, environment, process topology) and
        ``retraces.json`` (compilation counters, when a telemetry
        session carries them)."""
        base = directory or self.postmortem_dir or "."
        path = os.path.join(base, f"postmortem-step{int(step)}")
        try:
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "anomalies.jsonl"), "w",
                      encoding="utf-8") as f:
                for a in self.timeline:
                    f.write(json.dumps(a.record(), sort_keys=True) + "\n")
                for e in self.events:
                    f.write(json.dumps(e, sort_keys=True) + "\n")
                if anomaly is not None:
                    f.write(json.dumps(
                        {"kind": "watchdog", "action": ACTION_ABORT,
                         "step": int(step), "anomaly": anomaly.kind},
                        sort_keys=True) + "\n")
            with open(os.path.join(path, "ring_dump.jsonl"), "w",
                      encoding="utf-8") as f:
                for r in self._recent:
                    f.write(json.dumps(r, sort_keys=True) + "\n")
            with open(os.path.join(path, "config.json"), "w",
                      encoding="utf-8") as f:
                json.dump(self._config_snapshot(step), f, indent=1,
                          sort_keys=True, default=str)
            retrace = getattr(self.telemetry, "retrace", None)
            if retrace is not None:
                with open(os.path.join(path, "retraces.json"), "w",
                          encoding="utf-8") as f:
                    json.dump(retrace.records(step=int(step)), f,
                              indent=1, sort_keys=True)
            return path
        except OSError:
            return None

    def _config_snapshot(self, step: int) -> dict:
        env = {k: v for k, v in sorted(os.environ.items())
               if k.startswith(("APEX_TPU_", "JAX_", "XLA_"))}
        topo: Dict[str, Any] = {}
        try:
            import jax
            topo = {"backend": jax.default_backend(),
                    "process_index": jax.process_index(),
                    "process_count": jax.process_count(),
                    "device_count": jax.device_count()}
        except Exception:                    # diagnostics must not raise
            pass
        return {
            "step": int(step),
            "argv": list(sys.argv),
            "policy": {"actions": dict(self.policy.actions),
                       "quarantine_budget": self.policy.quarantine_budget,
                       "rollback": dataclasses.asdict(
                           self.policy.rollback)},
            "detectors": {d.name: d.config() for d in self.detectors},
            "clean_window": self.clean_window,
            "rollbacks_spent": self._rollbacks,
            "env": env,
            "topology": topo,
        }
