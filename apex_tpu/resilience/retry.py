"""Bounded retry with widening backoff — the one policy object every
recovery loop in the resilience stack shares.

``run_elastic`` retries transient step/save failures under it, and the
watchdog's rollback-and-replay budget reuses it verbatim: both are
"try again, a bounded number of times, waiting longer each time" —
hard-coding the constants separately in each loop is how one of them
ends up retrying forever.

The policy is pure arithmetic over an attempt number; the caller owns
the clock (``sleep=`` injection keeps every test fake-clocked).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Widening (exponential) backoff, bounded in count and delay.

    ``max_retries``: recoveries attempted AFTER the first failure;
    attempt ``max_retries + 1`` is never made (``exhausted``).
    ``base_delay_s`` doubles per attempt up to ``max_delay_s``.
    ``jitter``: fraction in ``[0, 1)`` of the delay added uniformly at
    random — decorrelates a fleet of hosts hammering the same flaky
    filesystem.  Deterministic tests pass an explicit ``rng``
    (``random.Random(seed)``) or leave jitter at 0; multi-host
    lockstep recoveries MUST keep jitter at 0 (hosts sleeping
    different times before a collective restore still agree — the
    restore walk is the barrier — but the grace window shrinks by the
    skew).
    """
    max_retries: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 5.0
    jitter: float = 0.0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {self.max_retries}")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if not (0.0 <= self.jitter < 1.0):
            raise ValueError(f"jitter must be in [0, 1), "
                             f"got {self.jitter}")

    def delay_s(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Backoff before retry ``attempt`` (1-based: the delay taken
        after the ``attempt``-th failure)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        d = min(self.base_delay_s * (2.0 ** (attempt - 1)),
                self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (rng or random).random()
        return d

    def exhausted(self, attempts: int) -> bool:
        """True once ``attempts`` recoveries exceed the budget."""
        return attempts > self.max_retries
