"""``run_elastic`` — the in-job supervisor loop that makes a train
loop survive what preemptible fleets actually do to it.

The reference has no failure story (SURVEY.md §5: a crashed rank kills
the job).  ``run_elastic`` wraps a user step function with the full
recovery contract:

- **resume**: restore the newest valid checkpoint before the first
  step (reusing ``CheckpointManager.restore_latest`` — including its
  multi-host lockstep agreement, so every host resumes from the SAME
  step or none does);
- **cadence saves** through the manager (bucket-native v2 when the
  optimizer runs bucketed);
- **transient-failure recovery**: a step or save raising a retryable
  error (``OSError`` by default — flaky disk, NFS hiccup) triggers
  bounded retry-with-backoff: restore the newest valid checkpoint and
  resume from it (training replay is deterministic from a checkpoint,
  so the result is bit-identical to an uninterrupted run);
- **preemption**: a :class:`~.preemption.PreemptionGuard` notice
  (SIGTERM / ``--preempt-at-step``) converts into one final FORCED
  save at the current step boundary, a durability wait, and a clean
  return with ``preempted=True``;
- **self-healing** (``watchdog=``): a
  :class:`~.watchdog.Watchdog` polled at every step boundary turns
  detected training anomalies into the escalation ladder — quarantine
  (the ``on_quarantine`` hook re-anchors the loss scale), bounded
  rollback-and-replay onto the last-known-good checkpoint, or
  abort-with-diagnostics (:class:`~.watchdog.WatchdogAbort` after the
  post-mortem bundle is written).  Cadence saves age toward
  last-known-good through the watchdog's clean-window rule, pinned
  against rotation while they age;
- **multi-host failure domains** (``fleet=`` + ``step_deadline=``): a
  :class:`~.fleet.FleetMonitor` beaten at every step boundary
  publishes this host's liveness beacon and classifies peers; a peer
  agreed DEAD — or a deadline-armed step/save converting a hung
  collective into :class:`~.fleet.StepDeadlineExceeded` — triggers
  shrink-to-healthy-mesh recovery: barrier-free survivor agreement,
  mesh re-initialization over the survivors (``comm.shrink_mesh`` or
  the caller's ``on_shrink`` hook), restore of the last-known-good
  checkpoint through the ``sharding=`` reshard flow, and resume —
  bounded by the same ``RetryPolicy`` budget and reported as
  ``ElasticResult.mesh_shrinks``.  A slow peer only warns.

The user's step function owns the optimizer and any AMP state (a
closure); ``save_extras``/``on_restore`` thread the non-optimizer
state (amp scaler dict, BN batch_stats) through the checkpoint bundle:

>>> def step_fn(step):                      # 1-based steps
...     loss, flat = pipe.scaled_value_and_grad(...)
...     opt.step(flat)
...     box["amp"] = amp.update_scaler(box["amp"], flat.found_inf)
>>> res = run_elastic(
...     step_fn, mgr, opt, total_steps=1000,
...     guard=PreemptionGuard(),
...     save_extras=lambda: {"amp_state": box["amp"].state_dict()},
...     on_restore=lambda amp_sd, extra, step:
...         box.update(amp=box["amp"].load_state_dict(amp_sd)))
>>> if res.preempted: sys.exit(0)           # checkpoint is durable
"""

from __future__ import annotations

import dataclasses
import errno
import inspect
import time
import warnings
from typing import Any, Callable, Optional, Tuple, Type, Union

import jax

from apex_tpu.resilience import faults as _faults
from apex_tpu.resilience import fleet as _fleet
from apex_tpu.resilience import watchdog as _watchdog
from apex_tpu.resilience.manager import CheckpointManager
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.resilience.retry import RetryPolicy

Pytree = Any

# OSErrors no amount of retrying can fix: restoring and replaying onto
# a full / quota-exhausted / read-only filesystem fails the same way
# every time — burning the whole retry budget on them just delays the
# inevitable abort by the full backoff schedule
_FATAL_ERRNOS = frozenset(
    e for e in (getattr(errno, "ENOSPC", None),
                getattr(errno, "EDQUOT", None),
                getattr(errno, "EROFS", None)) if e is not None)


def _fatal_io(e: BaseException) -> bool:
    """True for a retryable-TYPED error whose errno says retrying is
    hopeless (ENOSPC and friends) — the straight-to-abort path."""
    return isinstance(e, OSError) and e.errno in _FATAL_ERRNOS


@dataclasses.dataclass
class ElasticResult:
    """What the supervisor loop did."""
    step: int                       # last COMPLETED step
    preempted: bool                 # True: exited on a notice, final
    #                                 checkpoint durable at .step
    restarts: int                   # in-job recoveries performed
    restored_from: Optional[int]    # initial resume step (None: fresh)
    rollbacks: int = 0              # watchdog rollback-and-replays
    mesh_shrinks: int = 0           # shrink-to-healthy-mesh recoveries
    #                                 (failure-driven + autoscaler)
    mesh_grows: int = 0             # admission-driven mesh grows


def run_elastic(step_fn: Callable[[int], Any],
                manager: CheckpointManager,
                optimizer=None, *,
                total_steps: int,
                params_like: Optional[Pytree] = None,
                extra_like: Optional[Pytree] = None,
                guard: Optional[PreemptionGuard] = None,
                watchdog=None,
                on_quarantine: Optional[Callable] = None,
                fleet=None,
                step_deadline: Union[None, str, float,
                                     "_fleet.DeadlineCalibrator"] = None,
                on_shrink: Optional[Callable] = None,
                shrink_sharding=None,
                on_grow: Optional[Callable] = None,
                grow_sharding=None,
                grow_max_bucket_bytes=None,
                admission_cooldown_steps: int = 0,
                autoscale=None,
                save_extras: Optional[Callable[[], dict]] = None,
                on_restore: Optional[Callable] = None,
                retryable: Tuple[Type[BaseException], ...] = (OSError,),
                retry: Optional[RetryPolicy] = None,
                max_restarts: int = 3,
                backoff_s: float = 0.05,
                sleep: Callable[[float], None] = time.sleep
                ) -> ElasticResult:
    """Drive ``step_fn(step)`` for steps ``1..total_steps`` (1-based,
    matching the manager's save cadence) under the recovery contract in
    the module docstring.

    ``params_like``: restore template (shapes/dtypes suffice —
    ``jax.ShapeDtypeStruct`` leaves are fine); defaults to the shape
    structure of ``optimizer.params``.  ``save_extras() -> dict`` may
    return ``amp_state=`` and/or ``extra=`` for the checkpoint bundle
    — and, with ``optimizer=None``, the ``params=`` pytree the
    per-leaf save requires;
    ``on_restore(amp_sd, extra, step)`` — or the 4-arg form
    ``on_restore(amp_sd, extra, step, params)``, opted into by naming
    the 4th parameter ``params`` (it is passed by keyword) — is
    called after every restore (``amp_sd``/``extra`` as saved) so the
    caller can rebind its own state.  With ``optimizer=None`` the
    4-arg form is REQUIRED: the restored params can only reach the
    caller's closure through it.  ``retryable`` failures of a step OR save trigger
    restore-newest-valid-and-resume under ``retry`` (a
    :class:`~apex_tpu.resilience.retry.RetryPolicy`; defaults to one
    built from the legacy ``max_restarts``/``backoff_s`` knobs);
    anything else propagates (a real crash — the external scheduler
    restarts the job, and the next ``run_elastic`` resumes).

    ``watchdog``: a :class:`~apex_tpu.resilience.watchdog.Watchdog`
    polled once per step boundary; its verdicts execute here —
    quarantine calls ``on_quarantine(anomaly)`` (re-anchor the loss
    scale, drop the window), rollback restores the last-known-good
    checkpoint through the manager (multi-host lockstep agreement
    included) and replays under the watchdog's own
    ``policy.rollback`` budget + widening backoff, abort writes the
    post-mortem bundle then raises ``WatchdogAbort``.  Cadence saves
    are reported to the watchdog and pinned until the clean-window
    rule resolves them (good -> ``manager.mark_good``).

    ``fleet``: a :class:`~apex_tpu.resilience.fleet.FleetMonitor`
    beaten once per completed step (publish this host's beacon,
    classify peers).  A peer declared DEAD triggers shrink recovery
    (below); a SLOW peer warns only.  ``step_deadline`` arms each
    step's materialization and each cadence save with a watchdog
    timer (``"auto"``: deadline calibrated from the trailing
    step-time baseline via
    :class:`~apex_tpu.resilience.fleet.DeadlineCalibrator` — pass
    your own instance to tune it — or a fixed number of seconds): a
    hung collective converts into a catchable
    :class:`~apex_tpu.resilience.fleet.StepDeadlineExceeded` instead
    of an eternal block, and with a ``fleet`` monitor present enters
    the same shrink recovery (without one it propagates).

    Shrink recovery: barrier-free survivor agreement
    (``fleet.agree_survivors``), mesh re-init over the survivors
    (``on_shrink(survivors, epoch)`` when given, else
    ``comm.shrink_mesh`` when a global mesh is installed), a sweep of
    the dead hosts' orphaned ``.tmp`` checkpoint files by the agreed
    lowest-rank survivor, then restore of the last-known-good
    checkpoint through ``manager.restore_good`` — passing
    ``shrink_sharding`` (a sharding pytree, or a zero-arg callable
    evaluated AFTER the mesh re-init) into the existing ``sharding=``
    reshard flow so the restored state lands on the shrunk mesh.
    Each shrink consumes the shared ``retry`` budget and increments
    ``ElasticResult.mesh_shrinks``; an exhausted budget or a missing
    restore target raises
    :class:`~apex_tpu.resilience.fleet.FleetRecoveryFailed`.

    Grow recovery (the inverse flow): a recovered or new host
    beaconing a FRESH incarnation becomes a return candidate
    (``fleet.return_candidates``); at the next step boundary the
    members run ``fleet.agree_admission`` (the survivor agreement
    inverted), re-initialize the mesh over the grown member set
    (``on_grow(members, epoch)`` when given, else ``comm.grow_mesh``),
    optionally re-chunk the optimizer's BucketPlan
    (``grow_max_bucket_bytes``: a byte cap, or a callable
    ``members -> cap`` — per-host HBM changed, so the overlap chunk
    size should track it; the restore lands in the new layout through
    the reconstruct path), then restore the last-known-good checkpoint
    through ``manager.restore_good`` with ``grow_sharding`` (pytree or
    zero-arg callable, evaluated AFTER the mesh re-init) — the same
    reshard flow as shrink, in the grow direction — with the same
    bit-exact-replay guarantee (telemetry rewind + watchdog detector
    reset).  Counted as ``ElasticResult.mesh_grows``.  Admission
    hysteresis: an admission is REFUSED (``admission_refused``
    timeline event) while the watchdog has an open incident and within
    ``admission_cooldown_steps`` of any resize — a flapping host
    therefore causes exactly one shrink and no grow/shrink
    oscillation.  A grow that admits hosts but then finds no valid
    checkpoint raises ``FleetRecoveryFailed`` (the grown mesh needs
    the reshard restore to be coherent).

    ``autoscale``: a :class:`~apex_tpu.resilience.fleet.
    FleetController` (requires ``fleet``).  The supervisor clocks each
    completed step into it, asks it to decide at every boundary, and
    executes: ``grow`` admits the current return candidates through
    the admission flow above; ``shrink`` voluntarily releases the
    highest-rank peer through ``fleet.agree_survivors(exclude=...)``
    and the same shrink machinery (no retry budget consumed — a
    planned resize is not a failure); ``stay`` does nothing.  Every
    resize (including failure shrinks) arms the controller's cooldown
    via ``note_resize``.

    Retryable-TYPED errors whose errno is hopeless (ENOSPC, EDQUOT,
    EROFS) skip the retry loop entirely: the post-mortem bundle is
    written (when a watchdog is attached) and the error propagates —
    retrying a full disk just delays the abort by the whole backoff
    schedule."""
    if optimizer is None and params_like is None:
        raise ValueError("need an optimizer or params_like to restore")
    if autoscale is not None and fleet is None:
        raise ValueError(
            "run_elastic(autoscale=...) needs a fleet monitor — the "
            "controller decides, the fleet's admission/shrink "
            "machinery executes")
    if retry is None:
        retry = RetryPolicy(max_retries=max_restarts,
                            base_delay_s=backoff_s)
    if watchdog is not None and fleet is not None:
        # ONE incident register for the whole recovery stack: a
        # watchdog anomaly during a fleet recovery (or vice versa)
        # joins the open incident instead of forking a second id, and
        # the shared ordinal sequence stays identical on every host
        watchdog.incidents = fleet.incidents
    if params_like is None:
        # only the SHAPES are the template; holding the unpacked
        # pytree itself would pin a params-sized HBM copy all run
        params_like = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
            optimizer.params)
    wants_params = False
    if on_restore is not None:
        # opt-in by NAME, not arity: a defaulted 4th flag parameter
        # must not silently receive the params pytree
        sig = inspect.signature(on_restore)
        wants_params = ("params" in sig.parameters or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values()))
    if optimizer is None and not wants_params:
        raise ValueError(
            "run_elastic(optimizer=None) restores params only through "
            "on_restore(amp_sd, extra, step, params) — name its 4th "
            "parameter 'params' (or accept **kwargs); without it a "
            "resumed run would silently keep its freshly-initialized "
            "weights")
    own_guard = guard is not None and not guard._installed
    if own_guard:
        guard.install()
    runner: Optional[_fleet.DeadlineRunner] = None
    calibrator: Optional[_fleet.DeadlineCalibrator] = None
    fixed_deadline: Optional[float] = None
    if step_deadline is not None:
        runner = _fleet.DeadlineRunner()
        if step_deadline == "auto":
            # seed from the step-time baseline the watchdog already
            # tracks (its straggler detector's trailing history), so
            # the deadline is calibrated before our own notes accrue
            calibrator = _fleet.DeadlineCalibrator(
                history_source=(watchdog.recent_step_times
                                if watchdog is not None else None))
        elif isinstance(step_deadline, _fleet.DeadlineCalibrator):
            calibrator = step_deadline
        else:
            fixed_deadline = float(step_deadline)
    restarts = 0
    rollbacks = 0
    mesh_shrinks = 0
    mesh_grows = 0
    last_resize_step: Optional[int] = None
    # (incident_id, failure step) of a rollback/resize replay in
    # flight: when the loop passes the failure step again the chain is
    # over — emit replay_complete and close the incident
    pending_replay: Optional[Tuple[str, int]] = None
    try:
        def _extras() -> dict:
            return save_extras() if save_extras is not None else {}

        def _deadline_s() -> float:
            return (calibrator.deadline_s() if calibrator is not None
                    else fixed_deadline)

        def _armed_step(step: int) -> None:
            """Chaos hook + step body, deadline-armed when configured.
            The hook runs INSIDE the armed region (an injected hang
            must convert like a real one); a thunk abandoned while
            blocked there re-checks the runner generation and skips
            the state-mutating body — an abandoned worker must never
            race the recovery that replaced it."""
            if runner is None:
                _faults.notify_step(step)
                step_fn(step)
                return
            gen = runner.generation

            def thunk():
                _faults.notify_step(step)
                if runner.generation == gen:
                    step_fn(step)
            t0 = time.monotonic()
            runner.run(thunk, _deadline_s(), step=step, phase="step")
            if calibrator is not None:
                calibrator.note(time.monotonic() - t0)

        def _armed_save(step: int, extras: dict) -> bool:
            """Cadence save, deadline-armed when configured (the save
            schedule joins the PREVIOUS async write — a hung network
            filesystem blocks exactly here)."""
            if runner is None:
                return manager.maybe_save(step, optimizer=optimizer,
                                          **extras)
            gen = runner.generation

            def save_thunk():
                # same abandonment guard as _armed_step: a save thunk
                # still queued when the deadline respawns the worker
                # must not touch the manager's rotation/pin state
                # concurrently with the recovery path's own saves
                if runner.generation != gen:
                    return False
                return manager.maybe_save(step, optimizer=optimizer,
                                          **extras)

            return runner.run(save_thunk, _deadline_s(), step=step,
                              phase="save")

        def _restore(restore_fn=None, sharding=None) -> Optional[int]:
            out = (restore_fn or manager.restore_latest)(
                params_like, optimizer, extra_like=extra_like,
                sharding=sharding)
            if out is None:
                return None
            if on_restore is not None:
                args = (out[1],
                        out[3] if extra_like is not None else None,
                        out[2])
                if wants_params:
                    on_restore(*args, params=out[0])
                else:
                    on_restore(*args)
            return out[2]

        def _abort_fatal_io(step: int, e: BaseException) -> None:
            """The non-retryable-errno path: post-mortem (when a
            watchdog is attached), then let the caller re-raise."""
            warnings.warn(
                f"run_elastic: step {step} hit a non-retryable IO "
                f"error ({type(e).__name__}: {e}); aborting without "
                "burning the retry budget")
            if watchdog is not None:
                watchdog.write_postmortem(
                    step, None, directory=watchdog.postmortem_dir
                    or manager.directory)

        def _rewind_replay(resumed: int) -> None:
            """Replay parity with the watchdog rollback path: the
            telemetry session's emitted-step watermark must rewind so
            the replayed steps re-record (flush filters on after_step
            — without this the replay would be silently dropped from
            the record), and watchdog detector state from the
            abandoned timeline must not re-trigger on replayed step
            numbers.  Shared by the shrink and grow recoveries — the
            bit-exact-replay guarantee is direction-independent."""
            tel = getattr(fleet, "telemetry", None) or (
                watchdog.telemetry if watchdog is not None else None)
            if watchdog is not None:
                # before the rewind's flush: the closure test inside
                # observe() must not resolve an incident whose replay
                # is about to start (replay_complete owns it)
                watchdog.disown_incident()
            if tel is not None:
                tel.rewind(resumed)
            if watchdog is not None:
                watchdog.reset_after_external_rewind(resumed)

        def _note_resize(step: int) -> None:
            nonlocal last_resize_step
            last_resize_step = step
            if autoscale is not None:
                autoscale.note_resize(step)

        def _note_replay(failed_step: int) -> None:
            """Arm the replay-complete watermark: the open incident
            closes (one ``replay_complete`` event carrying its id)
            when the loop passes ``failed_step`` again.  A second
            recovery joining the SAME open incident (a rollback during
            a shrink's replay) keeps the FURTHEST watermark — the
            chain is over only once the replay re-passes the original
            failure step too."""
            nonlocal pending_replay
            log = (fleet.incidents if fleet is not None
                   else watchdog.incidents
                   if watchdog is not None else None)
            if log is None or log.current is None:
                return
            if pending_replay is not None \
                    and pending_replay[0] == log.current:
                pending_replay = (log.current,
                                  max(pending_replay[1],
                                      int(failed_step)))
            else:
                pending_replay = (log.current, int(failed_step))

        def _shrink_recover(step: int) -> Optional[int]:
            """Agreement -> shrunk mesh -> reshard restore -> resume;
            None when the budget is spent or nothing restores."""
            nonlocal restarts, mesh_shrinks
            restarts += 1
            if retry.exhausted(restarts):
                return None
            sleep(retry.delay_s(restarts))
            # refresh liveness first: on the deadline path the monitor
            # has not polled since the hang began — a peer that went
            # silent mid-step must enter the agreement already
            # suspect, and the agreement's bounded response wait (not
            # an allgather) is what finally rules on it
            fleet.beat(step)
            prev_hosts = list(fleet.hosts)
            epoch, survivors = fleet.agree_survivors(step)
            dead = sorted(set(prev_hosts) - set(survivors))
            warnings.warn(
                f"run_elastic: shrinking to healthy mesh at step "
                f"{step}: survivors {survivors}, dead {dead} "
                f"(epoch {epoch})")
            if on_shrink is not None:
                on_shrink(survivors, epoch)
            else:
                from apex_tpu import comm as _comm
                if _comm.is_initialized():
                    _comm.shrink_mesh(survivors)
            # the agreed lowest-rank survivor sweeps the dead hosts'
            # orphaned .tmp files (construction-time GC is scoped to
            # each host's OWN suffix, so nobody else ever would)
            manager.gc_dead_host_tmp(dead, survivors, rank=fleet.host)
            sh = (shrink_sharding() if callable(shrink_sharding)
                  else shrink_sharding)
            resumed = _restore(manager.restore_good, sharding=sh)
            if resumed is None:
                return None
            _rewind_replay(resumed)
            mesh_shrinks += 1
            _note_resize(step)
            fleet.note_shrink(step, epoch, survivors, dead, resumed)
            _note_replay(step)
            return resumed

        def _grow_recover(step: int) -> Optional[int]:
            """Admission -> grown mesh -> reshard restore -> resume.
            The inverse of ``_shrink_recover`` (no retry budget: an
            admission is a planned resize, not a failure); None when
            the round admitted nobody."""
            nonlocal mesh_grows
            candidates = dict(fleet.return_candidates())
            if not candidates:
                return None
            prev_live = set(fleet.live_hosts())
            epoch, members = fleet.agree_admission(step, candidates)
            admitted = sorted(set(members) - prev_live)
            if not admitted:
                # a candidate that went silent again, or a member that
                # still rules it dead: the round degraded to a no-op
                fleet.note_admission_refused(step, candidates,
                                             "not_agreed")
                return None
            warnings.warn(
                f"run_elastic: admitting host(s) {admitted} at step "
                f"{step}: mesh grows to {members} (epoch {epoch})")
            if on_grow is not None:
                on_grow(members, epoch)
            else:
                from apex_tpu import comm as _comm
                if _comm.is_initialized():
                    _comm.grow_mesh(members)
            if grow_max_bucket_bytes is not None and optimizer is not \
                    None and getattr(optimizer, "_plan", None) is not None:
                # per-host HBM changed with the fleet size: re-chunk
                # the BucketPlan so the overlap schedule tracks it; the
                # restore below lands in the new layout through the
                # checkpoint reconstruct path
                cap = (grow_max_bucket_bytes(members)
                       if callable(grow_max_bucket_bytes)
                       else grow_max_bucket_bytes)
                optimizer.rechunk(cap)
            sh = (grow_sharding() if callable(grow_sharding)
                  else grow_sharding)
            resumed = _restore(manager.restore_good, sharding=sh)
            if resumed is None:
                # the mesh already grew: without the reshard restore
                # the admitted hosts hold nothing coherent to train on
                raise _fleet.FleetRecoveryFailed(
                    f"admission at step {step} (hosts {admitted}) "
                    "found no valid checkpoint to reshard onto the "
                    "grown mesh")
            _rewind_replay(resumed)
            mesh_grows += 1
            _note_resize(step)
            fleet.note_grow(step, epoch, members, admitted, resumed)
            _note_replay(step)
            return resumed

        def _voluntary_shrink(step: int, decision) -> Optional[int]:
            """The autoscaler's planned release: exclude the
            highest-rank MEMBER from this host's proposal, agree,
            shrink the mesh and reshard-restore — the failure
            machinery minus the retry budget and the dead-host GC.
            The victim is ``max(fleet.hosts)`` INCLUDING self: every
            host must compute the SAME victim (divergent proposals
            would intersect away two hosts), so when this host is the
            highest rank it excludes itself and ``agree_survivors``
            raises the typed ``FleetRecoveryFailed`` — the released
            host's clean self-eviction path (exit for the external
            scheduler)."""
            nonlocal mesh_shrinks
            if len(fleet.hosts) < 2:
                return None
            victim = max(fleet.hosts)
            prev_hosts = list(fleet.hosts)
            epoch, survivors = fleet.agree_survivors(
                step, exclude=(victim,))
            released = sorted(set(prev_hosts) - set(survivors))
            if not released:
                return None           # peers vetoed the release
            warnings.warn(
                f"run_elastic: autoscaler releasing host(s) "
                f"{released} at step {step} ({decision.reason}="
                f"{decision.signal}): mesh shrinks to {survivors} "
                f"(epoch {epoch})")
            if on_shrink is not None:
                on_shrink(survivors, epoch)
            else:
                from apex_tpu import comm as _comm
                if _comm.is_initialized():
                    _comm.shrink_mesh(survivors)
            sh = (shrink_sharding() if callable(shrink_sharding)
                  else shrink_sharding)
            resumed = _restore(manager.restore_good, sharding=sh)
            if resumed is None:
                raise _fleet.FleetRecoveryFailed(
                    f"autoscale release at step {step} found no valid "
                    "checkpoint to reshard onto the shrunk mesh")
            _rewind_replay(resumed)
            mesh_shrinks += 1
            _note_resize(step)
            fleet.note_shrink(step, epoch, survivors, released,
                              resumed, reason="autoscale")
            _note_replay(step)
            return resumed

        def _admission_and_autoscale(step: int) -> Optional[int]:
            """The grow half of the boundary: execute the autoscaler's
            decision, or (without one) admit any return candidates
            under the plain hysteresis gates.  Returns the resumed
            step when a resize+restore happened."""
            candidates = fleet.return_candidates()
            incident = (watchdog.open_incident(step)
                        if watchdog is not None else False)
            if autoscale is not None:
                dec = autoscale.decide(step, n_hosts=len(fleet.hosts),
                                       candidates=len(candidates),
                                       incident=incident)
                if dec.action == "grow":
                    return _grow_recover(step)
                if dec.action == "shrink":
                    return _voluntary_shrink(step, dec)
                if candidates and dec.reason == "open_incident":
                    fleet.note_admission_refused(step, candidates,
                                                 "open_incident")
                return None
            if not candidates:
                return None
            if incident:
                # grow_during_incident: resharding (and replicating
                # onto a new host) state the watchdog may be about to
                # roll away from — refuse until the incident closes
                fleet.note_admission_refused(step, candidates,
                                             "open_incident")
                return None
            if last_resize_step is not None and \
                    step - last_resize_step < admission_cooldown_steps:
                fleet.note_admission_refused(step, candidates,
                                             "cooldown")
                return None
            return _grow_recover(step)

        def _forced_save(step: int) -> None:
            """Save NOW, surviving transient IO errors (bounded)."""
            for attempt in range(retry.max_retries + 1):
                try:
                    manager.save(step, optimizer=optimizer, **_extras())
                    manager.wait()
                    return
                except retryable as e:
                    if _fatal_io(e) or attempt == retry.max_retries:
                        raise
                    warnings.warn(
                        f"run_elastic: final save at step {step} "
                        f"failed ({type(e).__name__}: {e}); retrying")
                    sleep(retry.delay_s(attempt + 1))

        restored_from = _restore()
        last_done = restored_from if restored_from is not None else 0
        step = last_done + 1
        while step <= total_steps:
            saved_now = False
            try:
                t_step0 = time.monotonic()
                _armed_step(step)         # chaos hook rides inside
                if autoscale is not None:
                    autoscale.note_step(step,
                                        time.monotonic() - t_step0)
                last_done = step
                # evaluate extras ONLY on cadence steps: state_dict()
                # callbacks device_get (loss scale etc.), and a
                # per-step host sync is the hazard class this whole
                # stack avoids (APX102)
                due = manager.due(step)
                saved_now = _armed_save(step, _extras() if due else {})
            except _fleet.StepDeadlineExceeded as e:
                # a hung collective, converted: without a fleet
                # monitor there is nobody to agree a shrink with —
                # propagate (the external scheduler restarts the job)
                if fleet is None:
                    raise
                fleet.note_deadline(e)
                warnings.warn(
                    f"run_elastic: {e.phase} at step {step} exceeded "
                    f"its {e.deadline_s:.3g}s deadline (hung "
                    "collective?); entering shrink recovery")
                resumed = _shrink_recover(step)
                if resumed is None:
                    raise _fleet.FleetRecoveryFailed(
                        f"step-deadline recovery at step {step} "
                        f"failed (restart {restarts}/"
                        f"{retry.max_retries} or no valid "
                        "checkpoint)") from e
                last_done = resumed
                step = resumed + 1
                continue
            except retryable as e:
                if _fatal_io(e):
                    # ENOSPC and friends: retrying is hopeless —
                    # straight to the post-mortem-and-abort path
                    _abort_fatal_io(step, e)
                    raise
                restarts += 1
                if retry.exhausted(restarts):
                    raise
                warnings.warn(
                    f"run_elastic: step {step} failed "
                    f"({type(e).__name__}: {e}); restoring newest "
                    f"valid checkpoint (restart {restarts}/"
                    f"{retry.max_retries})")
                sleep(retry.delay_s(restarts))
                resumed = _restore()
                if resumed is None:
                    # nothing valid to restore onto — the optimizer may
                    # hold post-failure state; restarting "fresh" here
                    # would silently train from a dirty midpoint
                    raise
                last_done = resumed
                step = resumed + 1
                continue
            if pending_replay is not None \
                    and last_done >= pending_replay[1]:
                # the replay caught back up to the step the incident
                # opened at: the causal chain is over — one
                # replay_complete event carries the id out, and the
                # register is free for the next incident
                iid, _ = pending_replay
                pending_replay = None
                if fleet is not None:
                    fleet.note_replay_complete(last_done,
                                               incident_id=iid)
                elif watchdog is not None:
                    watchdog.note_replay_complete(last_done,
                                                  incident_id=iid)
            if watchdog is not None:
                if saved_now:
                    # the save starts aging toward last-known-good;
                    # pinned so rotation cannot delete a candidate
                    manager.pin(step)
                    watchdog.note_save(step)
                verdict = watchdog.check(step)
                for s, good in watchdog.resolved_saves():
                    if good:
                        manager.mark_good(s)     # unpins; LKG pinned
                    else:
                        manager.unpin(s)
                if verdict.action == _watchdog.ACTION_QUARANTINE:
                    warnings.warn(
                        f"run_elastic: watchdog quarantined step "
                        f"{step} ({verdict.anomaly.kind}: "
                        f"{dict(verdict.anomaly.evidence)})")
                    watchdog.note_quarantine(step, verdict.anomaly)
                    if on_quarantine is not None:
                        on_quarantine(verdict.anomaly)
                elif verdict.action == _watchdog.ACTION_ROLLBACK:
                    warnings.warn(
                        f"run_elastic: watchdog rollback at step "
                        f"{step} ({verdict.anomaly.kind}); restoring "
                        f"last-known-good (rollback "
                        f"{watchdog.rollbacks}/"
                        f"{watchdog.policy.rollback.max_retries})")
                    sleep(watchdog.policy.rollback.delay_s(
                        watchdog.rollbacks))
                    resumed = _restore(manager.restore_good)
                    if resumed is None:
                        # nothing proven-good to roll onto: recovery
                        # is impossible, not merely over budget
                        pm = watchdog.write_postmortem(
                            step, verdict.anomaly,
                            directory=watchdog.postmortem_dir
                            or manager.directory)
                        raise _watchdog.WatchdogAbort(
                            f"watchdog rollback at step {step} "
                            f"({verdict.anomaly.kind}) found no valid "
                            f"checkpoint to roll back to; post-mortem: "
                            f"{pm}", pm)
                    rollbacks += 1
                    watchdog.note_rollback(resumed, step,
                                           verdict.anomaly)
                    _note_replay(step)
                    last_done = resumed
                    step = resumed + 1
                    continue
                elif verdict.action == _watchdog.ACTION_ABORT:
                    pm = watchdog.write_postmortem(
                        step, verdict.anomaly,
                        directory=watchdog.postmortem_dir
                        or manager.directory)
                    raise _watchdog.WatchdogAbort(
                        f"watchdog abort at step {step}"
                        + (f" ({verdict.anomaly.kind})"
                           if verdict.anomaly else "")
                        + f"; recovery exhausted after "
                        f"{watchdog.rollbacks} rollback(s); "
                        f"post-mortem: {pm}", pm)
            if fleet is not None:
                failures = fleet.beat(step)
                for f in failures:
                    if f.kind == "host_slow":
                        # a slow peer is an infrastructure warning,
                        # never an eviction
                        warnings.warn(
                            f"run_elastic: peer host {f.host} is slow "
                            f"(beacon gap {f.gap_s:.3g}s, lag "
                            f"{f.lag_steps} steps)")
                    elif f.kind == "host_return":
                        warnings.warn(
                            f"run_elastic: peer host {f.host} "
                            "returned with a fresh incarnation "
                            f"({dict(f.evidence).get('incarnation')});"
                            " awaiting admission at a step boundary")
                dead = [f for f in failures if f.kind == "host_dead"]
                if dead:
                    warnings.warn(
                        f"run_elastic: peer host(s) "
                        f"{sorted(f.host for f in dead)} declared "
                        f"dead at step {step}; entering shrink "
                        "recovery")
                    resumed = _shrink_recover(step)
                    if resumed is None:
                        raise _fleet.FleetRecoveryFailed(
                            f"peer-death recovery at step {step} "
                            f"failed (restart {restarts}/"
                            f"{retry.max_retries} or no valid "
                            "checkpoint)")
                    last_done = resumed
                    step = resumed + 1
                    continue
                # the grow half of the boundary: autoscaler decision
                # or plain admission of return candidates (hysteresis
                # gates inside)
                resumed = _admission_and_autoscale(step)
                if resumed is not None:
                    last_done = resumed
                    step = resumed + 1
                    continue
            if guard is not None and guard.check(step):
                # preemption notice -> durable-now-then-clean-exit at
                # this step boundary.  A cadence save just scheduled
                # for THIS step only needs its durability wait — a
                # second full write would double time-to-durable
                # inside the eviction grace window
                if saved_now:
                    try:
                        manager.wait()
                    except retryable as e:
                        warnings.warn(
                            f"run_elastic: final save at step {step} "
                            f"failed ({type(e).__name__}: {e}); "
                            "rewriting")
                        _forced_save(step)
                else:
                    _forced_save(step)
                return ElasticResult(step=step, preempted=True,
                                     restarts=restarts,
                                     restored_from=restored_from,
                                     rollbacks=rollbacks,
                                     mesh_shrinks=mesh_shrinks,
                                     mesh_grows=mesh_grows)
            step += 1
        try:
            manager.wait()                # final cadence save durable
        except retryable as e:
            if _fatal_io(e):
                _abort_fatal_io(last_done, e)
                raise
            # the LAST async save's deferred failure surfaces here,
            # past the loop's retry handling — re-write the newest
            # state under the same bounded-retry contract
            warnings.warn(
                f"run_elastic: final save failed "
                f"({type(e).__name__}: {e}); retrying")
            _forced_save(last_done)
        return ElasticResult(step=last_done, preempted=False,
                             restarts=restarts,
                             restored_from=restored_from,
                             rollbacks=rollbacks,
                             mesh_shrinks=mesh_shrinks,
                             mesh_grows=mesh_grows)
    finally:
        if runner is not None:
            runner.close()
        if own_guard:
            guard.uninstall()
