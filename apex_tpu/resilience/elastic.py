"""``run_elastic`` — the in-job supervisor loop that makes a train
loop survive what preemptible fleets actually do to it.

The reference has no failure story (SURVEY.md §5: a crashed rank kills
the job).  ``run_elastic`` wraps a user step function with the full
recovery contract:

- **resume**: restore the newest valid checkpoint before the first
  step (reusing ``CheckpointManager.restore_latest`` — including its
  multi-host lockstep agreement, so every host resumes from the SAME
  step or none does);
- **cadence saves** through the manager (bucket-native v2 when the
  optimizer runs bucketed);
- **transient-failure recovery**: a step or save raising a retryable
  error (``OSError`` by default — flaky disk, NFS hiccup) triggers
  bounded retry-with-backoff: restore the newest valid checkpoint and
  resume from it (training replay is deterministic from a checkpoint,
  so the result is bit-identical to an uninterrupted run);
- **preemption**: a :class:`~.preemption.PreemptionGuard` notice
  (SIGTERM / ``--preempt-at-step``) converts into one final FORCED
  save at the current step boundary, a durability wait, and a clean
  return with ``preempted=True``;
- **self-healing** (``watchdog=``): a
  :class:`~.watchdog.Watchdog` polled at every step boundary turns
  detected training anomalies into the escalation ladder — quarantine
  (the ``on_quarantine`` hook re-anchors the loss scale), bounded
  rollback-and-replay onto the last-known-good checkpoint, or
  abort-with-diagnostics (:class:`~.watchdog.WatchdogAbort` after the
  post-mortem bundle is written).  Cadence saves age toward
  last-known-good through the watchdog's clean-window rule, pinned
  against rotation while they age.

The user's step function owns the optimizer and any AMP state (a
closure); ``save_extras``/``on_restore`` thread the non-optimizer
state (amp scaler dict, BN batch_stats) through the checkpoint bundle:

>>> def step_fn(step):                      # 1-based steps
...     loss, flat = pipe.scaled_value_and_grad(...)
...     opt.step(flat)
...     box["amp"] = amp.update_scaler(box["amp"], flat.found_inf)
>>> res = run_elastic(
...     step_fn, mgr, opt, total_steps=1000,
...     guard=PreemptionGuard(),
...     save_extras=lambda: {"amp_state": box["amp"].state_dict()},
...     on_restore=lambda amp_sd, extra, step:
...         box.update(amp=box["amp"].load_state_dict(amp_sd)))
>>> if res.preempted: sys.exit(0)           # checkpoint is durable
"""

from __future__ import annotations

import dataclasses
import inspect
import time
import warnings
from typing import Any, Callable, Optional, Tuple, Type

import jax

from apex_tpu.resilience import faults as _faults
from apex_tpu.resilience import watchdog as _watchdog
from apex_tpu.resilience.manager import CheckpointManager
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.resilience.retry import RetryPolicy

Pytree = Any


@dataclasses.dataclass
class ElasticResult:
    """What the supervisor loop did."""
    step: int                       # last COMPLETED step
    preempted: bool                 # True: exited on a notice, final
    #                                 checkpoint durable at .step
    restarts: int                   # in-job recoveries performed
    restored_from: Optional[int]    # initial resume step (None: fresh)
    rollbacks: int = 0              # watchdog rollback-and-replays


def run_elastic(step_fn: Callable[[int], Any],
                manager: CheckpointManager,
                optimizer=None, *,
                total_steps: int,
                params_like: Optional[Pytree] = None,
                extra_like: Optional[Pytree] = None,
                guard: Optional[PreemptionGuard] = None,
                watchdog=None,
                on_quarantine: Optional[Callable] = None,
                save_extras: Optional[Callable[[], dict]] = None,
                on_restore: Optional[Callable] = None,
                retryable: Tuple[Type[BaseException], ...] = (OSError,),
                retry: Optional[RetryPolicy] = None,
                max_restarts: int = 3,
                backoff_s: float = 0.05,
                sleep: Callable[[float], None] = time.sleep
                ) -> ElasticResult:
    """Drive ``step_fn(step)`` for steps ``1..total_steps`` (1-based,
    matching the manager's save cadence) under the recovery contract in
    the module docstring.

    ``params_like``: restore template (shapes/dtypes suffice —
    ``jax.ShapeDtypeStruct`` leaves are fine); defaults to the shape
    structure of ``optimizer.params``.  ``save_extras() -> dict`` may
    return ``amp_state=`` and/or ``extra=`` for the checkpoint bundle
    — and, with ``optimizer=None``, the ``params=`` pytree the
    per-leaf save requires;
    ``on_restore(amp_sd, extra, step)`` — or the 4-arg form
    ``on_restore(amp_sd, extra, step, params)``, opted into by naming
    the 4th parameter ``params`` (it is passed by keyword) — is
    called after every restore (``amp_sd``/``extra`` as saved) so the
    caller can rebind its own state.  With ``optimizer=None`` the
    4-arg form is REQUIRED: the restored params can only reach the
    caller's closure through it.  ``retryable`` failures of a step OR save trigger
    restore-newest-valid-and-resume under ``retry`` (a
    :class:`~apex_tpu.resilience.retry.RetryPolicy`; defaults to one
    built from the legacy ``max_restarts``/``backoff_s`` knobs);
    anything else propagates (a real crash — the external scheduler
    restarts the job, and the next ``run_elastic`` resumes).

    ``watchdog``: a :class:`~apex_tpu.resilience.watchdog.Watchdog`
    polled once per step boundary; its verdicts execute here —
    quarantine calls ``on_quarantine(anomaly)`` (re-anchor the loss
    scale, drop the window), rollback restores the last-known-good
    checkpoint through the manager (multi-host lockstep agreement
    included) and replays under the watchdog's own
    ``policy.rollback`` budget + widening backoff, abort writes the
    post-mortem bundle then raises ``WatchdogAbort``.  Cadence saves
    are reported to the watchdog and pinned until the clean-window
    rule resolves them (good -> ``manager.mark_good``)."""
    if optimizer is None and params_like is None:
        raise ValueError("need an optimizer or params_like to restore")
    if retry is None:
        retry = RetryPolicy(max_retries=max_restarts,
                            base_delay_s=backoff_s)
    if params_like is None:
        # only the SHAPES are the template; holding the unpacked
        # pytree itself would pin a params-sized HBM copy all run
        params_like = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
            optimizer.params)
    wants_params = False
    if on_restore is not None:
        # opt-in by NAME, not arity: a defaulted 4th flag parameter
        # must not silently receive the params pytree
        sig = inspect.signature(on_restore)
        wants_params = ("params" in sig.parameters or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values()))
    if optimizer is None and not wants_params:
        raise ValueError(
            "run_elastic(optimizer=None) restores params only through "
            "on_restore(amp_sd, extra, step, params) — name its 4th "
            "parameter 'params' (or accept **kwargs); without it a "
            "resumed run would silently keep its freshly-initialized "
            "weights")
    own_guard = guard is not None and not guard._installed
    if own_guard:
        guard.install()
    restarts = 0
    rollbacks = 0
    try:
        def _extras() -> dict:
            return save_extras() if save_extras is not None else {}

        def _restore(restore_fn=None) -> Optional[int]:
            out = (restore_fn or manager.restore_latest)(
                params_like, optimizer, extra_like=extra_like)
            if out is None:
                return None
            if on_restore is not None:
                args = (out[1],
                        out[3] if extra_like is not None else None,
                        out[2])
                if wants_params:
                    on_restore(*args, params=out[0])
                else:
                    on_restore(*args)
            return out[2]

        def _forced_save(step: int) -> None:
            """Save NOW, surviving transient IO errors (bounded)."""
            for attempt in range(retry.max_retries + 1):
                try:
                    manager.save(step, optimizer=optimizer, **_extras())
                    manager.wait()
                    return
                except retryable as e:
                    if attempt == retry.max_retries:
                        raise
                    warnings.warn(
                        f"run_elastic: final save at step {step} "
                        f"failed ({type(e).__name__}: {e}); retrying")
                    sleep(retry.delay_s(attempt + 1))

        restored_from = _restore()
        last_done = restored_from if restored_from is not None else 0
        step = last_done + 1
        while step <= total_steps:
            _faults.notify_step(step)     # chaos hook; no-op normally
            saved_now = False
            try:
                step_fn(step)
                last_done = step
                # evaluate extras ONLY on cadence steps: state_dict()
                # callbacks device_get (loss scale etc.), and a
                # per-step host sync is the hazard class this whole
                # stack avoids (APX102)
                due = manager.due(step)
                saved_now = manager.maybe_save(
                    step, optimizer=optimizer,
                    **(_extras() if due else {}))
            except retryable as e:
                restarts += 1
                if retry.exhausted(restarts):
                    raise
                warnings.warn(
                    f"run_elastic: step {step} failed "
                    f"({type(e).__name__}: {e}); restoring newest "
                    f"valid checkpoint (restart {restarts}/"
                    f"{retry.max_retries})")
                sleep(retry.delay_s(restarts))
                resumed = _restore()
                if resumed is None:
                    # nothing valid to restore onto — the optimizer may
                    # hold post-failure state; restarting "fresh" here
                    # would silently train from a dirty midpoint
                    raise
                last_done = resumed
                step = resumed + 1
                continue
            if watchdog is not None:
                if saved_now:
                    # the save starts aging toward last-known-good;
                    # pinned so rotation cannot delete a candidate
                    manager.pin(step)
                    watchdog.note_save(step)
                verdict = watchdog.check(step)
                for s, good in watchdog.resolved_saves():
                    if good:
                        manager.mark_good(s)     # unpins; LKG pinned
                    else:
                        manager.unpin(s)
                if verdict.action == _watchdog.ACTION_QUARANTINE:
                    warnings.warn(
                        f"run_elastic: watchdog quarantined step "
                        f"{step} ({verdict.anomaly.kind}: "
                        f"{dict(verdict.anomaly.evidence)})")
                    watchdog.note_quarantine(step, verdict.anomaly)
                    if on_quarantine is not None:
                        on_quarantine(verdict.anomaly)
                elif verdict.action == _watchdog.ACTION_ROLLBACK:
                    warnings.warn(
                        f"run_elastic: watchdog rollback at step "
                        f"{step} ({verdict.anomaly.kind}); restoring "
                        f"last-known-good (rollback "
                        f"{watchdog.rollbacks}/"
                        f"{watchdog.policy.rollback.max_retries})")
                    sleep(watchdog.policy.rollback.delay_s(
                        watchdog.rollbacks))
                    resumed = _restore(manager.restore_good)
                    if resumed is None:
                        # nothing proven-good to roll onto: recovery
                        # is impossible, not merely over budget
                        pm = watchdog.write_postmortem(
                            step, verdict.anomaly,
                            directory=watchdog.postmortem_dir
                            or manager.directory)
                        raise _watchdog.WatchdogAbort(
                            f"watchdog rollback at step {step} "
                            f"({verdict.anomaly.kind}) found no valid "
                            f"checkpoint to roll back to; post-mortem: "
                            f"{pm}", pm)
                    rollbacks += 1
                    watchdog.note_rollback(resumed, step,
                                           verdict.anomaly)
                    last_done = resumed
                    step = resumed + 1
                    continue
                elif verdict.action == _watchdog.ACTION_ABORT:
                    pm = watchdog.write_postmortem(
                        step, verdict.anomaly,
                        directory=watchdog.postmortem_dir
                        or manager.directory)
                    raise _watchdog.WatchdogAbort(
                        f"watchdog abort at step {step}"
                        + (f" ({verdict.anomaly.kind})"
                           if verdict.anomaly else "")
                        + f"; recovery exhausted after "
                        f"{watchdog.rollbacks} rollback(s); "
                        f"post-mortem: {pm}", pm)
            if guard is not None and guard.check(step):
                # preemption notice -> durable-now-then-clean-exit at
                # this step boundary.  A cadence save just scheduled
                # for THIS step only needs its durability wait — a
                # second full write would double time-to-durable
                # inside the eviction grace window
                if saved_now:
                    try:
                        manager.wait()
                    except retryable as e:
                        warnings.warn(
                            f"run_elastic: final save at step {step} "
                            f"failed ({type(e).__name__}: {e}); "
                            "rewriting")
                        _forced_save(step)
                else:
                    _forced_save(step)
                return ElasticResult(step=step, preempted=True,
                                     restarts=restarts,
                                     restored_from=restored_from,
                                     rollbacks=rollbacks)
            step += 1
        try:
            manager.wait()                # final cadence save durable
        except retryable as e:
            # the LAST async save's deferred failure surfaces here,
            # past the loop's retry handling — re-write the newest
            # state under the same bounded-retry contract
            warnings.warn(
                f"run_elastic: final save failed "
                f"({type(e).__name__}: {e}); retrying")
            _forced_save(last_done)
        return ElasticResult(step=last_done, preempted=False,
                             restarts=restarts,
                             restored_from=restored_from,
                             rollbacks=rollbacks)
    finally:
        if own_guard:
            guard.uninstall()
