"""Deterministic fault injection for the checkpoint/restart stack —
the chaos harness that PROVES recovery works instead of asserting it.

Real failure modes, injected at the exact layer they occur in
production, on a deterministic (optionally seeded) schedule:

===================  ======================================================
kind                 what happens
===================  ======================================================
``truncate``         the payload write stops mid-buffer and the writer
                     dies (``InjectedCrash``) — the mid-write-crash
                     artifact: a torn ``.tmp`` that must never publish
``fsync_error``      ``fsync`` raises ``OSError(EIO)`` once — the
                     transient-disk case ``run_elastic`` retries with
                     backoff
``slow_disk``        the ``.tmp`` open stalls ``delay_s`` seconds —
                     surfaces as ``ckpt/blocked_ms`` backpressure, must
                     corrupt nothing
``preempt``          a real ``SIGTERM`` is delivered to this process at
                     step ``at_step`` (via the ``notify_step`` hook
                     ``run_elastic`` calls each iteration) — drives the
                     actual :class:`~.preemption.PreemptionGuard` path
``crash_before_publish``  write + fsync complete, the process dies
                     between the durable ``.tmp`` and the atomic
                     ``os.replace`` — the unpublished-checkpoint case
``nan_grads``        advisory: the training loop should poison this
                     step's gradients with NaN (the hardware-flake /
                     bad-batch NaN storm the watchdog detects)
``loss_spike``       advisory: the training loop should spike this
                     step's loss (corrupt batch / divergence onset)
``scale_collapse``   advisory: the training loop should feed the
                     scaler intermittent overflows so the loss scale
                     pins at its floor without a contiguous NaN streak
``straggler``        ``notify_step`` stalls ``delay_s`` seconds — a
                     simulated slow host, visible as a step-time
                     regression to the watchdog's straggler detector
``disk_full``        the payload write raises ``OSError(ENOSPC)`` — the
                     NON-retryable disk failure ``run_elastic`` must
                     abort on instead of burning its retry budget
``peer_death``       advisory (fleet): the targeted simulated peer
                     stops beaconing forever — a crashed host, detected
                     by the FleetMonitor's liveness deadlines
``peer_hang``        advisory (fleet) + local stall: the targeted peer
                     stops beaconing AND ``notify_step`` blocks
                     ``delay_s`` seconds — the hung-collective shape a
                     deadline-armed step converts into
                     ``StepDeadlineExceeded``
``slow_network``     advisory (fleet): the targeted peer's beacons
                     arrive ``lag_steps`` steps / ``delay_s`` seconds
                     stale for ``n_steps`` beats — a slow peer the
                     monitor warns about but never evicts
``host_return``      advisory (fleet): a previously-dead peer resumes
                     beaconing under a FRESH incarnation — the rejoin
                     candidate the admission round admits at a step
                     boundary (a stale-incarnation beacon would be a
                     split-brain zombie and stays ignored)
``flapping_host``    advisory (fleet): the peer returns with a fresh
                     incarnation then dies AGAIN when its ``n_steps``
                     budget expires — hysteresis (the post-resize
                     admission cooldown) must yield exactly one shrink
                     and zero grow/shrink oscillation
``grow_during_incident``  advisory (fleet): the peer returns while the
                     watchdog has an OPEN incident — the admission
                     must be refused (``admission_refused`` timeline
                     event) until the incident closes
``hung_decode``      advisory (serving): the engine's next decode
                     dispatch stalls ``delay_s`` seconds inside the
                     deadline-armed thunk's prologue — the wedged
                     compile/dispatch shape that converts into a typed
                     ``DecodeDeadlineExceeded`` and evicts only the
                     suspect requests, never the process
``slow_request``     advisory (serving): the targeted in-flight
                     request (slot ``target``, default the lowest
                     active slot) is treated as past its per-request
                     deadline — evicted with the typed
                     ``deadline_exceeded`` verdict, everyone else
                     untouched
``replica_death``    advisory (serving): the targeted peer REPLICA
                     stops beaconing — detected by the fleet monitor,
                     opens an incident, and the surviving replica
                     re-admits the dead peer's published queue under
                     that incident id
``queue_storm``      advisory (serving): a burst of synthetic requests
                     floods the engine's admission queue each window
                     the budget covers — the bounded queue must shed
                     with typed ``backpressure``/``queue_full``
                     verdicts under watermark hysteresis, zero
                     requests dropped without a verdict
``oom_admission``    advisory (serving): one synthetic request whose
                     prompt + budget exceeds a slot's page capacity —
                     admission must shed it immediately with the typed
                     ``oom_admission`` reason (queueing cannot help)
===================  ======================================================

The injector subclasses :class:`apex_tpu.checkpoint.CheckpointIO` and
installs itself with :func:`apex_tpu.checkpoint.set_io`, so every
checkpoint writer (v1 and v2, sync and async) runs through it without
test-only branches in library code.  Each fault fires once (tracked in
``fired``), keyed by the 0-based ordinal of the checkpoint write it
targets (``at_save``) or the training step (``at_step`` for the
step-keyed kinds).

Training-state faults (``nan_grads`` / ``loss_spike`` /
``scale_collapse``) are ADVISORY: fault injection cannot reach into a
user step function's gradients from outside, so the training loop asks
:func:`training_fault` once per step and applies the returned kind
itself (``examples/simple/train_toy.py --inject-nan-at`` and the chaos
suite are the reference consumers; production pays one module-global
read).  Their activation is BUDGETED, not step-ranged: a fault with
``n_steps=4`` poisons the first 4 steps at/after ``at_step`` it is
asked about and then stays spent — so a rollback that replays those
step numbers replays them CLEAN, which is exactly the
recovery-then-bit-exact-replay contract the chaos matrix asserts.
"""

from __future__ import annotations

import errno
import os
import signal
import threading
import time
from typing import List, NamedTuple, Optional, Sequence

from apex_tpu import checkpoint as _ckpt


class InjectedCrash(RuntimeError):
    """Simulated process death mid-save.  Deliberately NOT an OSError:
    ``run_elastic`` retries transient IO errors but a crash kills the
    job — the chaos tests catch this, then restart training the way an
    external supervisor would."""


class FaultSpec(NamedTuple):
    kind: str                       # one of FaultInjector.KINDS
    at_save: Optional[int] = None   # 0-based checkpoint-write ordinal
    at_step: Optional[int] = None   # training step (step-keyed kinds)
    delay_s: float = 0.0            # slow_disk / straggler / hang stall
    n_steps: int = 1                # training/fleet application budget
    target: Optional[int] = None    # peer host index (fleet kinds)
    lag_steps: int = 4              # slow_network beacon staleness


# module-level active injector: run_elastic's per-step chaos hook
# (notify_step) must find it without the supervisor importing test code
_ACTIVE: Optional["FaultInjector"] = None


def notify_step(step: int) -> None:
    """Per-step chaos hook (called by ``run_elastic``; a no-op unless a
    FaultInjector is installed — production pays one global read)."""
    if _ACTIVE is not None:
        _ACTIVE.on_step(step)


def training_fault(step: int) -> Optional[FaultSpec]:
    """The training-state fault a loop should apply at ``step``, if any
    (a no-op None unless a FaultInjector is installed).  Consumes one
    unit of the fault's ``n_steps`` budget per call — ask exactly once
    per step."""
    if _ACTIVE is not None:
        return _ACTIVE.training_fault(step)
    return None


def fleet_fault(step: int) -> Optional[FaultSpec]:
    """The fleet fault (peer_death / peer_hang / slow_network) the
    beacon simulation should apply at ``step``, if any (a no-op None
    unless a FaultInjector is installed).  Consumes one unit of the
    fault's ``n_steps`` budget per call — ask exactly once per beat."""
    if _ACTIVE is not None:
        return _ACTIVE.fleet_fault(step)
    return None


def serving_fault(step: int) -> Optional[FaultSpec]:
    """The serving fault the decode engine should apply at serve
    window ``step``, if any (a no-op None unless a FaultInjector is
    installed).  Consumes one unit of the fault's ``n_steps`` budget
    per call — the engine asks exactly once per window."""
    if _ACTIVE is not None:
        return _ACTIVE.serving_fault(step)
    return None


class FaultInjector(_ckpt.CheckpointIO):
    """Checkpoint-IO implementation that injects the scheduled faults.

    >>> faults = [FaultSpec("truncate", at_save=1)]
    >>> with FaultInjector(faults):
    ...     train()        # the 2nd checkpoint write dies mid-payload
    """

    KINDS = ("truncate", "fsync_error", "slow_disk", "preempt",
             "crash_before_publish", "disk_full",
             "nan_grads", "loss_spike", "scale_collapse", "straggler",
             "peer_death", "peer_hang", "slow_network",
             "host_return", "flapping_host", "grow_during_incident",
             "hung_decode", "slow_request", "replica_death",
             "queue_storm", "oom_admission")
    # step-keyed kinds delivered through notify_step/training_fault
    STEP_KINDS = ("preempt", "nan_grads", "loss_spike",
                  "scale_collapse", "straggler",
                  "peer_death", "peer_hang", "slow_network",
                  "host_return", "flapping_host",
                  "grow_during_incident",
                  "hung_decode", "slow_request", "replica_death",
                  "queue_storm", "oom_admission")
    # advisory kinds the TRAINING LOOP applies (training_fault)
    TRAINING_KINDS = ("nan_grads", "loss_spike", "scale_collapse")
    # advisory kinds the FLEET beacon simulation applies (fleet_fault)
    FLEET_KINDS = ("peer_death", "peer_hang", "slow_network",
                   "host_return", "flapping_host",
                   "grow_during_incident")
    # advisory kinds the SERVING engine applies (serving_fault) —
    # at_step is the serve-loop WINDOW ordinal, not a training step
    SERVING_KINDS = ("hung_decode", "slow_request", "replica_death",
                     "queue_storm", "oom_admission")

    def __init__(self, faults: Sequence[FaultSpec]):
        for f in faults:
            if f.kind not in self.KINDS:
                raise ValueError(f"unknown fault kind {f.kind!r}; "
                                 f"known: {self.KINDS}")
            if f.kind in self.STEP_KINDS and f.at_step is None:
                raise ValueError(f"{f.kind} faults need at_step")
            if f.kind not in self.STEP_KINDS and f.at_save is None:
                raise ValueError(f"{f.kind} faults need at_save")
        self.faults = list(faults)
        self.fired: List[FaultSpec] = []
        self.saves = -1            # ordinal of the CURRENT write
        # all bookkeeping is INDEX-keyed: specs are not unique (two
        # identical nan storms may be scheduled), so NamedTuple
        # equality would alias them — fired mirrors _fired_idx
        self._fired_idx: set = set()
        self._hang_stalled: set = set()    # peer_hang local stalls taken
        self._spent = [0] * len(self.faults)
        self._lock = threading.Lock()
        self._prev: Optional[_ckpt.CheckpointIO] = None

    def _mark_fired(self, idx: int) -> None:
        """Record fault ``idx`` as fired (caller holds the lock)."""
        # every caller sits inside `with self._lock:` (the hooks'
        # shared discipline); the helper itself stays lock-free so it
        # can be called mid-critical-section without deadlocking
        if idx not in self._fired_idx:
            self._fired_idx.add(idx)   # apexlint: disable=APX1001
            self.fired.append(self.faults[idx])   # apexlint: disable=APX1001

    @classmethod
    def seeded(cls, seed: int, n_saves: int = 8,
               kinds: Optional[Sequence[str]] = None,
               delay_s: float = 0.05) -> "FaultInjector":
        """A deterministic pseudo-random schedule: same seed, same
        faults, forever — the property a chaos suite needs to be
        debuggable.  Picks one fault kind per save ordinal with ~50%
        probability (the step-keyed kinds — preempt and the
        training-state faults — are excluded: schedule those
        explicitly with at_step)."""
        import random
        rng = random.Random(seed)
        kinds = tuple(kinds or ("truncate", "fsync_error", "slow_disk",
                                "crash_before_publish"))
        faults = [FaultSpec(rng.choice(kinds), at_save=i,
                            delay_s=delay_s)
                  for i in range(n_saves) if rng.random() < 0.5]
        return cls(faults)

    # ---- lifecycle -------------------------------------------------------
    def install(self) -> "FaultInjector":
        global _ACTIVE
        self._prev = _ckpt.set_io(self)
        # rebound on the main thread before run_elastic arms its
        # worker; notify_step and the fault hooks do one GIL-atomic
        # reference read and tolerate None at any point
        _ACTIVE = self   # apexlint: disable=APX1001
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if self._prev is not None:
            _ckpt.set_io(self._prev)
            self._prev = None
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "FaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- schedule --------------------------------------------------------
    def _take(self, kind: str) -> Optional[FaultSpec]:
        """Pop-and-fire the first unfired fault of ``kind`` scheduled
        for the current save ordinal."""
        with self._lock:
            for i, f in enumerate(self.faults):
                if f.kind == kind and f.at_save == self.saves \
                        and i not in self._fired_idx:
                    self._mark_fired(i)
                    return f
        return None

    def _draw_step_fault(self, step: int, kinds) -> Optional[FaultSpec]:
        """Pop one unit of budget from the first due step-keyed fault
        of ``kinds`` (record in ``fired`` on first application)."""
        with self._lock:
            for i, f in enumerate(self.faults):
                if f.kind in kinds and f.at_step is not None \
                        and step >= f.at_step \
                        and self._spent[i] < max(1, f.n_steps):
                    self._spent[i] += 1
                    self._mark_fired(i)
                    return f
        return None

    def on_step(self, step: int) -> None:
        """Step-keyed faults (called from ``notify_step``): deliver a
        REAL SIGTERM so the whole PreemptionGuard signal path is what
        gets tested, not a shortcut flag; a ``straggler`` fault stalls
        the step boundary itself — a slow host, not slow disk.  A
        ``peer_hang`` stalls too (the hung collective's LOCAL
        manifestation: this host blocks inside the psum its hung peer
        never joins), on top of the beacon suppression the fleet
        simulation applies — with a deadline-armed step, the stall is
        what converts into ``StepDeadlineExceeded``."""
        lag = self._draw_step_fault(step, ("straggler",))
        if lag is not None:
            time.sleep(lag.delay_s)
        hang = None
        with self._lock:
            for i, f in enumerate(self.faults):
                if f.kind == "peer_hang" and f.at_step is not None \
                        and step >= f.at_step \
                        and i not in self._hang_stalled:
                    self._hang_stalled.add(i)
                    hang = f
                    break
        if hang is not None and hang.delay_s > 0:
            time.sleep(hang.delay_s)
        with self._lock:
            due = [i for i, f in enumerate(self.faults)
                   if f.kind == "preempt" and i not in self._fired_idx
                   and f.at_step is not None and step >= f.at_step]
            for i in due:
                self._mark_fired(i)
        if due:
            os.kill(os.getpid(), signal.SIGTERM)

    def training_fault(self, step: int) -> Optional[FaultSpec]:
        """The advisory training-state fault to apply at ``step`` (one
        budget unit consumed per call — module docstring)."""
        return self._draw_step_fault(step, self.TRAINING_KINDS)

    def fleet_fault(self, step: int) -> Optional[FaultSpec]:
        """The advisory fleet fault the beacon simulation should apply
        at ``step`` (one budget unit consumed per call)."""
        return self._draw_step_fault(step, self.FLEET_KINDS)

    def serving_fault(self, step: int) -> Optional[FaultSpec]:
        """The advisory serving fault the decode engine should apply
        at window ``step`` (one budget unit consumed per call)."""
        return self._draw_step_fault(step, self.SERVING_KINDS)

    # ---- CheckpointIO overrides -----------------------------------------
    def open(self, path: str, mode: str = "wb"):
        if path.endswith(".tmp") and "w" in mode:
            with self._lock:
                self.saves += 1
            f = self._take("slow_disk")
            if f is not None:
                time.sleep(f.delay_s)
        return super().open(path, mode)

    def write_array(self, f, arr) -> None:
        fault = self._take("disk_full")
        if fault is not None:
            # ENOSPC: retrying cannot help — run_elastic must abort,
            # not burn its whole budget on a hopeless loop
            raise OSError(errno.ENOSPC,
                          f"injected disk full (save #{self.saves})")
        fault = self._take("truncate")
        if fault is not None:
            # torn write: half the bytes land, then the "process" dies
            half = arr.view("uint8").ravel()[:max(1, arr.nbytes // 2)]
            super().write_array(f, half)
            f.flush()
            raise InjectedCrash(
                f"injected mid-write truncation (save #{self.saves})")
        super().write_array(f, arr)

    def fsync(self, f) -> None:
        fault = self._take("fsync_error")
        if fault is not None:
            raise OSError(errno.EIO,
                          f"injected fsync failure (save #{self.saves})")
        super().fsync(f)

    def replace(self, tmp: str, path: str) -> None:
        fault = self._take("crash_before_publish")
        if fault is not None:
            raise InjectedCrash(
                f"injected crash between write and publish "
                f"(save #{self.saves})")
        super().replace(tmp, path)
