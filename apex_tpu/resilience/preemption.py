"""Preemption-safe shutdown: convert an async preemption notice into a
save-now-then-clean-exit at the next STEP BOUNDARY.

Preemptible TPU fleets deliver an eviction notice (SIGTERM on the VM —
the shape every cloud scheduler uses) some grace period before the
plug is pulled.  Killing the process mid-step would waste the work
since the last cadence checkpoint; handling the signal inline would
tear a half-dispatched step.  :class:`PreemptionGuard` therefore only
RECORDS the notice (signal handlers must do nearly nothing), and the
training loop — ``resilience.run_elastic`` does this for you — asks
``guard.check(step)`` once per step boundary, writes a final forced
checkpoint (``CheckpointManager.save``), waits for durability, and
returns cleanly.

``preempt_at_step=N`` simulates the notice deterministically with no
signal at all — the ``--preempt-at-step`` CLI knob the examples expose
and the chaos suite drives; ``notice()`` lets a host-agent thread
(e.g. a metadata-server watcher) inject the notice programmatically.
"""

from __future__ import annotations

import signal
import threading
import warnings
from typing import Optional, Sequence


class PreemptionGuard:
    """Record SIGTERM (or a custom signal set) and surface it at step
    boundaries.

    >>> with PreemptionGuard() as guard:
    ...     for step in range(start, total):
    ...         train_one(step)
    ...         mgr.maybe_save(step, optimizer=opt)
    ...         if guard.check(step):
    ...             mgr.save(step, optimizer=opt)   # forced, final
    ...             mgr.wait()
    ...             break
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,),
                 preempt_at_step: Optional[int] = None):
        self.signals = tuple(signals)
        self.preempt_at_step = preempt_at_step
        self._flag = threading.Event()
        self._old: dict = {}
        self._installed = False

    # ---- lifecycle -------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        """Install the signal handlers (idempotent).  Only the main
        thread may install handlers; elsewhere the guard degrades to
        its programmatic notices (``notice()`` / ``preempt_at_step``)
        with a warning rather than failing."""
        if self._installed:
            return self
        try:
            for s in self.signals:
                self._old[s] = signal.signal(s, self._on_signal)
            self._installed = True
        except ValueError:
            # roll back whatever DID install: a half-armed guard that
            # uninstall() won't touch would shadow SIGTERM forever
            for s, old in self._old.items():
                signal.signal(s, old)
            self._old.clear()
            if threading.current_thread() is threading.main_thread():
                raise   # an invalid signal set is a caller bug
            warnings.warn(   # off the main thread: expected, degrade
                "PreemptionGuard: signal handlers can only be "
                "installed from the main thread; falling back to "
                "programmatic notices only")
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- notice ----------------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        # a signal handler must do (nearly) nothing: set the flag, let
        # the step boundary do the real work
        self._flag.set()

    def notice(self) -> None:
        """Programmatic preemption notice (host-agent integrations)."""
        self._flag.set()

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    def check(self, step: int) -> bool:
        """True once a notice has arrived (or ``step`` reached
        ``preempt_at_step``) — ask at every step boundary."""
        if self.preempt_at_step is not None \
                and step >= self.preempt_at_step:
            self._flag.set()
        return self.preempted
