"""Multi-host failure domains: peer liveness, deadline-armed step
boundaries, and shrink-to-healthy-mesh recovery.

The resilience stack survives its OWN preemption (PreemptionGuard) and
its OWN bad training state (Watchdog) — but a PEER host that dies or
hangs produces neither a SIGTERM nor an anomaly: the survivors just
block forever inside the next psum.  This module is the third leg of
the failure-domain triad, in three pieces:

- **Beacons** — each host publishes a monotonic ``(step, wall_time,
  incarnation)`` beacon through an out-of-band channel at step
  boundaries (:class:`KVChannel` over jax.distributed's coordination
  KV store, :class:`FileChannel` over a shared filesystem, or the
  in-process :class:`LocalChannel` the chaos suite and the examples
  drive).  The channel is OUT-OF-BAND by construction: nothing in it
  ever touches the traced program (the ``fleet.instrumented_step``
  apexverify spec pins that a monitored step still lowers with zero
  transfer/callback primitives).

- **:class:`FleetMonitor`** — classifies every peer live / slow / dead
  against configurable deadlines (wall-clock beacon age AND/OR
  lockstep step-lag), surfaces typed :class:`HostFailure` events and
  ``fleet/*`` host counters through the telemetry SinkRegistry, and
  runs the **barrier-free agreement round**: on a suspected death,
  each survivor publishes its survivor-set proposal for a fresh epoch
  and collects its peers' proposals with a bounded wait — the agreed
  set is the intersection of the responders' proposals restricted to
  the hosts that responded at all, so a hung host can neither veto nor
  stall the verdict (the same lockstep-agreement shape
  ``restore_latest`` uses, minus the collective a dead peer would
  hang).

- **Deadline-armed step boundaries** — :class:`DeadlineRunner`
  materializes a step (or a cadence save) on a worker thread with a
  join deadline, so a hung collective converts into a catchable
  :class:`StepDeadlineExceeded` instead of an eternal block;
  :class:`DeadlineCalibrator` derives the deadline from the trailing
  step-time baseline (the same median the watchdog's straggler
  detector keeps) so a config constant never has to guess the step
  time.

``run_elastic(fleet=..., step_deadline=...)`` ties them together: a
peer agreed dead (or a step deadline) triggers agreement ->
re-initialize the mesh over the survivors (``comm.shrink_mesh`` or the
caller's ``on_shrink`` hook) -> restore the last-known-good checkpoint
through the existing ``sharding=`` reshard flow -> resume, recorded as
``ElasticResult.mesh_shrinks`` under the same ``RetryPolicy`` budget.

The GROW half (elastic scale-UP) is the inverse flow:

- **Rejoin protocol** — a recovered (or brand-new) host announces
  itself on the same beacon channel with a FRESH incarnation.  The
  monitor's sticky-dead classification keys on ``(host,
  incarnation)``: the dead incarnation's beacons stay ignored forever
  (a split-brain zombie must never look alive again), while a fresh
  incarnation beaconing within the liveness deadlines becomes a
  **return candidate** (typed ``host_return`` event).  At a step
  boundary the survivors run :meth:`FleetMonitor.agree_admission` —
  the ``agree_survivors`` proposal/poll shape inverted: each survivor
  proposes its live set PLUS the candidates under a fresh epoch, and
  the agreed member set is the same responder-restricted intersection
  — then ``comm.grow_mesh`` (the inverse of ``comm.shrink_mesh``)
  re-initializes the mesh and the last-known-good checkpoint reshards
  onto the larger device set through the existing
  ``restore_good(sharding=)`` flow, with the same bit-exact-replay
  guarantee (telemetry rewind + watchdog detector reset) as shrink
  recovery.

- **:class:`FleetController`** — a load-driven fleet autoscaler: a
  host-side observer on the telemetry session that watches step-time
  / queue-depth / ``fleet/*`` signals across window flushes and emits
  typed :class:`ScaleDecision` grow/shrink/stay decisions with
  hysteresis (cooldown after ANY resize, never a resize inside an
  open watchdog incident), executed by ``run_elastic(autoscale=...)``
  through the same admission/shrink machinery.

Known scope limit (docs/resilience.md spells it out): on a real
multi-host runtime the mesh re-initialization over a changed host set
requires a runtime that supports it (``on_shrink``/``on_grow`` are the
integration points).
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
import time
from typing import (Any, Callable, Dict, List, Mapping, Optional,
                    Sequence, Set, Tuple)

from apex_tpu.resilience import faults as _faults
from apex_tpu.telemetry import hostmetrics as _hostmetrics
from apex_tpu.telemetry.incident import IncidentLog

# peer liveness states
HOST_LIVE = "live"
HOST_SLOW = "slow"
HOST_DEAD = "dead"


class FleetRecoveryFailed(RuntimeError):
    """Shrink-to-healthy-mesh recovery could not complete: the retry
    budget is exhausted, or no valid checkpoint exists to restore the
    survivors from.  The job should exit and let the external
    scheduler restart it."""


class StepDeadlineExceeded(RuntimeError):
    """A deadline-armed step (or cadence save) did not materialize in
    time — the signature of a hung collective whose peer died or hung.
    ``.step``/``.phase``/``.deadline_s`` identify the blocked work."""

    def __init__(self, message: str, step: int = -1,
                 phase: str = "step", deadline_s: float = 0.0):
        super().__init__(message)
        self.step = step
        self.phase = phase
        self.deadline_s = deadline_s


# ---------------------------------------------------------------------
# Beacon channels: the out-of-band host-to-host transport.
# ---------------------------------------------------------------------

class BeaconChannel:
    """Tiny keyed-JSON blackboard every host can write and read.

    ``put(key, value)`` overwrites; ``get_all(prefix)`` returns the
    newest value per key under ``prefix``.  Implementations must be
    crash-tolerant on the read side (a torn write is skipped, never
    raised) — the monitor treats a missing beacon exactly like a
    silent host, which is the failure being detected anyway."""

    def put(self, key: str, value: dict) -> None:
        raise NotImplementedError

    def get_all(self, prefix: str) -> Dict[str, dict]:
        raise NotImplementedError


class LocalChannel(BeaconChannel):
    """In-process channel (dict + lock): the faked-multi-host chaos
    suite and the examples' simulated peers share one instance."""

    def __init__(self):
        self._data: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def put(self, key: str, value: dict) -> None:
        with self._lock:
            self._data[key] = dict(value)

    def get_all(self, prefix: str) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._data.items()
                    if k.startswith(prefix)}


class FileChannel(BeaconChannel):
    """Shared-filesystem channel: one small JSON file per key, written
    atomically (tmp + ``os.replace``) so readers never see a torn
    beacon.  The practical transport when the checkpoint directory is
    already on NFS/FUSE and no coordination service is reachable."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @staticmethod
    def _fname(key: str) -> str:
        return key.replace("/", "__") + ".json"

    def put(self, key: str, value: dict) -> None:
        path = os.path.join(self.directory, self._fname(key))
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(value, f)
        os.replace(tmp, path)

    def get_all(self, prefix: str) -> Dict[str, dict]:
        want = self._fname(prefix)[:-len(".json")]
        out: Dict[str, dict] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not (name.startswith(want) and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, name),
                          encoding="utf-8") as f:
                    out[name[:-len(".json")].replace("__", "/")] = \
                        json.load(f)
            except (OSError, ValueError):
                continue              # torn write / vanished: skip
        return out


class KVChannel(BeaconChannel):
    """jax.distributed coordination-service channel — the production
    transport: the KV store every multi-host jax job already runs for
    its startup handshake.

    Newer jax clients support ``key_value_set(..., allow_overwrite=
    True)``; older ones only write-once, so beacons fall back to
    sequence-suffixed keys read back newest-wins (and are pruned
    best-effort with ``key_value_delete`` where available).  This
    class is necessarily exercised only on real multi-host runs — CI
    covers the protocol through :class:`LocalChannel`/
    :class:`FileChannel`, which share every code path above the
    transport."""

    def __init__(self, client=None, prefix: str = "apex_tpu/fleet/"):
        if client is None:
            from jax._src import distributed
            client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "KVChannel needs an initialized jax.distributed client "
                "(comm.initialize_distributed); use FileChannel on a "
                "shared filesystem otherwise")
        self._client = client
        self._prefix = prefix
        self._seq = 0
        self._overwrite_ok: Optional[bool] = None

    def put(self, key: str, value: dict) -> None:
        payload = json.dumps(value, sort_keys=True)
        full = self._prefix + key
        if self._overwrite_ok is not False:
            try:
                self._client.key_value_set(full, payload,
                                           allow_overwrite=True)
                self._overwrite_ok = True
                return
            except TypeError:         # old client: write-once only
                self._overwrite_ok = False
        self._seq += 1
        self._client.key_value_set(f"{full}/{self._seq:08d}", payload)

    def get_all(self, prefix: str) -> Dict[str, dict]:
        try:
            items = self._client.key_value_dir_get(self._prefix + prefix)
        except Exception:             # noqa: BLE001 — silent host, not a crash
            return {}
        newest: Dict[str, Tuple[str, str]] = {}
        for full_key, payload in items:
            key = full_key[len(self._prefix):]
            base, _, seq = key.rpartition("/")
            # only the write-once fallback appends a sequence segment,
            # always zero-padded to exactly 8 digits — a bare digit
            # tail is a HOST ID ("beacon/0", "verdict/3/1") and must
            # NOT be stripped, or every host collapses into one entry
            if base and len(seq) == 8 and seq.isdigit():
                key = base            # seq-suffixed fallback key
            prev = newest.get(key)
            if prev is None or full_key > prev[0]:
                newest[key] = (full_key, payload)
        out: Dict[str, dict] = {}
        for key, (_, payload) in newest.items():
            try:
                out[key] = json.loads(payload)
            except ValueError:
                continue
        return out


# ---------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class HostFailure:
    """One typed peer-liveness event (the fleet analogue of the
    watchdog's :class:`~.watchdog.Anomaly`)."""
    kind: str                   # "host_dead" | "host_slow"
    host: int                   # the peer concerned
    step: int                   # local step at detection
    peer_step: int              # the peer's last beacon step (-1: none)
    gap_s: float                # wall-clock beacon age at detection
    lag_steps: int              # local step - peer's beacon step
    evidence: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def record(self) -> dict:
        """The typed telemetry event (``kind: "fleet"``) emitters
        write and ``telemetry summarize`` renders as a timeline row."""
        return {"kind": "fleet", "event": self.kind,
                "host": self.host, "step": self.step,
                "peer_step": self.peer_step,
                "gap_s": round(self.gap_s, 3),
                "lag_steps": self.lag_steps,
                **({"evidence": dict(self.evidence)}
                   if self.evidence else {})}


# ---------------------------------------------------------------------
# The monitor
# ---------------------------------------------------------------------

class FleetMonitor:
    """Out-of-band host liveness: publish this host's beacon, classify
    peers, agree on survivors.

    >>> ch = fleet.FileChannel(os.path.join(ckpt_dir, "fleet"))
    >>> mon = fleet.FleetMonitor(channel=ch, telemetry=tel)
    >>> res = run_elastic(step_fn, mgr, opt, total_steps=...,
    ...                   fleet=mon, step_deadline="auto")

    Liveness criteria (either may be disabled with ``None``; a peer is
    the WORST of the two):

    - wall clock: beacon age in ``(slow_after_s, dead_after_s]`` is
      slow, beyond ``dead_after_s`` is dead — the production criterion
      (clocks need only be comparable to within the slack between the
      two deadlines, not synchronized).
    - step lag: a lockstep trainer whose peer's beacon step trails by
      ``(slow_after_steps, dead_after_steps]`` is slow, beyond is dead
      — deterministic, and exactly the signal a data-parallel psum
      cares about.

    ``beat(step)`` is THE step-boundary poll (``run_elastic`` calls it
    for you): publish, run the registered pre-beat hooks (how
    :class:`SimulatedPeers` drives faked multi-host), classify, emit
    ``fleet/*`` counters, return new :class:`HostFailure` events.
    Detection adds zero device traffic — everything is host-side, and
    a telemetry session only carries the typed events out through its
    existing window flush."""

    def __init__(self, channel: BeaconChannel,
                 host: Optional[int] = None,
                 n_hosts: Optional[int] = None,
                 slow_after_s: Optional[float] = 30.0,
                 dead_after_s: Optional[float] = 120.0,
                 slow_after_steps: Optional[int] = None,
                 dead_after_steps: Optional[int] = None,
                 agreement_timeout_s: float = 30.0,
                 incarnation: Optional[int] = None,
                 telemetry=None,
                 incidents: Optional[IncidentLog] = None,
                 clock: Callable[[], float] = time.time):
        import jax
        if (slow_after_s is None) != (dead_after_s is None):
            raise ValueError("enable both wall deadlines or neither")
        if (slow_after_steps is None) != (dead_after_steps is None):
            raise ValueError("enable both step-lag deadlines or neither")
        if slow_after_s is None and slow_after_steps is None:
            raise ValueError("at least one liveness criterion required")
        if slow_after_s is not None and not \
                (0 < slow_after_s < dead_after_s):
            raise ValueError("need 0 < slow_after_s < dead_after_s")
        if slow_after_steps is not None and not \
                (0 < slow_after_steps < dead_after_steps):
            raise ValueError(
                "need 0 < slow_after_steps < dead_after_steps")
        self.channel = channel
        self.host = jax.process_index() if host is None else int(host)
        n = jax.process_count() if n_hosts is None else int(n_hosts)
        self.hosts: List[int] = list(range(n))
        self.slow_after_s = slow_after_s
        self.dead_after_s = dead_after_s
        self.slow_after_steps = slow_after_steps
        self.dead_after_steps = dead_after_steps
        self.agreement_timeout_s = float(agreement_timeout_s)
        self.incarnation = (int(incarnation) if incarnation is not None
                            else int(time.time() * 1e3) % (1 << 31))
        self._clock = clock
        self.epoch = 0
        # the incident register: a peer death, step deadline or resize
        # opens an incident whose id threads every resulting event
        # record (telemetry/incident.py) — minted from replicated
        # facts, so every survivor stamps the SAME id without talking
        self.incidents = incidents if incidents is not None \
            else IncidentLog()
        self.timeline: List[HostFailure] = []     # full event history
        self.events: List[dict] = []              # shrink/deadline too
        self._event_records: List[dict] = []      # queued for flush
        self._status: Dict[int, str] = {h: HOST_LIVE for h in self.hosts}
        self._slow_warned: Set[int] = set()
        # rejoin bookkeeping: sticky-dead keys on (host, incarnation) —
        # the incarnation a host held when it was declared dead (or
        # evicted by an agreement round) stays dead forever; only a
        # FRESH incarnation beaconing within the liveness deadlines
        # becomes a return candidate
        self._peer_incarnation: Dict[int, int] = {}
        self._dead_incarnation: Dict[int, int] = {}
        self._candidates: Dict[int, int] = {}     # host -> fresh inc
        self._return_seen: Set[Tuple[int, int]] = set()
        self._refused_seen: Set[Tuple[int, int, str]] = set()
        self._pre_beat: List[Callable[[int], None]] = []
        self._spin_hooks: List[Callable[[int], None]] = []
        self._publish_warned = False
        self._start_wall = clock()
        self._last_step = 0
        self.telemetry = telemetry
        self._attached = False
        if telemetry is not None:
            telemetry.add_observer(self._on_flush)
            self._attached = True

    # ---- lifecycle -------------------------------------------------------
    def close(self) -> None:
        if self._attached and self.telemetry is not None:
            if self._event_records:
                # drain queued events through one last flush while the
                # observer is still attached — a shrink right before
                # shutdown must reach the JSONL
                try:
                    self.telemetry.flush()
                except Exception:        # noqa: BLE001 — teardown path
                    pass
            self.telemetry.remove_observer(self._on_flush)
            self._attached = False

    def __enter__(self) -> "FleetMonitor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _on_flush(self, records) -> List[dict]:
        """Telemetry flush observer: hand queued fleet event records
        to the emitters (the watchdog's observer discipline)."""
        out, self._event_records = self._event_records, []
        return out

    def add_beat_hook(self, fn: Callable[[int], None]) -> None:
        """Run ``fn(step)`` at the start of every ``beat`` — the seam
        :class:`SimulatedPeers` (and tests) publish peer beacons
        through before classification reads them."""
        self._pre_beat.append(fn)

    def add_spin_hook(self, fn: Callable[[int], None]) -> None:
        """Run ``fn(epoch)`` on every agreement-round poll — how
        simulated peers answer verdicts without their own thread."""
        self._spin_hooks.append(fn)

    # ---- beacons ---------------------------------------------------------
    def publish(self, step: int) -> None:
        """Publish this host's ``(step, wall_time, incarnation)``
        beacon (monotonic per incarnation).  A transient channel
        failure must never kill training: it degrades to a missed
        beacon (this host looks slow to its peers — which is true)."""
        self._last_step = int(step)
        try:
            self.channel.put(f"beacon/{self.host}", {
                "host": self.host, "step": int(step),
                "wall_time": self._clock(),
                "incarnation": self.incarnation,
                "epoch": self.epoch})
        except OSError as e:
            if not self._publish_warned:
                self._publish_warned = True
                import warnings
                warnings.warn(
                    f"fleet: beacon publish failed "
                    f"({type(e).__name__}: {e}); continuing — peers "
                    "will see this host as slow until the channel "
                    "recovers")

    def peers(self) -> List[int]:
        return [h for h in self.hosts if h != self.host]

    def _read_beacons(self) -> Dict[int, dict]:
        """Every non-self beacon on the channel — member peers AND
        non-members (an evicted host announcing a fresh incarnation,
        or a brand-new host joining)."""
        out: Dict[int, dict] = {}
        try:
            beacons = self.channel.get_all("beacon/")
        except OSError:
            return out            # unreadable channel = silent peers
        for key, rec in beacons.items():
            try:
                h = int(key.rsplit("/", 1)[-1])
            except ValueError:
                continue
            if h != self.host:
                out[h] = rec
        return out

    def _classify(self, step: int, beacon: Optional[dict],
                  now: float) -> Tuple[str, float, int]:
        """-> (status, gap_s, lag_steps) for one peer."""
        if beacon is None:
            # no beacon yet: age from monitor start (startup grace),
            # lag from step 0
            gap_s = now - self._start_wall
            peer_step = -1
        else:
            gap_s = max(0.0, now - float(beacon.get("wall_time", now)))
            peer_step = int(beacon.get("step", -1))
        lag = int(step) - max(peer_step, 0)
        status = HOST_LIVE
        if self.slow_after_s is not None:
            if gap_s > self.dead_after_s:
                status = HOST_DEAD
            elif gap_s > self.slow_after_s:
                status = HOST_SLOW
        if self.slow_after_steps is not None and status != HOST_DEAD:
            if lag > self.dead_after_steps:
                status = HOST_DEAD
            elif lag > self.slow_after_steps and status == HOST_LIVE:
                status = HOST_SLOW
        return status, gap_s, lag

    def _consider_return(self, h: int, beacon: Optional[dict],
                         step: int, now: float,
                         found: List[HostFailure]) -> None:
        """Rejoin candidacy for a sticky-dead member or a non-member
        host.  Sticky-dead keys on ``(host, incarnation)``: the dead
        incarnation's beacons stay ignored (split-brain zombie), a
        FRESH incarnation beaconing within the liveness deadlines is a
        return candidate — surfaced once per incarnation as a typed
        ``host_return`` event and re-validated every poll (a candidate
        that stops beaconing — a flapping host — drops out again)."""
        if beacon is None:
            self._candidates.pop(h, None)
            return
        try:
            inc = int(beacon.get("incarnation", -1))
        except (TypeError, ValueError):
            return
        if inc <= self._dead_incarnation.get(h, -1):
            self._candidates.pop(h, None)     # stale incarnation: zombie
            return
        status, gap_s, lag = self._classify(step, beacon, now)
        if status != HOST_LIVE:
            self._candidates.pop(h, None)     # flapped away again
            return
        self._candidates[h] = inc
        if (h, inc) not in self._return_seen:
            self._return_seen.add((h, inc))
            found.append(HostFailure(
                kind="host_return", host=h, step=int(step),
                peer_step=int(beacon.get("step", -1)), gap_s=gap_s,
                lag_steps=lag, evidence={"incarnation": inc}))

    def poll(self, step: int) -> List[HostFailure]:
        """Classify every peer against the deadlines; return NEW
        failure events (dead fires once and is sticky per
        ``(host, incarnation)``; slow fires once per episode, re-armed
        by recovery; a dead or evicted host beaconing a FRESH
        incarnation fires ``host_return`` once per incarnation).
        Emits the ``fleet/*`` counters."""
        now = self._clock()
        beacons = self._read_beacons()
        found: List[HostFailure] = []
        worst_gap, worst_lag = 0.0, 0
        for h in self.peers():
            b = beacons.get(h)
            if b is not None:
                try:
                    self._peer_incarnation[h] = int(
                        b.get("incarnation", -1))
                except (TypeError, ValueError):
                    pass
            if self._status.get(h) == HOST_DEAD:
                # sticky for THIS incarnation — but a fresh incarnation
                # beaconing live is a rejoin candidate, not a zombie
                self._consider_return(h, b, step, now, found)
                continue
            status, gap_s, lag = self._classify(step, b, now)
            worst_gap = max(worst_gap, gap_s)
            worst_lag = max(worst_lag, lag)
            prev = self._status.get(h, HOST_LIVE)
            self._status[h] = status
            peer_step = int(b.get("step", -1)) if b else -1
            if status == HOST_DEAD:
                # the incarnation dying here is what stays dead; a
                # return must present a NEWER one
                self._dead_incarnation[h] = \
                    self._peer_incarnation.get(h, -1)
                found.append(HostFailure(
                    kind="host_dead", host=h, step=int(step),
                    peer_step=peer_step, gap_s=gap_s, lag_steps=lag))
            elif status == HOST_SLOW and h not in self._slow_warned:
                self._slow_warned.add(h)
                found.append(HostFailure(
                    kind="host_slow", host=h, step=int(step),
                    peer_step=peer_step, gap_s=gap_s, lag_steps=lag))
            elif status == HOST_LIVE and prev == HOST_SLOW:
                self._slow_warned.discard(h)      # episode over: re-arm
        # non-member hosts (evicted after a shrink, or brand-new):
        # their fresh-incarnation beacons are admission candidates
        for h, b in sorted(beacons.items()):
            if h in self.hosts:
                continue
            self._consider_return(h, b, step, now, found)
        for h in list(self._candidates):
            if h not in beacons:      # beacon gone entirely: drop
                self._candidates.pop(h, None)
        statuses = [self._status[h] for h in self.peers()]
        _hostmetrics.emit("fleet/hosts_live",
                          1 + statuses.count(HOST_LIVE))
        _hostmetrics.emit("fleet/hosts_slow", statuses.count(HOST_SLOW))
        _hostmetrics.emit("fleet/hosts_dead", statuses.count(HOST_DEAD))
        _hostmetrics.emit("fleet/beacon_gap_ms", worst_gap * 1e3)
        _hostmetrics.emit("fleet/beacon_lag_steps", worst_lag)
        for f in found:
            # a peer death opens an incident (keyed on the DEAD peer's
            # identity — the same on every survivor); a fresh
            # incarnation's return opens the grow chain's.  Follow-on
            # events (agreement, shrink/grow, replay) ride the id
            if f.kind == "host_dead":
                self.incidents.open(
                    "host_dead", host=f.host,
                    incarnation=self._dead_incarnation.get(f.host, -1),
                    epoch=self.epoch)
            elif f.kind == "host_return":
                self.incidents.open(
                    "host_return", host=f.host,
                    incarnation=dict(f.evidence).get("incarnation"),
                    epoch=self.epoch)
            self.timeline.append(f)
            rec = f.record()
            rec["t"] = round(self._clock(), 3)
            self.incidents.tag(rec)
            self._event_records.append(rec)
        return found

    def beat(self, step: int) -> List[HostFailure]:
        """THE step-boundary poll: publish + pre-beat hooks +
        classify.  ``run_elastic(fleet=...)`` calls it once per
        completed step."""
        self.publish(step)
        for hook in list(self._pre_beat):
            hook(step)
        return self.poll(step)

    # ---- views -----------------------------------------------------------
    def status(self, host: int) -> str:
        return HOST_LIVE if host == self.host \
            else self._status.get(host, HOST_LIVE)

    def live_hosts(self) -> List[int]:
        """Hosts not declared dead (self included; slow counts as
        live — a slow peer gets warned about, not evicted)."""
        return [h for h in self.hosts
                if self.status(h) != HOST_DEAD]

    def dead_hosts(self) -> List[int]:
        return [h for h in self.hosts if self.status(h) == HOST_DEAD]

    def peer_incarnation(self, host: int) -> int:
        """A peer's last observed beacon incarnation (0 before any
        beacon) — the public key for exactly-once-per-life claims
        (e.g. ``serving.ReplicaSet.claim_dead_queue``)."""
        return int(self._peer_incarnation.get(host, 0))

    # ---- agreement -------------------------------------------------------
    def _agreement_round(self, epoch: int, proposal: Sequence[int],
                         timeout_s: Optional[float]) -> Set[int]:
        """Publish this host's proposal for ``epoch``, poll peers'
        proposals with a bounded wait, and return the agreed set: the
        intersection of the responders' proposals restricted to the
        responders themselves — so every responding host computes the
        SAME set from the same published verdicts, and a host that
        fails to publish within the deadline can neither veto nor
        stall the round the way it would stall an allgather."""
        self.channel.put(f"verdict/{epoch}/{self.host}", {
            "host": self.host, "epoch": epoch,
            "survivors": list(proposal),
            "incarnation": self.incarnation})
        deadline = self._clock() + (timeout_s if timeout_s is not None
                                    else self.agreement_timeout_s)
        spins = 0
        while True:
            spins += 1
            for hook in list(self._spin_hooks):
                hook(epoch)
            verdicts = self.channel.get_all(f"verdict/{epoch}/")
            responders: Dict[int, List[int]] = {}
            for rec in verdicts.values():
                try:
                    responders[int(rec["host"])] = \
                        [int(s) for s in rec["survivors"]]
                except (KeyError, TypeError, ValueError):
                    continue
            if set(proposal) <= set(responders):
                break                 # everyone we expected answered
            if self._clock() >= deadline or spins > 1_000_000:
                break                 # non-responders are dead
            time.sleep(0.001)
        agreed = set(responders)
        for survivors in responders.values():
            agreed &= set(survivors)
        return agreed

    def agree_survivors(self, step: int,
                        timeout_s: Optional[float] = None,
                        exclude: Sequence[int] = ()
                        ) -> Tuple[int, List[int]]:
        """Barrier-free survivor agreement for a fresh epoch.

        Every survivor publishes its proposal (its live set) under the
        epoch and polls for its peers' proposals; a host that fails to
        publish within the deadline is treated as dead — it cannot
        stall the round the way it would stall an allgather.  The
        agreed set is the intersection of the responders' proposals
        restricted to the responders themselves, so every responding
        host computes the SAME set from the same published verdicts
        (the ``restore_latest`` lockstep-agreement shape, minus the
        collective).  A host the agreed set excludes — possible when
        a peer's proposal ruled it dead — raises
        :class:`FleetRecoveryFailed` and self-evicts instead of
        rebuilding a divergent (split-brain) mesh.  Updates the
        monitor's host set to the agreed survivors and bumps
        ``epoch``.

        ``exclude``: hosts left out of THIS host's proposal — the
        autoscaler's voluntary release (the intersection rule then
        drops them from the agreed set, and an excluded host that
        polls the round self-evicts exactly like a ruled-dead one)."""
        epoch = self.epoch + 1
        proposal = sorted(set(self.live_hosts()) - set(exclude))
        agreed = self._agreement_round(epoch, proposal, timeout_s)
        survivors = sorted(agreed)
        if self.host not in agreed:
            # a responder's proposal excluded US: by the same rule
            # every other survivor applies, this host is out of the
            # fleet — self-evict rather than rebuild a divergent
            # (split-brain) mesh the real survivors don't share
            raise FleetRecoveryFailed(
                f"host {self.host} is excluded from the agreed "
                f"survivor set {survivors} (epoch {epoch}) — the "
                "fleet considers this host failed; exiting for the "
                "external scheduler to restart it")
        self.epoch = epoch
        self._set_members(survivors)
        _hostmetrics.emit("fleet/epoch", epoch)
        return epoch, survivors

    def return_candidates(self) -> Dict[int, int]:
        """Hosts currently announcing a fresh incarnation within the
        liveness deadlines (host -> incarnation) — what
        :meth:`agree_admission` admits.  Re-validated every poll: a
        candidate that stops beaconing (a flapping host) drops out
        before it is ever admitted."""
        return dict(self._candidates)

    def agree_admission(self, step: int,
                        joiners: Mapping[int, int],
                        timeout_s: Optional[float] = None
                        ) -> Tuple[int, List[int]]:
        """Barrier-free ADMISSION agreement — :meth:`agree_survivors`
        inverted: every member proposes its live set PLUS the joiners
        (``host -> fresh incarnation``, normally
        :meth:`return_candidates`) under a fresh epoch; the agreed
        member set is the same responder-restricted intersection.  A
        joiner is admitted only when it answers the round itself AND
        every responding member proposed it — a member that still
        considers it dead (or a joiner that went silent again) drops
        it from the intersection and the round degrades to a no-op.
        Updates the monitor's host set to the agreed members (admitted
        joiners enter LIVE under the new epoch) and bumps ``epoch``."""
        joiners = {int(h): int(inc) for h, inc in dict(joiners).items()}
        epoch = self.epoch + 1
        proposal = sorted(set(self.live_hosts()) | set(joiners))
        agreed = self._agreement_round(epoch, proposal, timeout_s)
        members = sorted(agreed)
        if self.host not in agreed:
            raise FleetRecoveryFailed(
                f"host {self.host} is excluded from the agreed "
                f"member set {members} (epoch {epoch}) — the fleet "
                "considers this host failed; exiting for the external "
                "scheduler to restart it")
        self.epoch = epoch
        for h in set(members) & set(joiners):
            # this incarnation is IN: only a still-newer one may
            # re-candidate after a future death
            self._peer_incarnation[h] = joiners[h]
            self._dead_incarnation[h] = joiners[h] - 1
            self._candidates.pop(h, None)
        self._set_members(members)
        _hostmetrics.emit("fleet/epoch", epoch)
        return epoch, members

    def _set_members(self, members: Sequence[int]) -> None:
        """Adopt an agreed member set (shrink or grow).  Hosts leaving
        the set keep their current incarnation recorded as dead, so a
        released (not crashed) host's continuing beacons are ignored
        as stale until it restarts with a fresh incarnation."""
        new = sorted(set(int(h) for h in members) | {self.host})
        for h in self.hosts:
            if h not in new and h != self.host:
                self._dead_incarnation[h] = max(
                    self._dead_incarnation.get(h, -1),
                    self._peer_incarnation.get(h, -1))
        self.hosts = new
        self._status = {h: HOST_LIVE for h in self.hosts}
        self._slow_warned.clear()

    # ---- action events (recorded by run_elastic) -------------------------
    def _event(self, rec: dict) -> None:
        rec.setdefault("t", round(self._clock(), 3))
        self.incidents.tag(rec)
        self.events.append(rec)
        self._event_records.append(rec)

    def note_shrink(self, step: int, epoch: int,
                    survivors: Sequence[int], dead: Sequence[int],
                    restored_step: Optional[int],
                    reason: str = "failure") -> None:
        if self.incidents.current is None:
            # a resize is an incident opener in its own right (the
            # autoscaler's voluntary release has no preceding death)
            self.incidents.open(
                "shrink", host=(int(dead[0]) if dead else None),
                epoch=epoch)
        _hostmetrics.emit("fleet/mesh_shrinks", 1)
        self._event({
            "kind": "fleet", "event": "shrink", "step": int(step),
            "epoch": int(epoch), "survivors": list(survivors),
            "dead": list(dead), "reason": reason,
            "to_step": (int(restored_step)
                        if restored_step is not None else None)})

    def note_grow(self, step: int, epoch: int,
                  members: Sequence[int], admitted: Sequence[int],
                  restored_step: Optional[int]) -> None:
        if self.incidents.current is None:
            self.incidents.open(
                "grow", host=(int(admitted[0]) if admitted else None),
                epoch=epoch)
        _hostmetrics.emit("fleet/mesh_grows", 1)
        self._event({
            "kind": "fleet", "event": "grow", "step": int(step),
            "epoch": int(epoch), "members": list(members),
            "admitted": list(admitted),
            "to_step": (int(restored_step)
                        if restored_step is not None else None)})

    def note_admission_refused(self, step: int,
                               candidates: Mapping[int, int],
                               reason: str) -> None:
        """Record a refused admission (open watchdog incident, resize
        cooldown, or a round the members did not agree) — once per
        (host, incarnation, reason), so a candidate polling every
        boundary does not flood the timeline."""
        for h, inc in sorted(dict(candidates).items()):
            key = (int(h), int(inc), reason)
            if key in self._refused_seen:
                continue
            self._refused_seen.add(key)
            self._event({
                "kind": "fleet", "event": "admission_refused",
                "step": int(step), "host": int(h),
                "incarnation": int(inc), "reason": reason})

    def note_deadline(self, exc: "StepDeadlineExceeded") -> None:
        # subject-less opener: every survivor hits the same hung
        # collective's deadline at the same step under the same epoch
        self.incidents.open("deadline", epoch=self.epoch)
        self._event({
            "kind": "fleet", "event": "deadline_exceeded",
            "step": int(exc.step), "phase": exc.phase,
            "deadline_s": round(exc.deadline_s, 3)})

    def note_replay_complete(self, step: int,
                             incident_id: Optional[str] = None) -> None:
        """The replay after a shrink/grow restore caught back up to
        the failure step: the incident's causal chain is over.  Emits
        the ``replay_complete`` event carrying the incident id and
        closes it in the register."""
        iid = incident_id if incident_id is not None \
            else self.incidents.current
        rec = {"kind": "fleet", "event": "replay_complete",
               "step": int(step)}
        if iid is not None:
            rec["incident_id"] = iid
        self._event(rec)
        self.incidents.close(iid)


# ---------------------------------------------------------------------
# Simulated peers: faked multi-host for the chaos suite + examples.
# ---------------------------------------------------------------------

class SimulatedPeers:
    """Drive the OTHER hosts of a faked fleet in-process.

    Publishes a live beacon per simulated peer on every monitor beat
    and answers agreement rounds on their behalf — so the full
    beacon -> classify -> agree -> shrink/grow protocol runs end to
    end in one process (the examples' ``--fleet`` mode and the chaos
    matrix).  Consumes the scheduled fleet faults from
    :mod:`~apex_tpu.resilience.faults`: a killed peer
    (``peer_death``/``peer_hang``) stops beaconing (its last beacon
    ages out / lags behind exactly like a real dead host's), a
    slow-networked peer publishes stale beacons for the fault's
    budget, a returning peer (``host_return`` /
    ``grow_during_incident``) resumes beaconing under a FRESH
    incarnation, and a ``flapping_host`` returns then dies again when
    the fault's ``n_steps`` budget expires.

    >>> sim = SimulatedPeers(channel, hosts=[1, 2])
    >>> sim.attach(monitor)      # beat + agreement hooks
    """

    def __init__(self, channel: BeaconChannel, hosts: Sequence[int],
                 clock: Callable[[], float] = time.time,
                 incarnation: int = 1):
        self.channel = channel
        self.hosts = [int(h) for h in hosts]
        self.killed: Set[int] = set()
        self._lag: Dict[int, Tuple[int, float]] = {}   # host -> (steps, s)
        self._clock = clock
        self.incarnation = incarnation
        self._inc: Dict[int, int] = {}    # per-host current incarnation
        self._flap_target: Optional[int] = None

    def attach(self, monitor: FleetMonitor) -> "SimulatedPeers":
        monitor.add_beat_hook(self.beat)
        monitor.add_spin_hook(self.answer_agreement)
        return self

    def kill(self, host: int) -> None:
        """The peer stops beaconing from now on (host crashed/hung)."""
        self.killed.add(int(host))

    def revive(self, host: int) -> None:
        """The peer returns: resumes beaconing under a FRESH
        incarnation (a restarted process, not the dead one's zombie —
        idempotent while already alive)."""
        h = int(host)
        if h in self.killed:
            self.killed.discard(h)
            self._inc[h] = self._inc.get(h, self.incarnation) + 1

    def incarnation_of(self, host: int) -> int:
        return self._inc.get(int(host), self.incarnation)

    def _default_target(self) -> int:
        alive = [h for h in self.hosts if h not in self.killed]
        return alive[-1] if alive else self.hosts[-1]

    def _default_return_target(self) -> int:
        dead = sorted(self.killed)
        return dead[-1] if dead else self.hosts[-1]

    def beat(self, step: int) -> None:
        """Publish one beacon per live simulated peer; apply any
        scheduled fleet fault first."""
        f = _faults.fleet_fault(step)
        if f is not None:
            if f.kind in ("host_return", "flapping_host",
                          "grow_during_incident"):
                target = f.target if f.target is not None \
                    else self._default_return_target()
                self.revive(target)
                if f.kind == "flapping_host":
                    # dies again when the fault's budget expires
                    self._flap_target = target
            else:
                target = f.target if f.target is not None \
                    else self._default_target()
                if f.kind in ("peer_death", "peer_hang"):
                    self.kill(target)
                elif f.kind == "slow_network":
                    self._lag[target] = (int(f.lag_steps),
                                         float(f.delay_s))
        now = self._clock()
        for h in self.hosts:
            if h in self.killed:
                continue
            lag_steps, lag_s = self._lag.get(h, (0, 0.0))
            self.channel.put(f"beacon/{h}", {
                "host": h, "step": int(step) - lag_steps,
                "wall_time": now - lag_s,
                "incarnation": self.incarnation_of(h), "epoch": 0})
        # a slow-network lag (and a flapping host's second life)
        # expires with the fault budget: faults hand out one unit per
        # beat, so apply the expiry when no longer drawn
        if f is None:
            self._lag.clear()
            if self._flap_target is not None:
                self.kill(self._flap_target)
                self._flap_target = None

    def answer_agreement(self, epoch: int) -> None:
        """Publish each live peer's verdict for ``epoch``: its own
        survivor view (everything it can see beaconing = everything
        not killed, plus the real hosts).  A revived peer answers too
        — its response is what lets :meth:`FleetMonitor.
        agree_admission` admit it."""
        verdicts = self.channel.get_all(f"verdict/{epoch}/")
        real_hosts = sorted(
            int(rec["host"]) for rec in verdicts.values()
            if "host" in rec and int(rec["host"]) not in self.hosts)
        view = sorted(set(real_hosts)
                      | {h for h in self.hosts if h not in self.killed})
        for h in self.hosts:
            if h in self.killed:
                continue              # a dead peer answers nothing
            key = f"verdict/{epoch}/{h}"
            if key in verdicts:
                continue
            self.channel.put(key, {
                "host": h, "epoch": int(epoch), "step": -1,
                "survivors": view,
                "incarnation": self.incarnation_of(h)})


# ---------------------------------------------------------------------
# Load-driven fleet autoscaling
# ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One typed autoscaler decision (the fleet analogue of the
    watchdog's :class:`~.watchdog.Verdict`)."""
    action: str                 # "grow" | "shrink" | "stay"
    step: int                   # boundary the decision was made at
    reason: str                 # signal name or hold reason
    signal: Optional[float] = None   # windowed value it keyed on

    def record(self) -> dict:
        rec = {"kind": "fleet", "event": "autoscale",
               "action": self.action, "step": int(self.step),
               "reason": self.reason}
        if self.signal is not None:
            rec["signal"] = round(float(self.signal), 6)
        return rec


class FleetController:
    """Load-driven fleet autoscaler: watch the run's load signals
    host-side, emit typed grow/shrink/stay :class:`ScaleDecision`\\ s
    with hysteresis.  Decisions are EMITTED here and EXECUTED by
    ``run_elastic(autoscale=...)`` through the same admission/shrink
    machinery the failure path uses — the controller never touches the
    mesh itself.

    Signals (configure at least one high watermark):

    - **step time** — ``note_step(step, duration_s)`` samples from the
      supervisor's step-boundary clock (the same wall times the
      watchdog's straggler detector sees); windowed median above
      ``step_time_high_s`` wants capacity, below ``step_time_low_s``
      wants release.
    - **queue depth** — a ring metric named by ``queue_metric`` (e.g.
      a data-loader backlog the trainer records per step), read from
      the telemetry session's window flushes when attached
      (``telemetry=``); same high/low watermark shape.  An EXTERNAL
      load signal — a serving admission queue, a scheduler backlog,
      anything outside the training loop — rides the same window via
      ``signal_source``: a zero-arg callable polled once per decision
      (return None for "no sample"), so the live-telemetry registry
      (``telemetry.export``) or any host-side producer can feed the
      autoscaler without touching the ring schema.
    - **fleet health** — the ``fleet/hosts_slow`` counter riding the
      hostmetrics sinks: a degraded fleet holds every resize (growing
      into — or shrinking under — an infrastructure wobble just
      churns the mesh).

    Hysteresis: a signal must hold out-of-band for ``patience``
    consecutive decisions before a resize fires; after ANY resize
    (``note_resize`` — run_elastic calls it for failure shrinks too)
    decisions stay for ``cooldown_steps``; and no resize is ever
    decided inside an open watchdog incident (``incident=`` passed by
    run_elastic, or a standalone ``incident_source`` callable).
    grow/shrink decisions are recorded as ``kind:"fleet"`` /
    ``event:"autoscale"`` timeline events through the attached
    session's flush."""

    def __init__(self, telemetry=None,
                 step_time_high_s: Optional[float] = None,
                 step_time_low_s: Optional[float] = None,
                 queue_metric: Optional[str] = None,
                 queue_high: Optional[float] = None,
                 queue_low: Optional[float] = None,
                 signal_source: Optional[
                     Callable[[], Optional[float]]] = None,
                 window: int = 32, patience: int = 2,
                 cooldown_steps: int = 100,
                 min_hosts: int = 1,
                 max_hosts: Optional[int] = None,
                 incident_source: Optional[Callable[[], bool]] = None):
        if step_time_high_s is None and queue_high is None:
            raise ValueError(
                "configure at least one grow signal: step_time_high_s "
                "or queue_high (with queue_metric or signal_source)")
        if queue_metric is None and signal_source is None \
                and queue_high is not None:
            raise ValueError(
                "queue_high needs queue_metric or signal_source")
        for lo, hi, what in ((step_time_low_s, step_time_high_s,
                              "step_time"),
                             (queue_low, queue_high, "queue")):
            if lo is not None and (hi is None or not lo < hi):
                raise ValueError(f"need {what} low < high watermarks")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        if cooldown_steps < 0:
            raise ValueError("cooldown_steps must be >= 0")
        self.step_time_high_s = step_time_high_s
        self.step_time_low_s = step_time_low_s
        self.queue_metric = queue_metric
        self.queue_high = queue_high
        self.queue_low = queue_low
        self.signal_source = signal_source
        self.patience = int(patience)
        self.cooldown_steps = int(cooldown_steps)
        self.min_hosts = int(min_hosts)
        self.max_hosts = max_hosts
        self.incident_source = incident_source
        import collections
        self._times = collections.deque(maxlen=int(window))
        self._queue = collections.deque(maxlen=int(window))
        # written by the hostmetrics sink (which fires on whatever
        # thread emits fleet/hosts_slow — the monitor beat, a
        # checkpoint worker) and read by decide() on the training
        # thread: every touch takes the lock (APX1001)
        self._beat_lock = threading.Lock()
        self._hosts_slow = 0.0
        self._grow_streak = 0
        self._shrink_streak = 0
        self._last_resize: Optional[int] = None
        # bounded: one decision lands per step boundary for the whole
        # run (overwhelmingly "stay") — an unbounded list would be a
        # slow host-RAM leak on multi-million-step autoscaled runs
        self.decisions = collections.deque(maxlen=512)
        self._event_records: List[dict] = []
        self.telemetry = telemetry
        self._attached = False
        _hostmetrics.add_sink(self._on_counter)
        if telemetry is not None:
            telemetry.add_observer(self._on_flush)
            self._attached = True

    # ---- lifecycle -------------------------------------------------------
    def close(self) -> None:
        _hostmetrics.remove_sink(self._on_counter)
        if self._attached and self.telemetry is not None:
            if self._event_records:
                try:
                    self.telemetry.flush()
                except Exception:    # noqa: BLE001 — teardown path
                    pass
            self.telemetry.remove_observer(self._on_flush)
            self._attached = False

    def __enter__(self) -> "FleetController":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _on_counter(self, name: str, value: float) -> None:
        if name == "fleet/hosts_slow":
            with self._beat_lock:
                self._hosts_slow = float(value)

    def _on_flush(self, records) -> List[dict]:
        self.observe(records)
        out, self._event_records = self._event_records, []
        return out

    # ---- signal intake ---------------------------------------------------
    def observe(self, records) -> None:
        """Window-flush intake: pull the queue-depth metric out of the
        decoded step records (the telemetry observer calls this; unit
        tests feed synthetic streams directly)."""
        if self.queue_metric is None:
            return
        for r in records:
            if r.get("kind", "step") != "step":
                continue
            v = r.get(self.queue_metric)
            if v is not None:
                try:
                    self._queue.append(float(v))
                except (TypeError, ValueError):
                    continue

    def note_step(self, step: int, duration_s: float) -> None:
        """One completed step's wall duration (run_elastic's
        step-boundary clock)."""
        self._times.append(float(duration_s))

    def note_resize(self, step: int) -> None:
        """ANY mesh resize happened (grow, voluntary shrink, or a
        failure shrink): arm the cooldown and drop the streaks — the
        new mesh gets a fresh observation window."""
        self._last_resize = int(step)
        self._grow_streak = 0
        self._shrink_streak = 0

    @staticmethod
    def _median(values) -> Optional[float]:
        vals = sorted(values)
        return vals[len(vals) // 2] if vals else None

    # ---- the decision ----------------------------------------------------
    def _decision(self, action: str, step: int, reason: str,
                  signal: Optional[float]) -> ScaleDecision:
        d = ScaleDecision(action, int(step), reason, signal)
        self.decisions.append(d)
        if action != "stay":
            self._event_records.append(d.record())
        return d

    def decide(self, step: int, n_hosts: int = 1, candidates: int = 0,
               incident: Optional[bool] = None) -> ScaleDecision:
        """The step-boundary decision.  ``n_hosts``: current member
        count; ``candidates``: hosts currently announcing a fresh
        incarnation (a grow can only be EXECUTED with one, so without
        any the decision stays); ``incident``: whether the watchdog
        has an open incident (None consults ``incident_source``)."""
        step = int(step)
        if self.signal_source is not None:
            # external load sample (serving queue depth etc.): one
            # poll per decision, riding the same hysteresis window as
            # the ring metric
            try:
                v = self.signal_source()
            except Exception:     # noqa: BLE001 — a broken gauge must
                v = None          # not kill the supervisor loop
            if v is not None:
                try:
                    self._queue.append(float(v))
                except (TypeError, ValueError):
                    pass
        if incident is None:
            incident = bool(self.incident_source()) \
                if self.incident_source is not None else False
        tmed = self._median(self._times)
        qmed = self._median(self._queue)
        if incident:
            self._grow_streak = self._shrink_streak = 0
            return self._decision("stay", step, "open_incident", None)
        with self._beat_lock:
            hosts_slow = self._hosts_slow
        if hosts_slow > 0:
            self._grow_streak = self._shrink_streak = 0
            return self._decision("stay", step, "fleet_degraded",
                                  hosts_slow)
        if self._last_resize is not None and \
                step - self._last_resize < self.cooldown_steps:
            self._grow_streak = self._shrink_streak = 0
            return self._decision("stay", step, "cooldown", None)
        grow_sig = shrink_sig = None
        if self.queue_high is not None and qmed is not None \
                and qmed > self.queue_high:
            grow_sig = ("queue_depth", qmed)
        elif self.step_time_high_s is not None and tmed is not None \
                and tmed > self.step_time_high_s:
            grow_sig = ("step_time", tmed)
        elif self.queue_low is not None and qmed is not None \
                and qmed < self.queue_low:
            shrink_sig = ("queue_depth", qmed)
        elif self.step_time_low_s is not None and tmed is not None \
                and tmed < self.step_time_low_s:
            shrink_sig = ("step_time", tmed)
        if grow_sig is not None:
            self._grow_streak += 1
            self._shrink_streak = 0
            if self._grow_streak < self.patience:
                return self._decision("stay", step, "patience",
                                      grow_sig[1])
            if candidates <= 0:
                # capacity is wanted but nobody is announcing: surface
                # the demand on the timeline once per episode (an
                # external scheduler can act on it), execution waits
                d = self._decision("stay", step,
                                   "grow_wanted_no_candidates",
                                   grow_sig[1])
                if self._grow_streak == self.patience:
                    self._event_records.append(d.record())
                return d
            if self.max_hosts is not None and n_hosts >= self.max_hosts:
                return self._decision("stay", step, "at_max_hosts",
                                      grow_sig[1])
            return self._decision("grow", step, grow_sig[0],
                                  grow_sig[1])
        if shrink_sig is not None:
            self._shrink_streak += 1
            self._grow_streak = 0
            if self._shrink_streak < self.patience:
                return self._decision("stay", step, "patience",
                                      shrink_sig[1])
            if n_hosts <= self.min_hosts:
                return self._decision("stay", step, "at_min_hosts",
                                      shrink_sig[1])
            return self._decision("shrink", step, shrink_sig[0],
                                  shrink_sig[1])
        self._grow_streak = self._shrink_streak = 0
        return self._decision("stay", step, "in_band",
                              qmed if qmed is not None else tmed)


# ---------------------------------------------------------------------
# Deadline-armed execution
# ---------------------------------------------------------------------

class DeadlineCalibrator:
    """Derive the step deadline from the trailing step-time baseline.

    ``deadline_s() = clamp(factor * median(recent durations), min_s,
    max_s)`` — the same trailing-median shape the watchdog's
    :class:`~.watchdog.StepTimeDetector` keeps, so the deadline tracks
    warmup/compile drift instead of guessing a constant.  Before
    ``min_history`` samples exist, ``history_source`` (a zero-arg
    callable returning recent durations — ``run_elastic`` passes the
    watchdog's ``recent_step_times`` so the baseline the watchdog
    already tracks calibrates the deadline too) is consulted; with
    neither, ``default_s`` applies (generous: the first steps include
    compilation)."""

    def __init__(self, factor: float = 10.0, min_s: float = 1.0,
                 max_s: float = 600.0, default_s: float = 120.0,
                 min_history: int = 5, history: int = 64,
                 history_source: Optional[
                     Callable[[], Sequence[float]]] = None):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        self.factor = float(factor)
        self.min_s = float(min_s)
        self.max_s = float(max_s)
        self.default_s = float(default_s)
        self.min_history = int(min_history)
        self.history_source = history_source
        import collections
        self._hist = collections.deque(maxlen=int(history))

    def note(self, duration_s: float) -> None:
        """Record one COMPLETED step's duration (a timed-out step is
        not a baseline sample)."""
        self._hist.append(float(duration_s))

    def deadline_s(self) -> float:
        samples = list(self._hist)
        if len(samples) < self.min_history \
                and self.history_source is not None:
            samples = list(self.history_source())
        if len(samples) < self.min_history:
            return self.default_s
        med = sorted(samples)[len(samples) // 2]
        return min(max(self.factor * med, self.min_s), self.max_s)


class DeadlineRunner:
    """Run a thunk on a persistent worker thread with a join deadline.

    A hung collective blocks its thread forever; Python cannot
    interrupt it.  What it CAN do is stop WAITING: ``run`` hands the
    thunk to the worker and waits at most ``deadline_s`` for the
    result — on expiry it abandons the (daemon) worker, respawns a
    fresh one for the next call, and raises
    :class:`StepDeadlineExceeded`.  Results from an abandoned worker
    go to its abandoned queue and can never be mistaken for a live
    call's (queues are replaced on every timeout).  Exceptions from
    the thunk re-raise in the caller."""

    def __init__(self):
        self._inq: Optional[queue.Queue] = None
        self._outq: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        # bumped on every timeout: a thunk captured before submission
        # can re-check it after a blocking prologue and skip its
        # side-effecting body once abandoned (run_elastic's step thunk
        # does), so an abandoned worker can never mutate training
        # state concurrently with the recovery that replaced it
        self.generation = 0

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._inq, self._outq = queue.Queue(), queue.Queue()

        def loop(inq: queue.Queue, outq: queue.Queue) -> None:
            while True:
                item = inq.get()
                if item is None:
                    return
                fn = item
                try:
                    outq.put(("ok", fn()))
                except BaseException as e:    # noqa: BLE001 — re-raised
                    outq.put(("err", e))

        self._worker = threading.Thread(
            target=loop, args=(self._inq, self._outq),
            name="apex-tpu-deadline-runner", daemon=True)
        self._worker.start()

    def run(self, fn: Callable[[], Any], deadline_s: float,
            step: int = -1, phase: str = "step") -> Any:
        self._ensure_worker()
        self._inq.put(fn)
        try:
            kind, payload = self._outq.get(timeout=max(deadline_s,
                                                       1e-3))
        except queue.Empty:
            # abandon the stuck worker: its queues are dropped with it,
            # so a late result can never satisfy a FUTURE call
            self.generation += 1
            self._worker = None
            self._inq = self._outq = None
            raise StepDeadlineExceeded(
                f"{phase} at step {step} did not materialize within "
                f"{deadline_s:.3g}s — a hung collective (dead or hung "
                f"peer?)", step=step, phase=phase,
                deadline_s=deadline_s) from None
        if kind == "err":
            raise payload
        return payload

    def close(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            self._inq.put(None)
        self._worker = None
        self._inq = self._outq = None

    def __enter__(self) -> "DeadlineRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
