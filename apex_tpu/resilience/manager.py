"""Failure recovery: periodic checkpoints + resume-from-newest-valid.

SURVEY.md §5: the reference has NO failure detection or elastic story (a
crashed rank kills the job); the prescribed TPU recovery model is
"multi-host restart + checkpoint-resume".  This module is that story as
a first-class helper:

- ``CheckpointManager`` keeps a rotating window of packed checkpoints
  (``step-<N>.ckpt``), written asynchronously (AsyncCheckpointer) so the
  step loop never blocks, fsync'd before publish (checkpoint.py), each
  self-validating via header + crc + float-norm checksums.
- ``restore_latest`` walks checkpoints newest-first and resumes from the
  first VALID one — a file truncated by the crash that killed the job is
  detected (ValueError from load) and skipped, which is exactly the
  failure mode a mid-write crash produces.

Multi-host: only process_index 0 writes by default; ``all_hosts=True``
gives every host its own ``step-<N>.p<idx>.ckpt`` file (for per-host
extra state).  ``restore_latest`` is a COLLECTIVE on multi-host runs
(every process must call it): hosts allgather their on-disk step sets
and walk the intersection newest-first in lockstep, agreeing per step
on whether every host loaded it successfully — so either ALL hosts
resume from the SAME step, or ALL hosts return None and start fresh.
A host can never silently diverge from host 0's resume step
(VERDICT r3 #5):

- shared filesystem, ``all_hosts=False``: all hosts see host 0's
  files; everyone resumes from the newest step valid on every host
  (a file corrupt for one host is corrupt bytes for all, so it is
  skipped everywhere consistently).
- per-host disks, ``all_hosts=True``: a crash that interrupted some
  hosts' publish leaves the step sets unequal; the intersection drops
  the partially-published step and everyone resumes from the newest
  step ALL hosts hold.
- per-host disks, ``all_hosts=False``: non-writers have no files, the
  intersection is empty, and every host — including host 0, with a
  loud warning — starts fresh together instead of host 0 resuming
  from step N while the others restart from 0.
"""

from __future__ import annotations

import json
import os
import re
import warnings
from typing import Any, Optional, Set, Tuple

import jax

from apex_tpu import checkpoint as _ckpt
from apex_tpu.checkpoint import TemplateMismatchError
from apex_tpu.telemetry import hostmetrics as _hostmetrics
from apex_tpu.telemetry.spans import span

Pytree = Any


def _rollback_snapshot(optimizer):
    """Capture the optimizer as it came in, so a restore walk that a
    peer rejects (or that ends fresh-start after a local success) can
    undo its mutation.  Bucket granularity when packed — one device
    copy per flat buffer; the packed fast path's safety net must not
    pay the per-leaf unpack the format exists to avoid."""
    if optimizer is None:
        return None
    if getattr(optimizer, "_plan", None) is not None:
        return ("packed", optimizer.packed_snapshot())
    return ("per_leaf", dict(optimizer.state_dict()),
            getattr(optimizer, "params", None))


def _rollback(optimizer, snap) -> None:
    if snap[0] == "packed":
        s = snap[1]
        optimizer.load_packed_snapshot(s["step"], s["hypers"],
                                       s["param_bufs"],
                                       s["master_bufs"], s["state"])
    else:
        optimizer.load_state_dict(snap[1])
        optimizer.params = snap[2]


class CheckpointManager:
    """Rotating async training checkpoints with crash-safe resume.

    >>> mgr = CheckpointManager(dir, keep=3, every=100)
    >>> for step in range(start, total):
    ...     ...train...
    ...     mgr.maybe_save(step, optimizer=opt, amp_state=amp_sd)
    >>> mgr.close()

    ``format="auto"`` (default) writes the bucket-native v2 format
    whenever the optimizer runs bucketed (one device copy + one d2h
    per bucket, zero per-leaf unpack — checkpoint.py docstring);
    ``"v1"`` forces the per-leaf format for interop with old readers.
    """

    def __init__(self, directory: str, keep: int = 3, every: int = 100,
                 all_hosts: bool = False, format: str = "auto"):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if format not in ("auto", "v1", "v2"):
            raise ValueError(f"unknown checkpoint format {format!r}")
        self.directory = directory
        self.keep = keep
        self.every = every
        self.format = format
        self._writer = (jax.process_index() == 0) or all_hosts
        # per-host file names under all_hosts: hosts on a SHARED
        # filesystem must never race on one path
        self._suffix = (f".p{jax.process_index()}.ckpt" if all_hosts
                        else ".ckpt")
        self._step_re = re.compile(
            r"^step-(\d+)" + re.escape(self._suffix) + "$")
        # watchdog integration: steps pinned against rotation while
        # they age toward last-known-good, plus the LKG step itself
        self._pins: Set[int] = set()
        self._lkg: Optional[int] = self._read_lkg()
        self._async = _ckpt.AsyncCheckpointer()
        if self._writer:
            os.makedirs(directory, exist_ok=True)
            # a crash mid-write leaves step-N.ckpt.tmp behind forever
            # (_gc only matches published names); any .tmp predating
            # this process is by definition garbage — clear it now.
            # Strictly scoped to THIS host's exact tmp name shape: on a
            # shared filesystem another host's .tmp may be a live
            # in-flight write (".ckpt.tmp" is a suffix of ".pK.ckpt.tmp",
            # so a loose glob would cross-delete).  Contract: the
            # previous writer with this suffix is DEAD before this one
            # constructs (the normal restart sequence); a still-alive
            # superseded writer racing its replacement is unsafe with or
            # without this GC (both would publish the same step files)
            tmp_re = re.compile(
                r"^step-\d+" + re.escape(self._suffix) + r"\.tmp$")
            for name in os.listdir(directory):
                if tmp_re.match(name):
                    try:
                        os.remove(os.path.join(directory, name))
                    except OSError:
                        pass

    def gc_dead_host_tmp(self, dead_hosts, survivors,
                         rank: Optional[int] = None) -> int:
        """Clear ``.tmp`` orphans belonging to hosts the fleet
        agreement declared DEAD (never a live host's — on a shared
        filesystem a live peer's ``.tmp`` may be an in-flight write).

        The construction-time GC is strictly scoped to THIS host's
        suffix, so a dead PEER's torn ``.tmp`` files would otherwise
        accumulate forever in a shared checkpoint dir.  Exactly one
        survivor does the sweep — the agreed lowest-rank one (every
        survivor holds the same agreed sets, so the election needs no
        extra round); everyone else no-ops.  Returns the number of
        files removed.

        Covers both naming modes: ``all_hosts=True`` peers write
        ``step-N.p<idx>.ckpt.tmp``; with a single writer
        (``all_hosts=False``) only host 0's plain
        ``step-N.ckpt.tmp`` shape exists — swept only when host 0
        itself is among the dead."""
        dead = sorted(set(int(h) for h in dead_hosts))
        alive = sorted(set(int(h) for h in survivors))
        if not dead or not alive:
            return 0
        if rank is None:
            rank = jax.process_index()
        if int(rank) != alive[0]:
            return 0
        patterns = [re.compile(rf"^step-\d+\.p{h}\.ckpt\.tmp$")
                    for h in dead]
        if 0 in dead:
            patterns.append(re.compile(r"^step-\d+\.ckpt\.tmp$"))
        removed = 0
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for name in names:
            if any(p.match(name) for p in patterns):
                try:
                    os.remove(os.path.join(self.directory, name))
                    removed += 1
                except OSError:
                    pass
        return removed

    # how many of the newest local steps each host contributes to the
    # multi-host agreement.  MUST be the same on every host (allgather
    # needs equal shapes even when hosts configure different `keep`),
    # so it is a class constant, never derived from instance config; a
    # keep window larger than this only loses steps older than the
    # newest 16 from the agreement, which resume never wants anyway
    _SYNC_CAP = 16

    def _allgather(self, arr):
        """Hook for tests; multi-host runs use process_allgather."""
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(arr)

    def _process_count(self) -> int:
        return jax.process_count()

    def _agreed_steps(self):
        """Steps every host holds on disk (descending).  Collective on
        multi-host runs; the local list on single-host runs."""
        import numpy as np
        local = self.steps_on_disk()
        if self._process_count() == 1:
            return list(reversed(local))
        cap = self._SYNC_CAP
        vec = np.full((cap,), -1, np.int64)
        tail = local[-cap:]
        vec[:len(tail)] = tail
        allv = np.asarray(self._allgather(vec))       # [nprocs, cap]
        common = set(int(s) for s in allv[0] if s >= 0)
        for row in allv[1:]:
            common &= set(int(s) for s in row if s >= 0)
        # warn from the allgathered view, not the local disk: the host
        # holding the stranded checkpoints may not be host 0 at all
        any_local = bool((allv >= 0).any())
        if any_local and not common and (local
                                         or jax.process_index() == 0):
            warnings.warn(
                "restore_latest: some host has checkpoints but the "
                "cluster shares none (per-host disks with "
                "all_hosts=False?); ALL hosts are starting fresh "
                "together to stay in step. Use a shared filesystem or "
                "all_hosts=True to make multi-host resume possible.")
        return sorted(common, reverse=True)

    # per-step load outcomes for the lockstep agreement
    _LOAD_FAIL, _LOAD_OK, _LOAD_FATAL = 0, 1, 2

    def _agree_status(self, code: int) -> int:
        """Combine per-host load outcomes; collective.  Returns _LOAD_OK
        iff EVERY host loaded, _LOAD_FATAL if ANY host hit a template
        mismatch (a caller bug that must abort the whole cluster, in
        lockstep — a lone raiser would strand its peers inside the next
        allgather), else _LOAD_FAIL."""
        import numpy as np
        if self._process_count() == 1:
            return code
        flags = np.asarray(
            self._allgather(np.asarray([code], np.int64)))
        if (flags == self._LOAD_FATAL).any():
            return self._LOAD_FATAL
        return self._LOAD_OK if (flags == self._LOAD_OK).all() \
            else self._LOAD_FAIL

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step-{step}{self._suffix}")

    def steps_on_disk(self):
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        out = []
        for n in names:
            m = self._step_re.match(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # ---- last-known-good tagging (watchdog integration) ------------------
    # The watchdog stamps a checkpoint "good" only after a FULL clean
    # telemetry window has aged past it with no anomaly.  Rotation must
    # never delete the LKG (it is the rollback target) nor a still-aging
    # candidate (it may BECOME the LKG); the marker survives restarts so
    # a rollback after a crash still lands on a proven-clean state.

    def _lkg_path(self) -> str:
        return os.path.join(self.directory, f"lkg{self._suffix}.json")

    def _read_lkg(self) -> Optional[int]:
        try:
            with open(self._lkg_path(), encoding="utf-8") as f:
                return int(json.load(f)["step"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def pin(self, step: int) -> None:
        """Exempt ``step`` from rotation while it ages toward
        last-known-good (unpin when the verdict lands)."""
        self._pins.add(int(step))

    def unpin(self, step: int) -> None:
        self._pins.discard(int(step))

    def mark_good(self, step: int) -> None:
        """Stamp ``step`` as the last-known-good checkpoint: rotation
        keeps it (beyond ``keep``) until a newer step is stamped, and
        ``restore_good`` rolls back to it.  Persisted next to the
        checkpoints so a restarted job inherits the stamp."""
        step = int(step)
        self._lkg = step
        self._pins.discard(step)       # the LKG pin supersedes
        if self._writer:
            tmp = self._lkg_path() + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"step": step}, f)
                os.replace(tmp, self._lkg_path())
            except OSError:
                # the stamp is an optimization (rollback falls back to
                # newest-valid); a transient marker-write failure must
                # not kill training
                warnings.warn(f"mark_good: could not persist LKG marker "
                              f"for step {step}")
        # a superseded LKG loses its exemption at the next _gc

    def lkg_step(self) -> Optional[int]:
        """The last-known-good step (None before the first stamp)."""
        return self._lkg

    def restore_good(self, params_like: Pytree, optimizer=None,
                     extra_like: Optional[Pytree] = None,
                     sharding=None) -> Optional[Tuple]:
        """Rollback restore: resume from the newest valid checkpoint
        NO NEWER than the last-known-good step — checkpoints taken
        after the LKG may hold the very state the watchdog flagged.
        Without a stamp yet this degrades to ``restore_latest`` (and
        the watchdog's bounded rollback budget still ends a recovery
        loop that keeps restoring poisoned state).  Collective on
        multi-host runs exactly like ``restore_latest``."""
        return self.restore_latest(params_like, optimizer,
                                   extra_like=extra_like,
                                   sharding=sharding,
                                   max_step=self._lkg)

    def due(self, step: int) -> bool:
        """True iff ``step`` is on the save cadence — THE predicate
        ``maybe_save`` applies.  Exposed so step loops can gate
        expensive checkpoint-argument capture on it (``state_dict()``
        callbacks device_get; evaluating them on the 99% of steps
        whose result ``maybe_save`` discards is a per-step host
        sync)."""
        return step % self.every == 0

    def maybe_save(self, step: int, params: Pytree = None, optimizer=None,
                   amp_state=None, extra: Optional[Pytree] = None,
                   force: bool = False) -> bool:
        """Save iff ``step`` is on the cadence (or ``force``); returns
        True if a save was scheduled.  Non-writer hosts no-op (all
        hosts return the same value, so loops stay in step).

        ``params`` may be None with a bucketed optimizer — the v2 path
        snapshots the packed buffers directly and never touches the
        lazily-unpacked ``optimizer.params`` view."""
        if not self.due(step) and not force:
            return False
        if self._writer:
            # save_training_state first JOINS the previous async save
            # (raising if it failed), so everything on disk below is
            # known-durable; the checkpoint scheduled here is NOT, and
            # _gc therefore keeps `keep` durable files besides it — a
            # failed in-flight write can never leave zero checkpoints.
            # The span times the SCHEDULING cost paid by the step loop
            # (join + snapshot + handoff), not the async write itself.
            with span("checkpoint/save"):
                self._async.save_training_state(
                    self._path(step), params, optimizer=optimizer,
                    amp_state=amp_state, step=step, extra=extra,
                    format=self.format)
                self._gc(in_flight=step)
        return True

    def save(self, step: int, params: Pytree = None, optimizer=None,
             amp_state=None, extra: Optional[Pytree] = None) -> bool:
        """Save NOW regardless of cadence — the preemption-notice path
        (PreemptionGuard/run_elastic call this for the final
        save-before-exit) and the supervisor's retry-after-failure
        path."""
        return self.maybe_save(step, params, optimizer=optimizer,
                               amp_state=amp_state, extra=extra,
                               force=True)

    def _gc(self, in_flight: Optional[int] = None) -> None:
        """Trim to the newest ``keep`` checkpoints, never counting (or
        deleting) the not-yet-durable in-flight one — so a failed
        in-flight write can never reduce the durable window.  The LKG
        step and watchdog-pinned (still-aging) steps are exempt and do
        not count toward ``keep``: retention pinning means rotation can
        never delete the rollback target out from under a recovery."""
        exempt = set(self._pins)
        if self._lkg is not None:
            exempt.add(self._lkg)
        steps = [s for s in self.steps_on_disk()
                 if s != in_flight and s not in exempt]
        for s in steps[:max(0, len(steps) - self.keep)]:
            try:
                os.remove(self._path(s))
            except OSError:
                pass

    def restore_latest(self, params_like: Pytree, optimizer=None,
                       extra_like: Optional[Pytree] = None,
                       sharding=None,
                       max_step: Optional[int] = None) -> Optional[Tuple]:
        """Resume from the newest VALID checkpoint, or None if none.

        Corrupt/truncated files (the artifact of dying mid-write) are
        skipped with the next-newest tried — the crash-recovery
        contract.  A TEMPLATE mismatch (intact checkpoint, wrong
        tree/shape/dtype) is a caller bug and re-raises instead of
        silently restarting from scratch.  Returns
        load_training_state's tuple.

        ``max_step`` bounds the walk: only checkpoints at or below it
        are considered (the watchdog's rollback-to-LKG path —
        checkpoints newer than the last-known-good may hold the bad
        state being rolled away from).  The bound must be the SAME on
        every host: it filters the agreed step set before the lockstep
        walk, so agreement semantics are unchanged.

        COLLECTIVE on multi-host runs (every process must call it, in
        the same program order): the candidate steps are the
        intersection of all hosts' on-disk sets, and a step counts as
        restored only when EVERY host loaded it — so the whole cluster
        resumes from one agreed step, or none at all (module
        docstring).
        """
        # a load that succeeds locally but is rejected by a peer has
        # already mutated the optimizer; snapshot so a walk that ends
        # fresh-start leaves the optimizer as it came in
        snap = _rollback_snapshot(optimizer)
        dirty = False
        with span("checkpoint/restore"):
            out = self._restore_walk(params_like, optimizer, extra_like,
                                     snap, dirty, sharding, max_step)
        if out is not None:
            _hostmetrics.emit("ckpt/restore_step", out[2])
        return out

    def _restore_walk(self, params_like, optimizer, extra_like, snap,
                      dirty, sharding=None, max_step=None):
        steps = self._agreed_steps()
        if max_step is not None:
            steps = [s for s in steps if s <= max_step]
        for step in steps:
            out, code, tmpl_err = None, self._LOAD_OK, None
            try:
                out = _ckpt.load_training_state(
                    self._path(step), params_like, optimizer=optimizer,
                    extra_like=extra_like, sharding=sharding)
            except TemplateMismatchError as e:
                # caller bug (intact file, wrong tree) — but raising
                # HERE on one host would strand its peers in the next
                # collective; agree on the abort first, raise after
                code, tmpl_err = self._LOAD_FATAL, e
            except (ValueError, OSError) as e:
                # corrupt or vanished: try the previous one — but LOUDLY,
                # so a transient I/O failure that walks past every good
                # checkpoint (and thereby restarts training from scratch)
                # is observable in the logs
                warnings.warn(
                    f"restore_latest: skipping {self._path(step)}: "
                    f"{type(e).__name__}: {e}")
                code = self._LOAD_FAIL
            agreed = self._agree_status(code)
            if agreed == self._LOAD_FATAL:
                if code == self._LOAD_OK and snap is not None:
                    # this host's load succeeded and mutated the
                    # optimizer; a caller catching the abort to fall
                    # back to fresh training must not inherit a
                    # half-restored optimizer while its peers are
                    # pristine
                    _rollback(optimizer, snap)
                if tmpl_err is not None:
                    raise tmpl_err
                raise TemplateMismatchError(
                    f"restore_latest: step {step} hit a template "
                    "mismatch on another host; aborting the cluster "
                    "restore in lockstep")
            if agreed == self._LOAD_OK:
                return out
            if code == self._LOAD_OK:
                # a PEER failed on this step: discard the local load
                # (the next accepted load overwrites the mutation) and
                # stay in lockstep
                dirty = True
                warnings.warn(
                    f"restore_latest: step {step} loaded here but "
                    "failed on another host; falling back together")
        if dirty and snap is not None:
            _rollback(optimizer, snap)
        return None

    def wait(self) -> None:
        """Block until the in-flight save is durable (call before an
        intentional shutdown); then trim the window to ``keep``."""
        self._async.wait_until_finished()
        if self._writer:
            self._gc()

    def close(self) -> None:
        self._async.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
