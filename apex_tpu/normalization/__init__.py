"""apex_tpu.normalization (reference: apex/normalization)."""

from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    fused_layer_norm,
    fused_rms_norm,
    layer_norm_ref,
    rms_norm_ref,
)

__all__ = [
    "FusedLayerNorm", "FusedRMSNorm",
    "MixedFusedLayerNorm", "MixedFusedRMSNorm",
    "fused_layer_norm", "fused_rms_norm",
    "layer_norm_ref", "rms_norm_ref",
]
