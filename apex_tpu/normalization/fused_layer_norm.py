"""Module-level normalization API (reference:
apex/normalization/fused_layer_norm.py).

``FusedLayerNorm`` / ``FusedRMSNorm`` are flax.linen modules with the
reference's constructor surface (normalized_shape, eps,
elementwise_affine, memory_efficient).  The "Mixed" variants keep params
in f32 while the input may be bf16 — on TPU this is simply param_dtype
pinned to f32 (the kernels accumulate in f32 regardless), matching
MixedFusedLayerNorm/MixedFusedRMSNorm semantics.

Functional forms (fused_layer_norm / fused_rms_norm) live in
apex_tpu.ops.layer_norm and are re-exported here.
"""

from __future__ import annotations

import math
from typing import Iterable, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import (fused_layer_norm, fused_rms_norm,
                                     layer_norm_ref, rms_norm_ref)

Shape = Union[int, Iterable[int]]


def _normalize_shape(s: Shape) -> Tuple[int, ...]:
    if isinstance(s, int):
        return (s,)
    return tuple(s)


def _sp_param_sync(w, b):
    """Replicated norm params consumed by SEQUENCE-SHARDED activations:
    each tp rank's weight grad is a sum over its local sequence shard
    only, so the true grad needs a psum over the model axis.  Megatron
    marks these params `sequence_parallel` and allreduces their grads
    before the step; the by-construction equivalent is the f/g copy
    mapping (fwd identity, bwd psum) applied to the params at use."""
    from apex_tpu import comm
    from apex_tpu.transformer.tensor_parallel import mappings
    if not (comm.axis_is_bound(mappings.AXIS)):
        return w, b
    cp = mappings.copy_to_tensor_model_parallel_region
    return (cp(w) if w is not None else None,
            cp(b) if b is not None else None)


class FusedLayerNorm(nn.Module):
    normalized_shape: Shape = None
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32
    # True when the input is sequence-sharded over the model axis
    # (Megatron LayerNorm's `sequence_parallel` attribute): syncs the
    # affine-param grads across tp ranks
    sequence_parallel: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _normalize_shape(self.normalized_shape)
        h = math.prod(shape)
        lead = x.shape[:x.ndim - len(shape)]
        x2 = x.reshape(lead + (h,))
        if self.elementwise_affine:
            w = self.param("weight", nn.initializers.ones, (h,),
                           self.param_dtype)
            b = self.param("bias", nn.initializers.zeros, (h,),
                           self.param_dtype)
        else:
            w = b = None
        if self.sequence_parallel:
            w, b = _sp_param_sync(w, b)
        y = fused_layer_norm(x2, w, b, self.eps, self.memory_efficient)
        return y.reshape(x.shape)


class FusedRMSNorm(nn.Module):
    normalized_shape: Shape = None
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32
    sequence_parallel: bool = False      # see FusedLayerNorm

    @nn.compact
    def __call__(self, x):
        shape = _normalize_shape(self.normalized_shape)
        h = math.prod(shape)
        lead = x.shape[:x.ndim - len(shape)]
        x2 = x.reshape(lead + (h,))
        w = (self.param("weight", nn.initializers.ones, (h,),
                        self.param_dtype)
             if self.elementwise_affine else None)
        if self.sequence_parallel:
            w, _ = _sp_param_sync(w, None)
        y = fused_rms_norm(x2, w, self.eps, self.memory_efficient)
        return y.reshape(x.shape)


class MixedFusedLayerNorm(FusedLayerNorm):
    """bf16-input / f32-param LayerNorm (reference MixedFusedLayerNorm)."""
    param_dtype: jnp.dtype = jnp.float32


class MixedFusedRMSNorm(FusedRMSNorm):
    """bf16-input / f32-param RMSNorm (reference MixedFusedRMSNorm)."""
    param_dtype: jnp.dtype = jnp.float32


__all__ = [
    "FusedLayerNorm", "FusedRMSNorm",
    "MixedFusedLayerNorm", "MixedFusedRMSNorm",
    "fused_layer_norm", "fused_rms_norm",
    "layer_norm_ref", "rms_norm_ref",
]
