from apex_tpu.contrib.index_mul_2d.index_mul_2d import index_mul_2d  # noqa: F401

__all__ = ["index_mul_2d"]
