"""contrib.index_mul_2d parity (reference: apex/contrib/index_mul_2d/
over index_mul_2d_cuda — fused gather+multiply for 2D tensors,
SURVEY.md §2.3; used by openfold-style models).

out[i] = in1[idx[i]] * in2[i].  One XLA gather + one fused multiply;
the backward (scatter-add into in1, gather-mul into in2) is the autodiff
transpose, which XLA lowers to the same scatter the CUDA bwd hand-codes.
"""

from __future__ import annotations

import jax.numpy as jnp


def index_mul_2d(in1, in2, idx):
    """in1 (N1, F), in2 (N2, F), idx (N2,) int -> (N2, F)."""
    return jnp.take(in1, idx, axis=0) * in2
