"""Stub: reference apex/contrib/openfold_triton/ (Triton GPU kernels for
OpenFold: fused MHA/layernorm variants authored in Triton — SURVEY.md
§2.3 misc [later-era]).  Triton targets CUDA; the TPU-native equivalents
of every kernel it provides already exist in this package: the flash
attention family (apex_tpu.ops.attention) and the Pallas LayerNorm
(apex_tpu.ops.layer_norm).  See PARITY.md."""

from apex_tpu.contrib._unavailable import make

_REASON = "is authored in Triton (a CUDA kernel language)"
AttnTri = make("openfold_triton.AttnTri",
               "apex_tpu.ops.attention.flash_attention", reason=_REASON)
LayerNormSmallShapeOptImpl = make(
    "openfold_triton.LayerNormSmallShapeOptImpl",
    "apex_tpu.ops.layer_norm.fused_layer_norm", reason=_REASON)
