"""Stub: reference apex/contrib/nccl_p2p/ (raw NCCL point-to-point side
channels).  TPU replacement: `jax.lax.ppermute` under shard_map (see
apex_tpu.transformer.pipeline_parallel.p2p_communication).  See
PARITY.md."""

from apex_tpu.contrib._unavailable import make

nccl_p2p = make(
    "nccl_p2p", "apex_tpu.transformer.pipeline_parallel.p2p_communication")
