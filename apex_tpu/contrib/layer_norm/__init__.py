"""contrib.layer_norm parity (reference: apex/contrib/layer_norm/
layer_norm.py — `FastLayerNorm` over the fast_layer_norm extension,
hidden sizes <= ~8k, SURVEY.md §2.3).

The reference maintains two separate LN kernel stacks (core
fused_layer_norm_cuda and contrib fast_layer_norm); the TPU rebuild has
one Pallas LN (apex_tpu.ops.layer_norm) serving both, so FastLayerNorm
IS FusedLayerNorm under the contrib name (SURVEY.md §2.4 maps them to
the same kernel).
"""

from apex_tpu.normalization import FusedLayerNorm as FastLayerNorm  # noqa: F401

__all__ = ["FastLayerNorm"]
