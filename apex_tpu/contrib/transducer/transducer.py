"""apex.contrib.transducer parity (reference:
apex/contrib/transducer/transducer.py — `TransducerJoint`,
`TransducerLoss` module facades over the CUDA kernels, SURVEY.md §2.3).

Packed-layout options (`pack_output`, `packed_input`) are accepted and
mapped to the masked equivalents: XLA requires static shapes, so ragged
batches are handled by masking padded cells instead of physically
packing them (documented deviation — same numerics, see PARITY.md).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from apex_tpu.ops.transducer import transducer_joint, transducer_loss


class TransducerJoint:
    """h[b,t,u] = f[b,t] + g[b,u], optional ReLU+dropout fusion.

    Reference ctor flags kept: pack_output (→ masking), relu, dropout.
    """

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0, opt: int = 1,
                 fwd_tile_size: int = 4, dropout_prob: float = 0.0,
                 probe_mask: bool = False):
        del opt, fwd_tile_size, probe_mask     # kernel-tuning knobs: N/A
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout or dropout_prob

    def __call__(self, f, g, f_len=None, g_len=None, *,
                 dropout_rng=None, batch_offset=None, packed_batch=0):
        del batch_offset, packed_batch          # packing bookkeeping: N/A
        if self.pack_output and (f_len is None or g_len is None):
            raise ValueError("pack_output requires f_len AND g_len")
        # reference semantics: the unpacked joint leaves padding as-is;
        # pack_output's physical packing becomes masking (PARITY.md)
        mask_f, mask_g = (f_len, g_len) if self.pack_output else (None,
                                                                  None)
        return transducer_joint(
            f, g, mask_f, mask_g, relu=self.relu,
            dropout_rate=self.dropout, dropout_rng=dropout_rng)


class TransducerLoss:
    """RNN-T negative log-likelihood; differentiable via jax.grad (the
    reference's fuse_softmax_backward is the autodiff transpose here)."""

    def __init__(self, fuse_softmax_backward: bool = True,
                 opt: int = 1, packed_input: bool = False):
        del fuse_softmax_backward, opt
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0,
                 batch_offset: Optional[jnp.ndarray] = None,
                 max_f_len: Optional[int] = None,
                 debug_list=None):
        del batch_offset, max_f_len, debug_list
        if self.packed_input:
            raise NotImplementedError(
                "packed_input has no static-shape analog; pass padded "
                "(B, T, U, V) logits with f_len/y_len masks")
        return transducer_loss(x, label, f_len, y_len, blank_idx)
