from apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
)

__all__ = ["TransducerJoint", "TransducerLoss"]
