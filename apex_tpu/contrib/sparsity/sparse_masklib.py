"""2:4 structured-sparsity mask search (reference:
apex/contrib/sparsity/sparse_masklib.py — `create_mask` with m4n2
patterns, SURVEY.md §2.3).

A mask keeps the n largest-magnitude elements of every group of m along
the chosen dim.  Rank-based selection (double argsort) keeps exactly n
per group even with ties, matching the reference's behavior of picking a
deterministic winner.
"""

from __future__ import annotations

import jax.numpy as jnp

_PATTERNS = {
    "m4n2_1d": (4, 2),
    "m8n2_1d": (8, 2),
    "m4n1_1d": (4, 1),
}


def mn_1d_mask(w, m: int, n: int):
    """Boolean mask keeping the n largest |w| in every m-group along the
    LAST axis (the reference's 1d patterns group along the input dim)."""
    shape = w.shape
    if shape[-1] % m != 0:
        raise ValueError(f"last dim {shape[-1]} not divisible by m={m}")
    g = w.reshape(shape[:-1] + (shape[-1] // m, m))
    aw = jnp.abs(g.astype(jnp.float32))
    order = jnp.argsort(-aw, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    return (ranks < n).reshape(shape)


def create_mask(tensor, pattern: str = "m4n2_1d"):
    """Reference-shaped entry: create_mask(weight, "m4n2_1d") -> mask of
    tensor's dtype with exactly n/m density per group."""
    if pattern not in _PATTERNS:
        raise ValueError(
            f"unknown pattern {pattern!r}; available: {sorted(_PATTERNS)}")
    m, n = _PATTERNS[pattern]
    return mn_1d_mask(tensor, m, n).astype(tensor.dtype)
