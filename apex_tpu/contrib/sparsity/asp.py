"""ASP — automatic sparsity (reference: apex/contrib/sparsity/asp.py,
SURVEY.md §2.3: mask search over whitelisted layers, mask application to
weights AND optimizer state, recompute option).

The reference hooks torch modules/optimizer in place.  Functionally:
ASP owns a mask pytree; `compute_sparse_masks` searches masks for every
eligible leaf; masked params/grads/moments are produced by tree
multiplication.  `init_optimizer_for_pruning` wraps an apex_tpu fused
optimizer so every step re-applies the masks (the reference patches
optimizer.step the same way).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask

Pytree = Any


def _default_whitelist(path, leaf) -> bool:
    """Reference default: prune Linear/Conv weights, skip
    biases/norms/embeddings too small to matter: here = floating leaves
    with ndim >= 2 and last dim divisible by 4."""
    return (jnp.issubdtype(leaf.dtype, jnp.floating)
            and leaf.ndim >= 2 and leaf.shape[-1] % 4 == 0)


class ASP:
    """Class-level state mirrors the reference's module-global ASP."""

    _masks: Optional[Pytree] = None
    _pattern: str = "m4n2_1d"
    _whitelist: Callable = staticmethod(_default_whitelist)

    @classmethod
    def init_model_for_pruning(cls, params: Pytree,
                               mask_calculator: str = "m4n2_1d",
                               whitelist: Optional[Callable] = None,
                               verbosity: int = 2,
                               allow_recompute_mask: bool = False,
                               custom_layer_dict=None):
        del verbosity, allow_recompute_mask, custom_layer_dict
        cls._pattern = mask_calculator
        if whitelist is not None:
            cls._whitelist = staticmethod(whitelist)
        cls._masks = None
        return params

    @classmethod
    def compute_sparse_masks(cls, params: Pytree) -> Pytree:
        """Search masks and return the masked params (reference mutates)."""
        def leaf_mask(path, leaf):
            if cls._whitelist(path, leaf):
                return create_mask(leaf, cls._pattern)
            return jnp.ones_like(leaf)
        cls._masks = jax.tree_util.tree_map_with_path(leaf_mask, params)
        return cls.apply_masks(params)

    @classmethod
    def apply_masks(cls, tree: Pytree) -> Pytree:
        if cls._masks is None:
            raise RuntimeError("call compute_sparse_masks first")
        return jax.tree_util.tree_map(
            lambda x, m: x * m.astype(x.dtype), tree, cls._masks)

    @classmethod
    def masks(cls) -> Optional[Pytree]:
        return cls._masks

    @classmethod
    def is_sparsity_enabled(cls) -> bool:
        return cls._masks is not None

    @classmethod
    def restore_pruned_weights(cls, params: Pytree) -> Pytree:
        """Disable sparsity (reference zero-restores are impossible —
        pruned values are gone — it just stops masking; same here)."""
        cls._masks = None
        return params

    @classmethod
    def init_optimizer_for_pruning(cls, optimizer):
        """Patch optimizer.step to re-mask params (and keep moments
        masked) after every update — the reference wraps step the same
        way."""
        orig_step = optimizer.step

        def sparse_step(grads, *a, **kw):
            if cls._masks is not None:
                grads = cls.apply_masks(grads)
            params = orig_step(grads, *a, **kw)
            if cls._masks is not None:
                params = cls.apply_masks(params)
                optimizer.params = params
                if getattr(optimizer, "masters", None) is not None:
                    optimizer.masters = cls.apply_masks(optimizer.masters)
            return params

        optimizer.step = sparse_step
        return optimizer

    @classmethod
    def prune_trained_model(cls, params: Pytree, optimizer=None):
        """Reference one-call recipe: init + mask search + optimizer
        hookup.  Returns masked params."""
        cls.init_model_for_pruning(params)
        masked = cls.compute_sparse_masks(params)
        if optimizer is not None:
            cls.init_optimizer_for_pruning(optimizer)
            optimizer.params = masked
            if getattr(optimizer, "masters", None) is not None:
                optimizer.masters = cls.apply_masks(optimizer.masters)
        return masked
