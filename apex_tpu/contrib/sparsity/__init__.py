from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.sparse_masklib import create_mask  # noqa: F401
from apex_tpu.contrib.sparsity.permutation_search import (  # noqa: F401
    accelerated_search_for_good_permutation,
    apply_permutation,
    invert_permutation,
    magnitude_init_permutation,
    search_for_good_permutation,
    sum_after_2_to_4,
)

__all__ = ["ASP", "create_mask",
           "accelerated_search_for_good_permutation",
           "apply_permutation", "invert_permutation",
           "magnitude_init_permutation",
           "search_for_good_permutation", "sum_after_2_to_4"]
