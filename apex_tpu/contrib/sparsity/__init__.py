from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.sparse_masklib import create_mask  # noqa: F401

__all__ = ["ASP", "create_mask"]
